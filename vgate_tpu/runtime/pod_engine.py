"""Gateway-side pod of process-isolated engine workers.

``pod.workers > 0`` replaces the in-process engine stack behind the
backend seam with this router: N worker *processes* (each running the
full EngineCore/EngineSupervisor stack — runtime/worker.py), reached
over the length-prefixed frame protocol (runtime/rpc.py) on unix-domain
or localhost-TCP sockets.  PodEngine presents the SAME surface
ReplicatedEngine does — submit/stream/abort, health/stats/pressure,
/admin/replicas drain — so the batcher, admission, metrics and the
server never learn which mode they are in; ``pod.workers = 0`` keeps
the in-process path byte-identical.

Robustness contracts (the point of the process boundary):

* **Heartbeat liveness** — a monitor thread pings every worker at
  ``pod.heartbeat_interval_s``; the worker's engine beat rides back on
  each ping and is judged with the PR-5 classifier
  (``recovery.step_stall_s`` / ``compile_grace_s``), so a first-compile
  pause never reads as death.  No successful ping for
  ``pod.heartbeat_timeout_s`` → the worker is declared lost.
* **Fencing epochs** — every incarnation of a worker slot gets a
  monotonically-increasing epoch; declaring a worker lost bumps the
  slot's epoch IMMEDIATELY, so every late frame from the zombie
  (token, done, reply) mis-stamps against the current epoch and is
  discarded and counted (``vgt_pod_fenced_frames``) instead of
  corrupting the replacement's token streams — the PR-5 stale-wake
  epoch guard, cross-process.
* **Zero-5xx worker loss** — the gateway holds every in-flight
  request's full state (prompt + generated so far), so a crash/kill -9
  /heartbeat loss folds each affected sequence (``prepare_resume``,
  the PR-1/5 checkpoint fold) and resubmits it to a survivor; RNG
  continuation is implicit (see SequenceCheckpoint), so greedy and
  seeded streams stay token-identical.  Only an exhausted resume
  budget or a fully-dead pod surfaces the typed retryable
  ``WorkerLostError``.
* **Supervised respawn + canary gate** — losses draw on the SAME
  sliding restart budget dp uses (``recovery.max_restarts`` /
  ``restart_window_s``, shared across slots: one sick pod, one
  budget), respawns back off exponentially, and a respawned worker
  must answer the PR-9 pinned-greedy canary with the pod's recorded
  fingerprint before it becomes routable.
* **Drain / migrate per worker** — /admin/replicas drain maps to the
  ``evacuate`` RPC verb; the returned sequences replay onto survivors
  exactly like dp's ``_redistribute`` (``prepare_migrate``: never
  spends the crash-resume budget).  A worker dying mid-drain falls
  back to the loss path — same fold, same replay, crash counters.
* **Disaggregated prefill/decode pools** (``pod.roles``) — workers can
  be pinned ``prefill`` / ``decode`` / ``mixed``.  New requests route
  to the prefill pool; when the prefill finishes, the worker folds the
  sequence and stages its KV through the PR-11 host pool, and the
  gateway runs an epoch-fenced, checksummed, chunked pull transfer to
  the least-loaded decode worker (runtime/handoff.py state machine:
  PREFILLING → STAGED → TRANSFERRING → ACCEPTED → DECODING).  Every
  failure mode degrades, never 5xxs: transfer garble/timeout retries
  then falls back to *monolithic* decode on the prefill worker
  (swap-in, zero recompute); prefill death mid-transfer re-prefills on
  a survivor via the normal loss path; decode death after ACCEPTED
  rides the existing checkpoint-fold failover.  Tokens stay identical
  either way.
"""

from __future__ import annotations

import base64
import binascii
import itertools
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import zlib
from collections import deque
from types import SimpleNamespace
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence as Seq,
    Tuple,
)

from vgate_tpu import faults, metrics, tracing
from vgate_tpu.config import VGTConfig, get_config
from vgate_tpu.errors import (
    HandoffStaleError,
    HandoffTransferError,
    MigrationRefusedError,
    ResumeExhaustedError,
    WorkerLostError,
    raise_for_state,
    state_is_alive,
    state_is_ready,
)
from vgate_tpu.logging_config import get_logger
from vgate_tpu.models.specs import spec_for_model_id
from vgate_tpu.observability import perf as perf_attr
from vgate_tpu.runtime import handoff as handoff_mod
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.supervisor import (
    HealthState,
    classify_heartbeat,
    restart_budget_remaining,
)
from vgate_tpu.runtime.tokenizer import get_tokenizer
from vgate_tpu.runtime.worker import params_to_wire, unwire_error
from vgate_tpu.runtime.worker_client import WorkerClient

logger = get_logger(__name__)

# Threading contract (scripts/vgt_lint.py, checker thread-discipline).
# ONE reentrant pod lock guards topology (worker handles, epochs) and
# the in-flight table together — loss handling moves sequences between
# both atomically.  RPC calls NEVER run under it (snapshot-then-call),
# so a wedged worker can stall an RPC thread but never the pod lock.
VGT_COMPONENTS: Dict[str, str] = {}
VGT_LOCK_GUARDS = {
    "_inflight": "_lock",
    "_orphans": "_lock",
    "_restart_times": "_lock",
    "_handoffs": "_lock",
    "_req_ledger": "_lock",
    "_flight_cache": "_lock",
    "_last_crash": "_lock",
    "_adopted_sids": "_lock",
    "adopted_request_ids": "_lock",
    "adopted_results": "_lock",
}

# spawn-time connect poll cadence (the worker binds its listener before
# building the engine, so the socket appears in milliseconds; the slow
# part — engine build — is budgeted by the hello call's timeout)
_CONNECT_POLL_S = 0.05

# an orphan's registry beat refreshes every second; a record older than
# this with a live pid means the process is wedged, not adoptable
_ADOPT_BEAT_FRESH_S = 10.0


def _pc_to_ns(pc: float) -> int:
    """Epoch nanoseconds for a (recent) perf_counter reading — the same
    anchoring reqtrace's _NsClock does, re-anchored per call so gateway
    handoff spans carry real wall timestamps without a long-lived
    clock object per transfer."""
    return time.time_ns() + int((pc - time.perf_counter()) * 1e9)


class _PodSequence(Sequence):
    """Gateway-side sequence whose abort propagates to the owning
    worker.  Inherits the dataclass-generated ``__init__``; the pod
    wiring rides on class-level defaults overwritten per instance."""

    _pod: Optional["PodEngine"] = None
    _sid: int = -1
    _worker_idx: int = -1
    # the gateway's captured OTel context (the HTTP span rides in it)
    # and its W3C encoding — stamped on every submit / handoff_commit
    # frame so worker engine spans parent onto the HTTP span
    _trace_ctx: Any = None
    _traceparent: Optional[str] = None

    def request_abort(self, reason: str = "client_disconnect") -> None:
        super().request_abort(reason)
        pod = self._pod
        if pod is not None:
            pod._abort_remote(self, reason)


class _Worker:
    """One worker slot's handle: process + connection + incarnation."""

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.epoch = 0  # bumps on every (re)spawn AND on declared loss
        self.proc: Optional[subprocess.Popen] = None
        self.client: Optional[WorkerClient] = None
        self.hello: Dict[str, Any] = {}
        # down | spawning | serving | dead (budget exhausted)
        self.state = "down"
        self.draining = False
        self.last_fatal: Optional[str] = None
        self.last_ping: Dict[str, Any] = {}
        self.last_ok_t = time.monotonic()
        self.respawning = False
        self.address: Any = None

    @property
    def alive(self) -> bool:
        return self.state == "serving"


def _pid_alive(pid: int) -> bool:
    """Signal-0 liveness probe.  EPERM means the pid exists but isn't
    ours to signal — still alive for adoption purposes."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


class _AdoptedProc:
    """Popen-shaped handle for a worker process this gateway did NOT
    spawn (an orphan adopted from a crashed predecessor's registry).

    The adopted worker is not our child, so ``waitpid`` semantics are
    unavailable; every Popen surface the pod machinery touches —
    ``pid``, ``poll()``, ``returncode``, ``terminate()``, ``kill()``,
    ``wait(timeout)`` — is re-implemented over signal-0 probes so the
    monitor, loss path, ``_kill_proc`` and ``stop()`` treat adopted and
    spawned incarnations identically."""

    __slots__ = ("pid", "returncode")

    def __init__(self, pid: int) -> None:
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is None and not _pid_alive(self.pid):
            # exit status belongs to whoever reaps it (init); -1 marks
            # "gone, status unknown" without pretending to know more
            self.returncode = -1
        return self.returncode

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"adopted pid {self.pid}", timeout or 0.0
                )
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]


class _SourceLost(Exception):
    """Internal marker: the prefill-side connection died mid-transfer.
    The pod loss path owns the sequence (fold + replay on a survivor);
    the transfer thread just stands down."""


class _HandoffRec:
    """Gateway-side record of one prefill→decode handoff transaction
    (state machine in runtime/handoff.py).  Guarded by the pod lock;
    the transfer thread snapshots under it and calls outside it.

    ``buffered``/``terminal`` absorb frames the decode target emits
    between its commit landing and the gateway flipping sequence
    ownership — they replay in order at accept so the client stream
    never drops or reorders a token."""

    __slots__ = (
        "sid", "seq", "prefill_idx", "prefill_epoch", "state",
        "cancelled", "target_idx", "buffered", "terminal", "pages",
        "nbytes", "base_len", "generated_ids", "resume_count",
        "migrate_count", "preempt_count", "swap_count", "kv_dtype",
        "attempts", "t0", "t_staged_pc", "t_transfer_pc",
    )

    def __init__(
        self, sid: int, seq: "_PodSequence", prefill_idx: int,
        prefill_epoch: int,
    ) -> None:
        self.sid = sid
        self.seq = seq
        self.prefill_idx = prefill_idx
        self.prefill_epoch = prefill_epoch
        self.state = handoff_mod.PREFILLING
        self.cancelled = False
        self.target_idx = -1
        self.buffered: List[Dict[str, Any]] = []
        self.terminal: Optional[Any] = None
        self.pages = 0
        self.nbytes = 0
        self.base_len = 0
        self.generated_ids: List[int] = []
        self.resume_count = 0
        self.migrate_count = 0
        self.preempt_count = 0
        self.swap_count = 0
        self.kv_dtype: Optional[str] = None
        self.attempts = 0
        self.t0 = time.monotonic()
        # state-dwell anchors (perf_counter, for span timestamps and
        # vgt_handoff_state_seconds attribution)
        self.t_staged_pc = 0.0
        self.t_transfer_pc = 0.0


class _PodFlight:
    """dp's ``_MergedFlight`` across PROCESS boundaries: fans the worker
    ``flight`` / ``requests`` verbs out to live workers and merges the
    rings by wall time, stamping every entry with its worker index and
    fencing epoch.  Each successful fetch refreshes a per-slot cache;
    when a slot's live view is unavailable (the incarnation crashed, was
    SIGKILLed, or was fenced out on heartbeat loss) the cached entries
    are still merged, marked ``fenced: true`` — the dead incarnation's
    last-known timeline is exactly what a post-mortem needs.  Request
    records additionally get the gateway's per-request handoff ledger
    grafted on (``transfer_s``, outcome, worker pair) so disaggregated
    TTFT decomposes into queue → prefill → transfer → decode.

    Gateway-side events (the batcher's overload tick) land in a local
    ring stamped ``worker: "gateway"`` — there is no RPC verb for
    writing ticks, and the event genuinely happened in this process."""

    def __init__(self, pod: "PodEngine") -> None:
        self._pod = pod
        self._gateway_ticks: "deque[Dict[str, Any]]" = deque(maxlen=512)
        self._tick_counter = itertools.count()

    @property
    def enabled(self) -> bool:
        return bool(self._pod.config.observability.enabled)

    def record_tick(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        entry: Dict[str, Any] = {
            "n": next(self._tick_counter),
            "t": time.time(),
            "kind": kind,
            "worker": "gateway",
        }
        entry.update(fields)
        self._gateway_ticks.append(entry)

    # ------------------------------------------------------------ fetch

    def _fetch(self) -> List[Dict[str, Any]]:
        """One fan-out round: per worker slot, the live reply (cache
        refreshed under the pod lock) or the cached snapshot of an
        unreachable/fenced incarnation."""
        pod = self._pod
        views: Dict[int, Dict[str, Any]] = {}
        for w in pod._alive_workers():
            client = w.client
            if client is None:
                continue
            try:
                flight = client.call("flight", n=1024)
                reqs = client.call("requests", n=1024)
            except Exception:  # noqa: BLE001 — introspection best-effort
                continue
            view = {
                "worker": w.idx, "epoch": w.epoch, "fenced": False,
                "ticks": flight.get("ticks") or [],
                "stats": flight.get("stats") or {},
                "live": reqs.get("live") or [],
                "completed": reqs.get("completed") or [],
            }
            views[w.idx] = view
            with pod._lock:
                pod._flight_cache[w.idx] = view
        with pod._lock:
            cached = dict(pod._flight_cache)
        for idx, view in cached.items():
            if idx in views:
                continue
            w = pod.workers[idx]
            stale = dict(view)
            stale["fenced"] = (
                not w.alive or stale.get("epoch") != w.epoch
            )
            views[idx] = stale
        return [views[i] for i in sorted(views)]

    def _stamp(
        self, entry: Dict[str, Any], view: Dict[str, Any], graft: bool
    ) -> Dict[str, Any]:
        entry = dict(entry)
        entry["worker"] = view["worker"]
        entry["epoch"] = view["epoch"]
        if view["fenced"]:
            entry["fenced"] = True
        if graft:
            self._graft(entry)
        return entry

    def _graft(self, rec: Dict[str, Any]) -> None:
        """Attach the gateway's handoff ledger entry (transfer_s, the
        handoff outcome, the prefill/decode worker pair) to a request
        record — the worker-side recorder cannot know any of it."""
        rid = rec.get("request_id")
        if not rid:
            return
        with self._pod._lock:
            note = self._pod._req_ledger.get(rid)
            note = dict(note) if note else None
        if note:
            rec.update(note)

    def _merged(
        self, key: str, n: Optional[int], graft: bool = False
    ) -> List[Dict[str, Any]]:
        out = []
        for view in self._fetch():
            for entry in view[key]:
                out.append(self._stamp(entry, view, graft))
        if key == "ticks":
            out.extend(dict(e) for e in self._gateway_ticks)
        out.sort(key=lambda e: e.get("t") or e.get("arrival_t") or 0.0)
        if n is not None and n >= 0:
            out = out[-n:]
        return out

    # --------------------------------------- FlightRecorder's surface

    def ticks(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("ticks", n)

    def requests(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("completed", n, graft=True)

    def live_requests(self) -> List[Dict[str, Any]]:
        return self._merged("live", None, graft=True)

    def find_request(self, ident: str) -> Optional[Dict[str, Any]]:
        # newest attempt wins ACROSS workers too (a handoff or failover
        # leaves records for the same request id on several workers)
        best: Optional[Dict[str, Any]] = None
        for view in self._fetch():
            for key in ("live", "completed"):
                for rec in view[key]:
                    if ident not in (
                        rec.get("request_id"),
                        rec.get("trace_id"),
                        str(rec.get("seq_id")),
                    ):
                        continue
                    rec = self._stamp(rec, view, graft=True)
                    if best is None or (rec.get("arrival_t") or 0.0) >= (
                        best.get("arrival_t") or 0.0
                    ):
                        best = rec
        return best

    def get_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "workers": [
                {
                    "worker": v["worker"],
                    "epoch": v["epoch"],
                    "fenced": v["fenced"],
                    **(v["stats"] or {}),
                }
                for v in self._fetch()
            ],
        }


class PodEngine:
    """ReplicatedEngine's surface over worker processes."""

    def __init__(self, config: Optional[VGTConfig] = None) -> None:
        self.config = config or get_config()
        pod = self.config.pod
        if pod.workers < 1:
            raise ValueError("PodEngine requires pod.workers >= 1")
        self._pod_cfg = pod
        self._recovery = self.config.recovery
        # disaggregated pools: roles default to all-mixed, which keeps
        # routing and submission byte-identical to a role-less pod
        self._roles: List[str] = (
            list(pod.roles) if pod.roles else ["mixed"] * pod.workers
        )
        self._roles_active = any(r != "mixed" for r in self._roles)
        self.spec = spec_for_model_id(self.config.model.model_id)
        self.tokenizer = get_tokenizer(
            self.spec,
            self.config.model.tokenizer_path
            or self.config.model.checkpoint_path,
        )
        self._lock = threading.RLock()
        self._inflight: Dict[int, _PodSequence] = {}
        self._orphans: List[_PodSequence] = []
        self._handoffs: Dict[int, _HandoffRec] = {}
        # per-request gateway annotations (KV-handoff transfer_s and
        # outcome) grafted onto merged flight records; insertion-ordered
        # dict with FIFO eviction so it stays bounded
        self._req_ledger: Dict[str, Dict[str, Any]] = {}
        self._ledger_cap = 2048
        # last-known per-slot flight snapshot (refreshed on every
        # /debug scrape) — survives the incarnation so a crashed
        # worker's timeline stays inspectable, epoch-marked
        self._flight_cache: Dict[int, Dict[str, Any]] = {}
        # gateway-synthesized post-mortem for the most recent worker
        # loss (same shape as FlightRecorder.crash_snapshot)
        self._last_crash: Optional[Dict[str, Any]] = None
        self._tracer = tracing.get_tracer("vgate_tpu.pod")
        self._flight = _PodFlight(self)
        self._sids = itertools.count(1)
        self._rr = itertools.count()
        self._xfer_ids = itertools.count(1)
        self._restart_times: List[float] = []
        self._fenced_clients: List[WorkerClient] = []
        self._zombie_procs: List[subprocess.Popen] = []
        self._stopping = False
        self._monitor: Optional[threading.Thread] = None
        self.total_failovers = 0
        self.total_restarts = 0
        self.total_stalls = 0
        self.total_resumed = 0
        self.total_migrated = 0
        self.total_lost = 0
        self.fenced_frames = 0
        self.total_handoffs = 0
        self.total_handoff_fallbacks = 0
        self.total_handoff_failed = 0
        self._canary_expected: Optional[str] = None
        # gateway-crash survivability (pod.orphan_grace_s): workers
        # adopted from a predecessor's registry instead of respawned
        self.total_adopted = 0
        self.total_orphans_found = 0
        self.total_orphans_expired = 0
        # sid floor across concurrent adoptions — fresh sids must start
        # above every sid the predecessor ever issued to an adoptee
        self._sid_floor = 1
        # sids whose sequence is an adopted SHELL: the gateway holds no
        # prompt for them, so they can finish or fail typed but never
        # replay onto a survivor
        self._adopted_sids: set = set()
        # request_id → sid for adopted in-flight work; app.py reconciles
        # its journal's pending records against this at startup
        self.adopted_request_ids: Dict[str, int] = {}
        # app.py hook: (request_id, result|None, error|None), fired when
        # an adopted shell settles so the journal can settle/fail the
        # matching idempotency record.  Settles that land BEFORE the
        # hook is attached (a short decode finishing during boot) park
        # in adopted_results until drain_adopted_results() collects
        # them — results must never race the app's startup wiring.
        self.on_adopted_done: Optional[
            Callable[[str, Optional[Dict[str, Any]], Optional[str]], Any]
        ] = None
        self.adopted_results: Dict[
            str, Tuple[Optional[Dict[str, Any]], Optional[str]]
        ] = {}

        self._own_socket_dir = not pod.socket_dir
        self.socket_dir = pod.socket_dir or tempfile.mkdtemp(
            prefix="vgt-pod-"
        )
        self._config_path = self._write_worker_config()
        self.workers = [_Worker(i) for i in range(pod.workers)]
        try:
            self._boot_all()
        except BaseException:
            self.stop()
            raise
        lead = self.workers[0].hello
        # the backend seam logs core.mesh.shape.items() and
        # core.geometry.num_pages; present the lead worker's view plus
        # the pod axis, like dp presents dp=N
        self.mesh = SimpleNamespace(
            shape=dict(lead.get("mesh", {}), workers=pod.workers)
        )
        geo = lead.get("geometry", {})
        self.geometry = SimpleNamespace(
            num_pages=int(geo.get("num_pages", 0)) * pod.workers,
            page_size=int(geo.get("page_size", 0)),
            kv_dtype=geo.get("kv_dtype"),
        )
        self.load_time_s = sum(
            float(w.hello.get("load_time_s", 0.0)) for w in self.workers
        )
        logger.info(
            "pod engine ready",
            extra={
                "extra_data": {
                    "workers": pod.workers,
                    "transport": pod.transport,
                    "model": self.spec.name,
                }
            },
        )

    # ------------------------------------------------------------ boot

    def _write_worker_config(self) -> str:
        """Dump the RESOLVED gateway config for workers (JSON is valid
        YAML, so load_config-style tooling can read it too).  Workers
        must not recurse into pod mode and host exactly one engine."""
        dump = self.config.model_dump()
        dump["pod"]["workers"] = 0
        # roles are gateway routing state; a one-engine worker config
        # with roles but workers=0 would fail the per-worker validator
        dump["pod"]["roles"] = []
        dump["tpu"]["dp"] = 1
        if self._roles_active:
            # both sides of a KV handoff need the PR-11 pinned host
            # pool (prefill stages out of it, decode adopts into it);
            # floor it at the transfer staging budget so roles work
            # without the operator separately enabling host swap
            dump["kv_cache"]["host_swap_bytes"] = max(
                int(dump["kv_cache"].get("host_swap_bytes") or 0),
                int(dump["pod"].get("transfer_staging_bytes") or 0),
            )
        fd, path = tempfile.mkstemp(
            prefix="vgt-worker-cfg-", suffix=".json", dir=self.socket_dir
        )
        with os.fdopen(fd, "w") as fh:
            json.dump(dump, fh)
        return path

    def _boot_all(self) -> None:
        errors: List[BaseException] = []
        adoptable = self._scan_registry()

        def boot(w: _Worker) -> None:
            try:
                rec = adoptable.get(w.idx)
                if rec is not None:
                    try:
                        self._try_adopt(w, rec)
                        return
                    except BaseException as exc:  # noqa: BLE001
                        # adoption is best-effort: fence + kill the
                        # orphan and fall through to a fresh spawn
                        logger.warning(
                            "worker adoption failed; respawning",
                            extra={
                                "extra_data": {
                                    "worker": w.idx,
                                    "pid": rec.get("pid"),
                                    "error": str(exc),
                                }
                            },
                        )
                        self._abandon_adoption(w, rec)
                self._spawn_and_gate(w)
            except BaseException as exc:  # noqa: BLE001 — collected
                errors.append(exc)

        threads = [
            threading.Thread(
                target=boot, args=(w,), daemon=True,
                name=f"vgt-pod-boot-{w.idx}",
            )
            for w in self.workers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self._pod_cfg.spawn_timeout_s + 30.0)
        if errors:
            raise RuntimeError(
                f"pod boot failed: {errors[0]}"
            ) from errors[0]
        if any(not w.alive for w in self.workers):
            raise RuntimeError("pod boot failed: worker never became ready")

    def _worker_env(self, w: _Worker) -> Dict[str, str]:
        env = dict(os.environ)
        # `-m vgate_tpu.runtime.worker` must resolve THIS vgate_tpu no
        # matter what cwd the gateway was launched from
        import vgate_tpu as _pkg

        pkg_root = os.path.dirname(os.path.dirname(_pkg.__file__))
        paths = env.get("PYTHONPATH", "")
        if pkg_root not in paths.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + paths if paths else "")
            )
        # the gateway's own chaos config must not leak into workers —
        # a fault armed for the gateway wire would double-fire
        env.pop("VGT_FAULTS", None)
        env.pop("VGT_CHAOS", None)
        # drills target a SPECIFIC worker's FIRST incarnation:
        # VGT_POD_WORKER_FAULTS="0=decode_step:raise;1=rpc_send:delay:delay=30"
        # (respawned incarnations boot clean — the fault made its point)
        spec = os.environ.get("VGT_POD_WORKER_FAULTS", "")
        if spec and w.epoch == 1:
            for part in spec.split(";"):
                if "=" not in part:
                    continue
                idx_s, fault = part.split("=", 1)
                try:
                    if int(idx_s) == w.idx:
                        env["VGT_FAULTS"] = fault
                except ValueError:
                    continue
        return env

    def _spawn(self, w: _Worker) -> None:
        """Launch one worker incarnation (caller holds no RPCs; the
        epoch was already bumped by the caller)."""
        pod = self._pod_cfg
        if pod.transport == "uds":
            path = os.path.join(
                self.socket_dir, f"w{w.idx}.e{w.epoch}.sock"
            )
            w.address = path
            sock_args = ["--socket", path]
        else:
            # TCP reuses a stable per-slot port, so any previous
            # incarnation still bound to it must die first
            port = pod.port_base + w.idx
            w.address = ("127.0.0.1", port)
            sock_args = ["--port", str(port)]
        cmd = [
            pod.python or sys.executable,
            "-m",
            "vgate_tpu.runtime.worker",
            *sock_args,
            "--epoch",
            str(w.epoch),
            "--config",
            self._config_path,
            "--index",
            str(w.idx),
            # liveness/adoption registry rides in the shared socket dir
            # so a successor gateway (stable pod.socket_dir) finds it
            "--registry-dir",
            self.socket_dir,
        ]
        w.proc = subprocess.Popen(cmd, env=self._worker_env(w))
        logger.info(
            "spawned engine worker",
            extra={
                "extra_data": {
                    "worker": w.idx, "epoch": w.epoch, "pid": w.proc.pid,
                }
            },
        )

    def _connect(self, w: _Worker) -> WorkerClient:
        """Connect to the freshly-spawned worker: poll until its
        listener exists (bound before the engine builds, so this is
        fast), bounded by spawn_timeout_s; a worker that dies while we
        wait fails immediately instead of burning the budget."""
        pod = self._pod_cfg
        deadline = time.monotonic() + pod.spawn_timeout_s
        epoch = w.epoch
        idx = w.idx
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            if w.proc is not None and w.proc.poll() is not None:
                raise WorkerLostError(
                    f"worker {idx} (epoch {epoch}) exited with "
                    f"{w.proc.returncode} during boot"
                )
            try:
                return WorkerClient(
                    w.address,
                    epoch,
                    max_frame_bytes=pod.max_frame_bytes,
                    connect_timeout_s=pod.connect_timeout_s,
                    call_timeout_s=pod.call_timeout_s,
                    on_notify=lambda f, i=idx, e=epoch: self._on_frame(
                        i, e, f
                    ),
                    on_lost=lambda exc, i=idx, e=epoch: self._on_lost(
                        i, e, exc
                    ),
                    label=f"worker{idx}.e{epoch}",
                )
            except (FileNotFoundError, ConnectionRefusedError, OSError) as exc:
                last = exc
                time.sleep(_CONNECT_POLL_S)
        raise WorkerLostError(
            f"worker {idx} (epoch {epoch}) never accepted a connection "
            f"within {pod.spawn_timeout_s:.0f}s: {last}"
        ) from last

    def _spawn_and_gate(self, w: _Worker) -> None:
        """Spawn → connect → hello → canary gate → routable.  Raises on
        any step failing; the caller owns retry/budget policy."""
        with self._lock:
            w.epoch += 1
            w.state = "spawning"
            w.draining = False
        self._spawn(w)
        client = self._connect(w)
        try:
            hello = client.call(
                "hello", timeout=self._pod_cfg.spawn_timeout_s
            )
            self._canary_gate(w, client)
        except BaseException:
            client.close()
            raise
        with self._lock:
            w.client = client
            w.hello = hello
            w.last_ok_t = time.monotonic()
            w.last_fatal = None
            w.state = "serving"
        self._set_alive_gauge()
        self._drain_orphans()

    def _canary_gate(self, w: _Worker, client: WorkerClient) -> None:
        """PR-9 pinned-greedy gate before the worker becomes routable:
        identical weights + greedy decode ⇒ identical fingerprint
        across every worker and every incarnation.  First answer
        records; every later one must match."""
        icfg = self.config.integrity
        timeout = (
            icfg.canary_timeout_s + icfg.canary_compile_grace_s + 30.0
        )
        reply = client.call("canary", timeout=timeout)
        fp = reply.get("fingerprint")
        with self._lock:
            if self._canary_expected is None:
                self._canary_expected = fp
                return
            expected = self._canary_expected
        if fp != expected:
            metrics.CANARY_FAILURES.inc()
            raise RuntimeError(
                f"worker {w.idx} (epoch {w.epoch}) failed the canary "
                f"gate: fingerprint {fp} != recorded {expected}"
            )

    # ----------------------------------- adoption (gateway restart)

    def _scan_registry(self) -> Dict[int, Dict[str, Any]]:
        """Scan the registry a predecessor gateway shared with its
        workers (stable ``pod.socket_dir``).  A record whose pid is
        alive and whose liveness beat is fresh is an adoption
        candidate; a record that PROMISED a survivor (status serving/
        orphaned) without delivering one counts as an expired orphan —
        that is real work lost to the crash, and the alert rides on
        it.  Any record at all means a prior gateway lifetime ended in
        this registry dir and we are its successor."""
        found: Dict[int, Dict[str, Any]] = {}
        saw_any = False
        for w in self.workers:
            path = os.path.join(self.socket_dir, f"w{w.idx}.json")
            try:
                with open(path, encoding="utf-8") as fh:
                    rec = json.load(fh)
            except (OSError, ValueError):
                continue
            saw_any = True
            status = rec.get("status")
            pid = rec.get("pid")
            alive = (
                isinstance(pid, int) and pid > 0 and _pid_alive(pid)
            )
            try:
                beat_age = time.time() - float(rec.get("beat") or 0.0)
            except (TypeError, ValueError):
                beat_age = float("inf")
            if status not in ("serving", "orphaned"):
                continue  # clean exit post-mortem — nothing to adopt
            if alive and beat_age < _ADOPT_BEAT_FRESH_S:
                found[w.idx] = rec
                self.total_orphans_found += 1
                metrics.WORKERS_ORPHANED.inc()
            else:
                self.total_orphans_expired += 1
                metrics.ORPHAN_EXPIRED.inc()
                if alive:
                    # beat-stale but breathing: wedged — don't adopt,
                    # clear the slot for a fresh spawn
                    try:
                        os.kill(pid, signal.SIGTERM)
                    except OSError:
                        pass
        if saw_any:
            metrics.GATEWAY_RESTARTS.inc()
            logger.warning(
                "predecessor gateway registry found",
                extra={
                    "extra_data": {
                        "adoptable": sorted(found),
                        "expired": self.total_orphans_expired,
                    }
                },
            )
        return found

    def _try_adopt(self, w: _Worker, rec: Dict[str, Any]) -> None:
        """Adopt a live orphan left by a crashed predecessor: connect
        to its persisted address, re-hello it under a bumped fencing
        epoch, inherit its in-flight decodes as shell sequences,
        canary-gate it, then ask it to flush the frames it buffered
        while orphaned.  Warm weights, the compile ledger and the
        radix cache all survive — zero respawns.  Raises on any step
        failing; the caller falls back to a fresh spawn."""
        pod = self._pod_cfg
        with self._lock:
            # strictly newer than every epoch the orphan has seen, and
            # monotonic within this gateway's own bookkeeping
            w.epoch = max(w.epoch, int(rec.get("epoch") or 0)) + 1
            w.state = "spawning"
            w.draining = False
        addr = str(rec.get("address") or "")
        if pod.transport == "uds":
            w.address = addr
        else:
            host, _, port_s = addr.rpartition(":")
            w.address = (host or "127.0.0.1", int(port_s))
        w.proc = _AdoptedProc(int(rec["pid"]))
        client = self._connect(w)
        try:
            adopt = client.call(
                "adopt", timeout=pod.connect_timeout_s + 10.0
            )
            hello = client.call(
                "hello", timeout=pod.spawn_timeout_s
            )
            self._canary_gate(w, client)
        except BaseException:
            client.close()
            raise
        inflight = adopt.get("inflight") or []
        with self._lock:
            max_sid = 0
            for ent in inflight:
                try:
                    sid = int(ent["sid"])
                except (KeyError, TypeError, ValueError):
                    continue
                max_sid = max(max_sid, sid)
                if ent.get("cancelled"):
                    continue  # already aborted; let the worker reap it
                # shell sequence: the gateway holds no prompt for it —
                # it can finish (done carries the authoritative text)
                # or fail typed, but never replay onto a survivor
                shell = _PodSequence(
                    prompt_ids=[0], params=SamplingParams()
                )
                shell._pod = self
                shell._sid = sid
                shell._worker_idx = w.idx
                shell.request_id = ent.get("request_id")
                # pad to the delivered-token count; the orphan_flush
                # replay appends the buffered remainder, so usage
                # totals reconcile
                shell.generated_ids = [0] * int(
                    ent.get("generated_tokens") or 0
                )
                self._inflight[sid] = shell
                self._adopted_sids.add(sid)
                rid = ent.get("request_id")
                if rid:
                    self.adopted_request_ids[str(rid)] = sid
            # fresh sids must start above everything the predecessor
            # ever issued to any adoptee (adoptions run concurrently)
            self._sid_floor = max(self._sid_floor, max_sid + 1)
            self._sids = itertools.count(self._sid_floor)
            w.client = client
            w.hello = hello
            w.last_ok_t = time.monotonic()
            w.last_fatal = None
            w.state = "serving"
            self.total_adopted += 1
        metrics.WORKERS_ADOPTED.inc()
        logger.info(
            "adopted orphan worker",
            extra={
                "extra_data": {
                    "worker": w.idx,
                    "epoch": w.epoch,
                    "pid": rec.get("pid"),
                    "inflight": len(inflight),
                    "buffered_frames": adopt.get("buffered_frames"),
                    "was_orphaned": adopt.get("was_orphaned"),
                }
            },
        )
        try:
            # sids are registered — frames buffered during orphanhood
            # may now replay, in order, re-stamped with the new epoch
            client.notify("orphan_flush")
        except WorkerLostError:
            pass  # connection died post-adopt: the loss path owns it
        self._set_alive_gauge()
        self._drain_orphans()

    def _abandon_adoption(
        self, w: _Worker, rec: Dict[str, Any]
    ) -> None:
        """A failed adoption leaves a live-but-unadoptable orphan.  Its
        epoch is already behind the slot's, so it is fenced; kill it so
        the fresh spawn can take the slot (TCP: rebind the port) and
        count the in-flight work it carried as expired."""
        with self._lock:
            old_client, w.client = w.client, None
            old_proc, w.proc = w.proc, None
            w.state = "down"
        if old_client is not None:
            old_client.close()
        proc = old_proc
        if proc is None:
            pid = rec.get("pid")
            if isinstance(pid, int) and pid > 0:
                proc = _AdoptedProc(pid)
        if proc is not None:
            self._kill_proc(proc)
        with self._lock:
            self.total_orphans_expired += 1
        metrics.ORPHAN_EXPIRED.inc()

    def start(self) -> None:
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="vgt-pod-monitor"
        )
        self._monitor.start()

    # ------------------------------------------------------- frame dispatch

    def _on_frame(self, idx: int, client_epoch: int, frame: Dict[str, Any]) -> None:
        w = self.workers[idx]
        fe = frame.get("e")
        if not isinstance(fe, int) or fe != w.epoch:
            # late frame from a fenced incarnation (zombie declared
            # lost, or replaced after a drain): discard + count — it
            # must never interleave into the live incarnation's streams
            with self._lock:
                self.fenced_frames += 1
            metrics.POD_FENCED_FRAMES.inc()
            return
        op = frame.get("op")
        if op == "tok":
            self._on_token(idx, frame)
        elif op == "done":
            self._on_done(idx, frame)
        elif op == "err":
            self._on_err(idx, frame)
        elif op == "evacuated":
            self._on_evacuated(idx, frame)
        elif op == "handoff_staged":
            self._on_handoff_staged(idx, frame)
        elif op == "handoff_fallback":
            self._on_handoff_fallback(idx, frame)

    def _seq_for(self, idx: int, frame: Dict[str, Any]) -> Optional[_PodSequence]:
        with self._lock:
            seq = self._inflight.get(frame.get("sid"))
        if seq is None or seq._worker_idx != idx:
            return None  # settled, aborted, or resubmitted elsewhere
        return seq

    def _handoff_intercept(self, idx: int, frame: Dict[str, Any]) -> bool:
        """Pre-dispatch hook for tok/done/err frames while a handoff
        record exists for the sid.  Two cases:

        * frame from the DECODE TARGET before ownership flipped —
          buffer it on the record (replayed in order at accept) and
          consume it (return True);
        * frame from the PREFILL worker while the sequence is staged or
          transferring — the worker's own supervisor replayed it
          locally (the fold clears the hold), so the handoff is moot:
          cancel the record and let the frame flow (monolithic decode
          continues on the prefill worker, token-identically).
        """
        sid = frame.get("sid")
        fallback = False
        with self._lock:
            rec = self._handoffs.get(sid)
            if rec is None:
                return False
            if rec.target_idx == idx and not rec.cancelled:
                if frame.get("op") == "tok":
                    rec.buffered.append(frame)
                else:
                    rec.terminal = (frame.get("op"), frame)
                return True
            if rec.prefill_idx == idx and rec.state in (
                handoff_mod.STAGED, handoff_mod.TRANSFERRING
            ):
                self._handoffs.pop(sid, None)
                rec.cancelled = True
                self.total_handoff_fallbacks += 1
                fallback = True
        if fallback:
            metrics.HANDOFF_TOTAL.labels(outcome="fallback_monolithic").inc()
            self._ledger_note(
                rec.seq.request_id, handoff="fallback_monolithic"
            )
        return False

    @staticmethod
    def _apply_token(seq: _PodSequence, frame: Dict[str, Any]) -> None:
        lp = frame.get("lp")
        if lp is not None and seq.params.logprobs:
            # raw (chosen_lp, [(tid, lp), ...]) data — the gateway's
            # lp_entry renders it with its own tokenizer
            seq.logprob_data.append(
                (float(lp[0]), [(int(t), float(l)) for t, l in lp[1]])
            )
        seq.append_token(int(frame["t"]))

    def _on_token(self, idx: int, frame: Dict[str, Any]) -> None:
        if self._handoff_intercept(idx, frame):
            return
        seq = self._seq_for(idx, frame)
        if seq is None:
            return
        self._apply_token(seq, frame)

    def _on_done(self, idx: int, frame: Dict[str, Any]) -> None:
        if self._handoff_intercept(idx, frame):
            return
        seq = self._seq_for(idx, frame)
        if seq is None:
            return
        with self._lock:
            self._inflight.pop(seq._sid, None)
            adopted = seq._sid in self._adopted_sids
            self._adopted_sids.discard(seq._sid)
            # a sequence that finished before its handoff ever staged
            # (short decode) retires the record silently — nothing to
            # transfer, nothing degraded
            rec = self._handoffs.pop(seq._sid, None)
            if rec is not None:
                rec.cancelled = True
        text = frame.get("text")
        if text is not None:
            # the worker's final text is authoritative (stop-string
            # truncation happened against ITS decode state)
            seq.text_override = text
        lp = frame.get("lp")
        if lp is not None and seq.params.logprobs:
            seq.logprob_data = [
                (float(e[0]), [(int(t), float(l)) for t, l in e[1]])
                for e in lp
            ]
        # worker-internal supervisor restarts also bump these; take the
        # max of both views so neither hop under-reports
        seq.resume_count = max(
            seq.resume_count, int(frame.get("resume_count", 0))
        )
        seq.migrate_count = max(
            seq.migrate_count, int(frame.get("migrate_count", 0))
        )
        seq.finish(str(frame.get("finish_reason", "stop")))
        if adopted:
            self._notify_adopted_done(
                seq,
                result={
                    "request_id": seq.request_id,
                    "text": text if text is not None else "",
                    "finish_reason": str(
                        frame.get("finish_reason", "stop")
                    ),
                    "generated_tokens": len(seq.generated_ids),
                },
                error=None,
            )

    def _notify_adopted_done(
        self,
        seq: _PodSequence,
        result: Optional[Dict[str, Any]],
        error: Optional[str],
    ) -> None:
        """Tell the app layer an ADOPTED shell settled so it can settle
        or fail the matching journal record (idempotent replay)."""
        if not seq.request_id:
            return
        rid = str(seq.request_id)
        with self._lock:
            self.adopted_request_ids.pop(rid, None)
            cb = self.on_adopted_done
            if cb is None:
                self.adopted_results[rid] = (result, error)
                return
        try:
            cb(rid, result, error)
        except Exception:  # noqa: BLE001 — observer must not wedge I/O
            logger.exception("on_adopted_done callback failed")

    def drain_adopted_results(
        self,
    ) -> Dict[str, Tuple[Optional[Dict[str, Any]], Optional[str]]]:
        """Adopted settles that landed before ``on_adopted_done`` was
        attached — the app layer collects them right after wiring the
        hook, closing the boot-time race."""
        with self._lock:
            out, self.adopted_results = self.adopted_results, {}
        return out

    def _on_err(self, idx: int, frame: Dict[str, Any]) -> None:
        if self._handoff_intercept(idx, frame):
            return
        seq = self._seq_for(idx, frame)
        if seq is None:
            return
        with self._lock:
            self._inflight.pop(seq._sid, None)
            adopted = seq._sid in self._adopted_sids
            self._adopted_sids.discard(seq._sid)
            rec = self._handoffs.pop(seq._sid, None)
            if rec is not None:
                rec.cancelled = True
        err = unwire_error(frame.get("error") or {})
        seq.fail(err)
        if adopted:
            self._notify_adopted_done(seq, result=None, error=str(err))

    def _on_evacuated(self, idx: int, frame: Dict[str, Any]) -> None:
        """Worker-initiated drain (SIGTERM straight to the worker —
        rolling OS-level restarts): replay its evacuated sequences onto
        survivors as planned movements."""
        sids = [int(e["sid"]) for e in frame.get("evacuated") or []]
        seqs: List[_PodSequence] = []
        with self._lock:
            for sid in sids:
                seq = self._inflight.pop(sid, None)
                if seq is not None:
                    seqs.append(seq)
        for seq in seqs:
            self._replay(seq, exclude=idx, planned=True)

    # ------------------------------------------- KV handoff (pod.roles)

    def _on_handoff_staged(self, idx: int, frame: Dict[str, Any]) -> None:
        """The prefill worker folded + staged the sequence's KV: record
        the transfer metadata (PREFILLING → STAGED) and launch the
        transfer thread.  A staging notification with no live record
        (the request was replayed/aborted meanwhile) is answered with a
        cancel so the worker resumes monolithic decode immediately."""
        sid = int(frame.get("sid", -1))
        with self._lock:
            rec = self._handoffs.get(sid)
            seq = self._inflight.get(sid)
            ok = (
                rec is not None
                and not rec.cancelled
                and seq is not None
                and seq is rec.seq
                and seq._worker_idx == idx
                and rec.state == handoff_mod.PREFILLING
            )
            if ok:
                handoff_mod.advance(rec.state, handoff_mod.STAGED)
                rec.state = handoff_mod.STAGED
                rec.pages = int(frame.get("pages", 0))
                rec.nbytes = int(frame.get("nbytes", 0))
                rec.base_len = int(frame.get("base_len", 0))
                rec.generated_ids = [
                    int(t) for t in frame.get("generated_ids") or []
                ]
                rec.resume_count = int(frame.get("resume_count", 0))
                rec.migrate_count = int(frame.get("migrate_count", 0))
                rec.preempt_count = int(frame.get("preempt_count", 0))
                rec.swap_count = int(frame.get("swap_count", 0))
                rec.kv_dtype = frame.get("kv_dtype")
                rec.t0 = time.monotonic()
                rec.t_staged_pc = time.perf_counter()
        if not ok:
            w = self.workers[idx]
            client = w.client
            if client is not None and not client.dead:
                try:
                    client.notify("handoff_cancel", sid=sid)
                except WorkerLostError:
                    pass
            return
        threading.Thread(
            target=self._run_handoff, args=(rec,), daemon=True,
            name=f"vgt-pod-handoff-{sid}",
        ).start()

    def _on_handoff_fallback(self, idx: int, frame: Dict[str, Any]) -> None:
        """The prefill worker could not stage (host pool refused, abort
        raced the fold): it keeps decoding monolithically."""
        sid = int(frame.get("sid", -1))
        with self._lock:
            rec = self._handoffs.pop(sid, None)
            if rec is not None:
                rec.cancelled = True
                self.total_handoff_fallbacks += 1
        if rec is not None:
            metrics.HANDOFF_TOTAL.labels(outcome="fallback_monolithic").inc()
            self._ledger_note(
                rec.seq.request_id, handoff="fallback_monolithic"
            )

    def _handoff_span(
        self,
        seq: _PodSequence,
        stage: str,
        start_pc: float,
        end_pc: float,
        **attrs: Any,
    ) -> None:
        """Gateway-side ``handoff.<stage>`` span parented on the
        request's captured HTTP-span context — the explicit middle of
        the cross-process trace (prefill worker spans on one side,
        decode worker spans on the other).  No-op without a valid
        trace context, same gate reqtrace uses."""
        ctx = seq._trace_ctx
        if tracing.context_trace_id(ctx) is None:
            return
        span = self._tracer.start_span(
            f"handoff.{stage}",
            context=ctx,
            start_time=_pc_to_ns(start_pc),
        )
        if seq.request_id:
            span.set_attribute("request.id", seq.request_id)
        for key, val in attrs.items():
            span.set_attribute(key, val)
        span.end(end_time=_pc_to_ns(end_pc))

    def _ledger_note(
        self, request_id: Optional[str], **fields: Any
    ) -> None:
        """Record a gateway-side per-request annotation for the merged
        flight view (bounded FIFO; requests without an id — direct
        generate() calls — have no flight record to graft onto)."""
        if not request_id:
            return
        with self._lock:
            entry = self._req_ledger.get(request_id)
            if entry is None:
                while len(self._req_ledger) >= self._ledger_cap:
                    self._req_ledger.pop(
                        next(iter(self._req_ledger))
                    )
                entry = self._req_ledger[request_id] = {}
            entry.update(fields)

    def _run_handoff(self, rec: _HandoffRec) -> None:
        metrics.HANDOFF_ACTIVE.inc()
        try:
            self._handoff_attempts(rec)
        except BaseException:  # noqa: BLE001 — thread must not die loud
            logger.error(
                "handoff transfer thread crashed",
                extra={"extra_data": {"sid": rec.sid}},
                exc_info=True,
            )
            self._handoff_abandon(rec, "failed")
        finally:
            metrics.HANDOFF_ACTIVE.dec()

    def _handoff_attempts(self, rec: _HandoffRec) -> None:
        """Bounded-retry transfer loop.  Every exit is terminal for the
        record: accept (ownership flips to the decode worker), fallback
        (prefill worker resumes monolithic decode, zero recompute), or
        abandon (the loss path owns the sequence)."""
        pod = self._pod_cfg
        while True:
            staged_dwell = False
            with self._lock:
                if rec.cancelled or rec.sid not in self._handoffs:
                    return
                if rec.state == handoff_mod.STAGED:
                    handoff_mod.advance(
                        rec.state, handoff_mod.TRANSFERRING
                    )
                    rec.state = handoff_mod.TRANSFERRING
                    rec.t_transfer_pc = time.perf_counter()
                    staged_dwell = True
            if staged_dwell and rec.t_staged_pc:
                # STAGED → TRANSFERRING happens once per record (a
                # retry stays TRANSFERRING), so the stage dwell and its
                # span are emitted exactly once
                metrics.HANDOFF_STATE_SECONDS.labels(
                    state="staged"
                ).observe(rec.t_transfer_pc - rec.t_staged_pc)
                self._handoff_span(
                    rec.seq, "stage", rec.t_staged_pc,
                    rec.t_transfer_pc, sid=rec.sid,
                    prefill=rec.prefill_idx, pages=rec.pages,
                    nbytes=rec.nbytes,
                )
            target = self._decode_target(exclude=rec.prefill_idx)
            if target is None:
                self._handoff_fallback_monolithic(
                    rec, "no decode-capable worker alive"
                )
                return
            xid = f"h{rec.sid}.{next(self._xfer_ids)}"
            with self._lock:
                rec.target_idx = target.idx
                rec.buffered = []
                rec.terminal = None
            try:
                self._transfer_once(rec, target, xid)
            except HandoffStaleError:
                # the prefill side invalidated the staging (abort, or a
                # worker-internal replay cleared the hold): whoever
                # invalidated it owns the sequence now
                self._handoff_abandon(rec, "fallback_monolithic")
                return
            except _SourceLost:
                # prefill connection died: the pod loss path folds and
                # replays the sequence on a survivor
                self._handoff_abandon(rec, "failed")
                return
            except (
                HandoffTransferError,
                WorkerLostError,
                TimeoutError,
                faults.InjectedFault,
            ) as exc:
                rec.attempts += 1
                with self._lock:
                    committed = bool(rec.buffered or rec.terminal)
                if committed:
                    # the commit actually landed (the target is already
                    # streaming tokens) — the error was a lost/slow
                    # reply.  Finalize instead of retrying.
                    self._finalize_accept(rec, target)
                    return
                # kill any partial/ghost admission on the target before
                # the next attempt or the fallback
                self._kill_target_copy(target, xid, rec.sid)
                if rec.attempts > pod.transfer_max_retries:
                    self._handoff_fallback_monolithic(rec, str(exc))
                    return
                metrics.HANDOFF_TOTAL.labels(outcome="retried").inc()
                logger.warning(
                    "handoff transfer attempt failed; retrying",
                    extra={
                        "extra_data": {
                            "sid": rec.sid,
                            "attempt": rec.attempts,
                            "target": target.idx,
                            "error": str(exc),
                        }
                    },
                )
                continue
            self._finalize_accept(rec, target)
            return

    def _transfer_once(
        self, rec: _HandoffRec, target: _Worker, xid: str
    ) -> None:
        """One pull-relay attempt: fetch chunks from the prefill worker,
        put them to the decode worker, commit.  The ``kv_transfer``
        fault point probes once per chunk (drop/garble/duplicate/delay
        — drills for every framing failure mode)."""
        pod = self._pod_cfg
        pw = self.workers[rec.prefill_idx]
        with self._lock:
            stale_src = pw.epoch != rec.prefill_epoch
        pclient = pw.client
        tclient = target.client
        if stale_src or pclient is None or pclient.dead:
            raise _SourceLost()
        if tclient is None or tclient.dead:
            raise HandoffTransferError(
                f"decode worker {target.idx} has no live connection"
            )
        deadline = time.monotonic() + pod.transfer_timeout_s
        chunk = max(1, int(pod.transfer_chunk_bytes))
        off = 0
        total: Optional[int] = None
        digest = 0
        while total is None or off < total:
            with self._lock:
                if rec.cancelled:
                    raise HandoffStaleError("handoff record cancelled")
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise HandoffTransferError(
                    f"transfer timed out after "
                    f"{pod.transfer_timeout_s:.0f}s"
                )
            try:
                reply = pclient.call(
                    "handoff_fetch", sid=rec.sid, off=off, n=chunk,
                    timeout=budget,
                )
            except WorkerLostError as exc:
                raise _SourceLost() from exc
            total = int(reply.get("total", 0))
            digest = int(reply.get("digest", 0))
            try:
                data = base64.b64decode(
                    str(reply.get("data", "")), validate=True
                )
            except (binascii.Error, ValueError) as exc:
                raise HandoffTransferError(
                    f"undecodable fetch chunk: {exc}"
                ) from exc
            if not data:
                if off >= total:
                    break
                raise HandoffTransferError(
                    f"empty fetch chunk at offset {off}/{total}"
                )
            verdict = (
                faults.wire_action("kv_transfer")
                if faults.is_active()
                else None
            )
            if verdict != "drop":
                out = data
                if verdict == "garble":
                    out = bytes(b ^ 0x55 for b in data[:64]) + data[64:]
                payload = base64.b64encode(out).decode("ascii")
                budget = deadline - time.monotonic()
                if budget <= 0:
                    raise HandoffTransferError("transfer timed out")
                tclient.call(
                    "handoff_put", xfer=xid, off=off, total=total,
                    data=payload, timeout=budget,
                )
                if verdict == "duplicate":
                    tclient.call(
                        "handoff_put", xfer=xid, off=off, total=total,
                        data=payload,
                        timeout=max(1.0, deadline - time.monotonic()),
                    )
            # a dropped chunk leaves a gap: commit raises typed, the
            # attempt retries with a fresh transfer id
            off += len(data)
        if not total:
            raise HandoffTransferError("staged blob is empty")
        seq = rec.seq
        remaining = None
        if seq.deadline_t is not None:
            remaining = max(
                0.01, seq.deadline_t - time.perf_counter()
            )
        budget = deadline - time.monotonic()
        if budget <= 0:
            raise HandoffTransferError("transfer timed out before commit")
        reply = tclient.call(
            "handoff_commit",
            xfer=xid,
            sid=rec.sid,
            digest=digest,
            pages=rec.pages,
            base_len=rec.base_len,
            prompt_ids=[
                int(t) for t in seq.prompt_ids[: seq.orig_prompt_len]
            ],
            generated_ids=[int(t) for t in rec.generated_ids],
            params=params_to_wire(seq.params),
            remaining_s=remaining,
            request_id=seq.request_id,
            traceparent=seq._traceparent,
            resume_count=rec.resume_count,
            migrate_count=rec.migrate_count,
            preempt_count=rec.preempt_count,
            swap_count=rec.swap_count,
            handoff_count=seq.handoff_count + 1,
            kv_dtype=rec.kv_dtype,
            timeout=budget,
        )
        if not reply.get("accepted"):
            raise HandoffTransferError(
                f"decode worker {target.idx} refused commit"
            )

    def _finalize_accept(self, rec: _HandoffRec, target: _Worker) -> bool:
        """Atomically flip sequence ownership to the decode worker
        (TRANSFERRING → ACCEPTED → DECODING), reconcile the client
        token stream to the fold point, replay buffered target frames
        in order, and release the prefill worker's surplus copy."""
        accept_pc = time.perf_counter()
        with self._lock:
            seq = self._inflight.get(rec.sid)
            ok = (
                not rec.cancelled
                and rec.sid in self._handoffs
                and seq is rec.seq
                and seq is not None
                and seq._worker_idx == rec.prefill_idx
                and not seq.done_event.is_set()
            )
            if ok:
                handoff_mod.advance(rec.state, handoff_mod.ACCEPTED)
                rec.state = handoff_mod.ACCEPTED
                seq._worker_idx = target.idx
                seq.handoff_count += 1
                # tok frames still in flight from the prefill worker are
                # fenced by the ownership flip: append the fold-point
                # suffix here so the client stream loses nothing
                for t in rec.generated_ids[len(seq.generated_ids):]:
                    seq.append_token(int(t))
                handoff_mod.advance(rec.state, handoff_mod.DECODING)
                rec.state = handoff_mod.DECODING
        if not ok:
            # the sequence moved under us (loss replay / abort): the
            # current owner's stream is authoritative — kill the
            # decode-side admission so no ghost burns slots
            tclient = target.client
            if tclient is not None and not tclient.dead:
                try:
                    tclient.notify(
                        "abort", sid=rec.sid, reason="handoff_superseded"
                    )
                except WorkerLostError:
                    pass
            self._handoff_abandon(rec, "failed")
            return False
        # drain buffered decode-side frames in arrival order; keep the
        # record registered until the buffer runs dry so the reader
        # thread keeps buffering instead of racing these appends
        terminal = None
        while True:
            with self._lock:
                frames, rec.buffered = rec.buffered, []
                if not frames:
                    terminal = rec.terminal
                    self._handoffs.pop(rec.sid, None)
                    break
            for f in frames:
                self._apply_token(seq, f)
        if terminal is not None:
            kind, f = terminal
            if kind == "done":
                self._on_done(target.idx, f)
            elif kind == "err":
                self._on_err(target.idx, f)
        pw = self.workers[rec.prefill_idx]
        pclient = pw.client
        if pclient is not None and not pclient.dead:
            try:
                pclient.notify("handoff_done", sid=rec.sid)
            except WorkerLostError:
                pass  # dead prefill worker frees the copy by dying
        with self._lock:
            self.total_handoffs += 1
        metrics.HANDOFF_TOTAL.labels(outcome="ok").inc()
        metrics.HANDOFF_SECONDS.observe(time.monotonic() - rec.t0)
        metrics.HANDOFF_BYTES.observe(rec.nbytes)
        end_pc = time.perf_counter()
        if rec.t_transfer_pc:
            metrics.HANDOFF_STATE_SECONDS.labels(
                state="transfer"
            ).observe(accept_pc - rec.t_transfer_pc)
            self._handoff_span(
                seq, "transfer", rec.t_transfer_pc, accept_pc,
                sid=rec.sid, prefill=rec.prefill_idx,
                decode=target.idx, pages=rec.pages,
                nbytes=rec.nbytes, attempts=rec.attempts,
            )
        metrics.HANDOFF_STATE_SECONDS.labels(state="accept").observe(
            end_pc - accept_pc
        )
        self._handoff_span(
            seq, "accept", accept_pc, end_pc,
            sid=rec.sid, decode=target.idx,
        )
        # graft target for the merged flight view: the worker-side
        # recorders each see only their half of the request, so the
        # gateway owns the transfer_s phase and the outcome
        self._ledger_note(
            seq.request_id,
            transfer_s=round(
                end_pc - (rec.t_staged_pc or accept_pc), 6
            ),
            handoff="ok",
            prefill_worker=rec.prefill_idx,
            decode_worker=target.idx,
        )
        logger.info(
            "kv handoff complete",
            extra={
                "extra_data": {
                    "sid": rec.sid,
                    "prefill": rec.prefill_idx,
                    "decode": target.idx,
                    "pages": rec.pages,
                    "nbytes": rec.nbytes,
                    "attempts": rec.attempts,
                }
            },
        )
        return True

    def _kill_target_copy(
        self, target: _Worker, xid: str, sid: int
    ) -> None:
        """Best-effort ghost cleanup on the decode worker after a failed
        attempt: drop the partial reassembly AND abort any admission a
        lost commit reply may have left running (its frames are fenced
        by `_seq_for`'s ownership check either way)."""
        tclient = target.client
        if tclient is None or tclient.dead:
            return
        try:
            tclient.notify("handoff_abort", xfer=xid)
            tclient.notify("abort", sid=sid, reason="handoff_retry")
        except WorkerLostError:
            pass

    def _handoff_fallback_monolithic(
        self, rec: _HandoffRec, detail: str
    ) -> None:
        """Terminal degrade: release the hold on the prefill worker so
        it swap-ins the staged KV and decodes monolithically — zero
        recompute, zero 5xx, token-identical."""
        with self._lock:
            existed = self._handoffs.pop(rec.sid, None) is not None
            rec.cancelled = True
            if existed:
                self.total_handoff_fallbacks += 1
            pw = self.workers[rec.prefill_idx]
            stale_src = pw.epoch != rec.prefill_epoch
        if not existed:
            return
        metrics.HANDOFF_TOTAL.labels(outcome="fallback_monolithic").inc()
        self._ledger_note(
            rec.seq.request_id, handoff="fallback_monolithic"
        )
        logger.warning(
            "handoff degraded to monolithic decode",
            extra={
                "extra_data": {
                    "sid": rec.sid,
                    "prefill": rec.prefill_idx,
                    "detail": detail,
                }
            },
        )
        pclient = pw.client
        if stale_src or pclient is None or pclient.dead:
            return  # the loss path already owns the sequence
        try:
            pclient.call(
                "handoff_cancel", sid=rec.sid,
                timeout=self._pod_cfg.call_timeout_s,
            )
        except (WorkerLostError, TimeoutError):
            # the frame is queued on a live-but-slow connection and
            # will still release the hold when processed; a truly dead
            # worker routes through the loss path instead
            pass

    def _handoff_abandon(self, rec: _HandoffRec, outcome: str) -> None:
        """Drop a record whose sequence somebody else now owns (loss
        replay, abort, worker-local resume).  Counted once."""
        with self._lock:
            existed = self._handoffs.pop(rec.sid, None) is not None
            rec.cancelled = True
            if existed:
                if outcome == "failed":
                    self.total_handoff_failed += 1
                elif outcome == "fallback_monolithic":
                    self.total_handoff_fallbacks += 1
        if existed:
            metrics.HANDOFF_TOTAL.labels(outcome=outcome).inc()
            self._ledger_note(rec.seq.request_id, handoff=outcome)

    def _handoff_stats(self) -> Dict[str, Any]:
        with self._lock:
            active = len(self._handoffs)
            return {
                "active": active,
                "completed": self.total_handoffs,
                "fallback_monolithic": self.total_handoff_fallbacks,
                "failed": self.total_handoff_failed,
                "roles": list(self._roles) if self._roles_active else [],
            }

    # ------------------------------------------------------------- routing

    def _alive_workers(self, exclude: Optional[int] = None) -> List[_Worker]:
        with self._lock:
            return [
                w
                for w in self.workers
                if w.alive and not w.draining and w.idx != exclude
            ]

    def _role(self, idx: int) -> str:
        return self._roles[idx] if 0 <= idx < len(self._roles) else "mixed"

    def _decode_target(self, exclude: Optional[int] = None) -> Optional[_Worker]:
        """Least-loaded decode-capable worker, or None — the caller
        degrades to monolithic decode rather than 5xx."""
        cands = [
            w
            for w in self._alive_workers(exclude=exclude)
            if self._role(w.idx) in ("decode", "mixed")
        ]
        return min(cands, key=self._load) if cands else None

    def _pick_worker(
        self,
        prompt_ids: Optional[List[int]] = None,
        exclude: Optional[int] = None,
        role: Optional[str] = None,
    ) -> _Worker:
        """dp's router, over worker handles: least-loaded among routable
        workers with prefix affinity (each worker's KV prefix cache is
        private — requests sharing a first page stick together unless
        that costs real queueing headroom).  With ``pod.roles`` active,
        ``role`` names the preferred pool (prefill/decode; ``mixed``
        workers belong to both); an empty pool falls through to every
        routable worker — a drained pool degrades, never 500s."""
        candidates = self._alive_workers(exclude=exclude)
        if role is not None and candidates:
            pooled = [
                w
                for w in candidates
                if self._role(w.idx) in (role, "mixed")
            ]
            if pooled:
                candidates = pooled
        if not candidates:
            # fall back to any live worker (a fully-draining pod still
            # serves rather than 500s)
            with self._lock:
                live = [w for w in self.workers if w.alive]
            if not live:
                raise WorkerLostError(
                    "no live engine worker (pod respawning or dead); "
                    "retry shortly",
                    retry_after=self.retry_after_s,
                )
            candidates = live
        offset = next(self._rr) % len(candidates)
        ordered = candidates[offset:] + candidates[:offset]
        best = min(ordered, key=self._load)
        page = self.config.tpu.kv_page_size
        if (
            prompt_ids is not None
            and len(prompt_ids) >= page
            and self.config.tpu.prefix_cache.enabled
        ):
            block = bytes(
                b
                for t in prompt_ids[:page]
                for b in int(t).to_bytes(4, "little")
            )
            sticky = self.workers[zlib.crc32(block) % len(self.workers)]
            if (
                sticky.alive
                and not sticky.draining
                and sticky.idx != exclude
                and any(w.idx == sticky.idx for w in candidates)
                and self._load(sticky)
                <= self._load(best)
                + max(2, self.config.tpu.max_batch_slots // 4)
            ):
                return sticky
        return best

    @staticmethod
    def _load(w: _Worker) -> int:
        sig = w.last_ping.get("pressure") or {}
        return int(sig.get("engine_queue_depth", 0)) + int(
            sig.get("running", 0)
        )

    # ---------------------------------------------------------- submission

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: Any,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        raise_for_state(
            self.state.value, retry_after=self.retry_after_s
        )
        seq = _PodSequence(
            prompt_ids=list(prompt_ids),
            params=params,
            stream_cb=stream_cb,
        )
        seq._pod = self
        seq._sid = next(self._sids)
        if meta is not None:
            seq.request_id = getattr(meta, "request_id", None)
            # capture the gateway's OTel context ONCE (the HTTP span
            # rides in it); the W3C encoding travels on every frame
            # that creates engine work in a worker process, so the
            # worker's engine spans parent onto the HTTP span
            seq._trace_ctx = getattr(meta, "trace_ctx", None)
            seq._traceparent = tracing.context_to_traceparent(
                seq._trace_ctx
            )
        self._dispatch_submit(seq)
        return seq

    def _dispatch_submit(
        self, seq: _PodSequence, exclude: Optional[int] = None
    ) -> None:
        """Place a sequence on a worker, retrying over the remaining
        alive workers on connection-level failures (a typed engine
        error — quarantine, overload — propagates immediately)."""
        prompt = seq.prompt_ids[: seq.orig_prompt_len]
        role: Optional[str] = None
        if self._roles_active:
            # fresh (prefill-heavy) work goes to the prefill pool;
            # replays already carrying generated tokens — including
            # post-handoff continuations — belong with the decode pool
            role = (
                "decode"
                if (seq.generated_ids or seq.handoff_count)
                else "prefill"
            )
        tried: set = set()
        last: Optional[BaseException] = None
        for _ in range(len(self.workers)):
            try:
                w = self._pick_worker(prompt, exclude=exclude, role=role)
            except WorkerLostError as exc:
                last = exc
                break
            if w.idx in tried:
                break
            tried.add(w.idx)
            client = w.client
            if client is None:
                continue
            remaining = None
            if seq.deadline_t is not None:
                remaining = seq.deadline_t - time.perf_counter()
                if remaining <= 0:
                    remaining = 0.01  # let the worker shed it typed
            # request a staged handoff only when the chosen worker is a
            # dedicated prefill worker AND a decode-capable target
            # exists right now — otherwise decode monolithically
            want_handoff = (
                role == "prefill"
                and self._role(w.idx) == "prefill"
                and self._decode_target(exclude=w.idx) is not None
            )
            extra = {"handoff": True} if want_handoff else {}
            with self._lock:
                seq._worker_idx = w.idx
                self._inflight[seq._sid] = seq
                if want_handoff:
                    self._handoffs[seq._sid] = _HandoffRec(
                        seq._sid, seq, w.idx, w.epoch
                    )
            try:
                client.call(
                    "submit",
                    sid=seq._sid,
                    prompt_ids=[int(t) for t in prompt],
                    generated_ids=[int(t) for t in seq.generated_ids],
                    params=params_to_wire(seq.params),
                    remaining_s=remaining,
                    request_id=seq.request_id,
                    traceparent=seq._traceparent,
                    resume_count=seq.resume_count,
                    migrate_count=seq.migrate_count,
                    preempt_count=seq.preempt_count,
                    kv_dtype=seq.kv_dtype,
                    **extra,
                )
                return
            except (WorkerLostError, TimeoutError) as exc:
                # connection-level failure: unregister and try the next
                # worker (the loss machinery handles the dead one)
                last = exc
                with self._lock:
                    self._inflight.pop(seq._sid, None)
                    rec = self._handoffs.pop(seq._sid, None)
                    if rec is not None:
                        rec.cancelled = True
                continue
            except BaseException:
                with self._lock:
                    self._inflight.pop(seq._sid, None)
                    rec = self._handoffs.pop(seq._sid, None)
                    if rec is not None:
                        rec.cancelled = True
                raise
        raise last or WorkerLostError(
            "no engine worker accepted the request; retry shortly",
            retry_after=self.retry_after_s,
        )

    def encode_prompt(self, prompt: str) -> List[int]:
        ids = self.tokenizer.encode(prompt)
        max_prompt = self.config.model.max_model_len - 1
        if len(ids) > max_prompt:
            ids = ids[-max_prompt:]
        return ids or [self.tokenizer.bos_id]

    def submit_prompt(
        self,
        prompt: str,
        params: Any,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        return self.submit_tokens(
            self.encode_prompt(prompt), params, stream_cb, meta=meta
        )

    def generate(
        self, prompts: Seq[str], params: Seq[Any]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API (mirrors EngineCore.generate's shape)."""
        seqs = [self.submit_prompt(p, sp) for p, sp in zip(prompts, params)]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            results.append(
                {
                    "text": self.final_text(seq),
                    "token_ids": list(seq.generated_ids),
                    "num_tokens": seq.num_output_tokens,
                    "prompt_tokens": seq.orig_prompt_len,
                    "finish_reason": seq.finish_reason,
                    "metrics": {
                        "ttft": seq.ttft or 0.0,
                        "tpot": seq.tpot or 0.0,
                        "gen_time": (seq.finish_t or 0.0) - seq.arrival_t,
                        **seq.resume_metrics(),
                    },
                    **(
                        {"logprobs": self.logprob_entries(seq)}
                        if seq.params.logprobs
                        else {}
                    ),
                }
            )
        return results

    # ----------------------------------------------------- result assembly

    def final_text(self, seq: Sequence) -> str:
        if seq.text_override is not None:
            return seq.text_override
        return self.tokenizer.decode(seq.generated_ids)

    def lp_entry(self, tid: int, lp: float, top) -> Dict[str, Any]:
        return {
            "token": self.tokenizer.decode([tid]),
            "token_id": tid,
            "logprob": lp,
            "top_logprobs": [
                {
                    "token": self.tokenizer.decode([i]),
                    "token_id": i,
                    "logprob": l,
                }
                for i, l in top
            ],
        }

    def logprob_entries(self, seq: Sequence) -> List[Dict[str, Any]]:
        return [
            self.lp_entry(tid, lp, top)
            for tid, (lp, top) in zip(seq.generated_ids, seq.logprob_data)
        ]

    # -------------------------------------------------------------- aborts

    def _abort_remote(self, seq: _PodSequence, reason: str) -> None:
        with self._lock:
            if seq._sid not in self._inflight:
                return
            w = (
                self.workers[seq._worker_idx]
                if 0 <= seq._worker_idx < len(self.workers)
                else None
            )
            client = w.client if w is not None and w.alive else None
        if client is None:
            return
        try:
            client.notify("abort", sid=seq._sid, reason=reason)
        except WorkerLostError:
            pass  # loss path owns the sequence from here

    def abort_in_flight(self, reason: str = "drain") -> None:
        for w in self._alive_workers():
            client = w.client
            if client is None:
                continue
            try:
                client.notify("abort_all", reason=reason)
            except WorkerLostError:
                pass

    def set_spec_suspended(self, flag: bool) -> None:
        self._broadcast("set_spec_suspended", flag=bool(flag))

    def set_prefix_insert_suspended(self, flag: bool) -> None:
        self._broadcast("set_prefix_insert_suspended", flag=bool(flag))

    def _broadcast(self, op: str, **fields: Any) -> None:
        # ALL workers, draining included (dp fans brownout toggles out
        # the same way — a draining replica still decodes residents)
        for w in list(self.workers):
            client = w.client
            if client is None or client.dead:
                continue
            try:
                client.notify(op, **fields)
            except WorkerLostError:
                pass

    # ----------------------------------------------------------- liveness

    def _monitor_loop(self) -> None:
        pod = self._pod_cfg
        rec = self._recovery
        while not self._stopping:
            time.sleep(pod.heartbeat_interval_s)
            for w in list(self.workers):
                if self._stopping:
                    return
                if not w.alive:
                    continue
                # crash detection beats the heartbeat timeout: a dead
                # pid is a fact, not a suspicion
                if w.proc is not None and w.proc.poll() is not None:
                    self._handle_loss(
                        w.idx,
                        w.epoch,
                        "crash",
                        f"worker exited with {w.proc.returncode}",
                    )
                    continue
                client = w.client
                if client is None or client.dead:
                    continue  # loss callback owns it
                try:
                    ping = client.call(
                        "ping", timeout=pod.heartbeat_interval_s * 2
                    )
                    w.last_ping = ping
                    w.last_ok_t = time.monotonic()
                except (WorkerLostError, TimeoutError):
                    pass
                now = time.monotonic()
                # gateway-OBSERVED liveness (how long since this worker
                # last answered a ping), as opposed to the worker's own
                # self-reported engine beat — the gap between the two
                # is exactly what diagnoses a wedged RPC plane
                metrics.POD_HEARTBEAT_AGE.labels(
                    worker=str(w.idx)
                ).set(round(max(0.0, now - w.last_ok_t), 3))
                with self._lock:
                    inflight = sum(
                        1
                        for s in self._inflight.values()
                        if s._worker_idx == w.idx
                    )
                metrics.POD_WORKER_INFLIGHT.labels(
                    worker=str(w.idx)
                ).set(inflight)
                if now - w.last_ok_t > pod.heartbeat_timeout_s:
                    # unresponsive but process alive: the zombie case —
                    # fence it out and replace it; its late frames are
                    # discarded by the epoch check
                    self._handle_loss(
                        w.idx,
                        w.epoch,
                        "heartbeat",
                        f"no ping reply for "
                        f"{now - w.last_ok_t:.1f}s",
                    )
                    continue
                beat = (w.last_ping or {}).get("beat")
                if beat and rec.enabled:
                    verdict = classify_heartbeat(
                        {
                            "t": now - float(beat.get("age_s", 0.0)),
                            "kind": beat.get("kind"),
                            "compiling": beat.get("compiling", False),
                        },
                        now,
                        rec.step_stall_s,
                        rec.compile_grace_s,
                    )
                    if verdict is not None:
                        # the worker's OWN supervisor also sees this
                        # stall and restarts in-process; only declare
                        # the worker lost when the wedge outlives the
                        # cross-process budget too
                        if (
                            verdict["stalled_s"]
                            > pod.heartbeat_timeout_s
                        ):
                            with self._lock:
                                self.total_stalls += 1
                            self._handle_loss(
                                w.idx,
                                w.epoch,
                                "heartbeat",
                                f"engine beat stalled "
                                f"{verdict['stalled_s']:.1f}s in "
                                f"{verdict['phase']}",
                            )

    def _on_lost(self, idx: int, epoch: int, exc: Optional[BaseException]) -> None:
        reason = "eof"
        if exc is not None and not isinstance(exc, ConnectionError):
            reason = "crash"
        self._handle_loss(idx, epoch, reason, str(exc) if exc else "EOF")

    def _handle_loss(
        self, idx: int, epoch: int, reason: str, detail: str
    ) -> None:
        """Declare one worker incarnation lost: fence it, fail over its
        in-flight sequences, start the supervised respawn.  Idempotent
        per incarnation — the epoch check makes late/duplicate loss
        signals (reader EOF racing the monitor) no-ops."""
        with self._lock:
            if self._stopping:
                return
            w = self.workers[idx]
            if w.epoch != epoch or w.state not in ("serving",):
                return  # already handled (or a fenced zombie's echo)
            # bump the epoch NOW: from this instant every frame the old
            # incarnation still emits mis-stamps and is discarded
            w.epoch += 1
            w.state = "down"
            w.last_fatal = f"{reason}: {detail}"
            self.total_failovers += 1
            old_client, w.client = w.client, None
            old_proc, w.proc = w.proc, None
            victims = [
                s for s in self._inflight.values() if s._worker_idx == idx
            ]
            lost_handoffs = 0
            for s in victims:
                self._inflight.pop(s._sid, None)
                # a handoff whose prefill side just died: cancel the
                # record so the transfer thread stands down — the
                # replay below re-prefills on a survivor (budgeted)
                rec = self._handoffs.pop(s._sid, None)
                if rec is not None:
                    rec.cancelled = True
                    self.total_handoff_failed += 1
                    lost_handoffs += 1
            # gateway-synthesized post-mortem (the incarnation can no
            # longer report its own): same shape the monolithic
            # supervisor keeps for /stats → engine.last_crash, with
            # the dead incarnation's last cached flight ticks attached
            cache = self._flight_cache.get(idx)
            self._last_crash = {
                "time": time.time(),
                "error": (
                    f"WorkerLost: worker {idx} (epoch {epoch}) — "
                    f"{reason}: {detail}"
                ),
                "worker": idx,
                "epoch": epoch,
                "ticks": (
                    (cache.get("ticks") or [])[-32:]
                    if cache and cache.get("epoch") == epoch
                    else []
                ),
                "in_flight": [
                    {"sid": s._sid, "request_id": s.request_id}
                    for s in victims
                ],
            }
        for _ in range(lost_handoffs):
            metrics.HANDOFF_TOTAL.labels(outcome="failed").inc()
        metrics.POD_WORKER_LOSSES.labels(reason=reason).inc()
        self._set_alive_gauge()
        logger.error(
            "engine worker lost",
            extra={
                "extra_data": {
                    "worker": idx,
                    "epoch": epoch,
                    "reason": reason,
                    "detail": detail,
                    "inflight": len(victims),
                }
            },
        )
        if old_client is not None:
            if reason == "heartbeat" and not old_client.dead:
                # zombie: keep its connection DRAINING so late frames
                # are observed (and counted as fenced) rather than
                # buffered in the kernel; the process is reaped at
                # stop() — killing it here would also kill the drill's
                # evidence that fencing works
                self._fenced_clients.append(old_client)
            else:
                old_client.close()
        if old_proc is not None:
            if reason == "heartbeat" and self._pod_cfg.transport == "uds":
                self._zombie_procs.append(old_proc)
            else:
                # TCP respawn rebinds the same port; a lingering
                # process would hold it
                self._kill_proc(old_proc)
        for s in victims:
            self._replay(s, exclude=idx, planned=False)
        threading.Thread(
            target=self._respawn_loop,
            args=(idx,),
            daemon=True,
            name=f"vgt-pod-respawn-{idx}",
        ).start()

    def _replay(
        self, seq: _PodSequence, exclude: int, planned: bool
    ) -> None:
        """Fold one orphaned sequence and resubmit it to a survivor —
        dp's ``_redistribute``, cross-process.  ``planned`` movements
        (drain/evacuate) never spend the crash-resume budget."""
        if seq.done_event.is_set():
            return
        with self._lock:
            adopted = seq._sid in self._adopted_sids
            if adopted:
                self._adopted_sids.discard(seq._sid)
                self.total_lost += 1
        if adopted:
            # an adopted SHELL has no prompt on this gateway — it rode
            # a predecessor's crash once already and its worker just
            # died too; fail typed (clients retry with their
            # idempotency key) instead of replaying garbage
            metrics.LOST_SEQUENCES.labels(reason="adopted").inc()
            err = WorkerLostError(
                "adopted in-flight request lost its worker before "
                "finishing; retry with the same Idempotency-Key",
                retry_after=self.retry_after_s,
            )
            seq.fail(err)
            self._notify_adopted_done(seq, result=None, error=str(err))
            return
        if seq.abort_requested:
            # the client already walked away; don't burn a survivor's
            # slots replaying it
            seq.finish("abort")
            return
        if planned:
            seq.prepare_migrate()
        else:
            if seq.resume_count >= self._recovery.max_resume_attempts:
                with self._lock:
                    self.total_lost += 1
                metrics.LOST_SEQUENCES.labels(reason="max_attempts").inc()
                seq.fail(
                    ResumeExhaustedError(
                        f"request rode {seq.resume_count} worker losses "
                        "and still never finished; giving up "
                        "(retryable)",
                        retry_after=self.retry_after_s,
                    )
                )
                return
            seq.prepare_resume()
        try:
            self._dispatch_submit(seq, exclude=exclude)
        except WorkerLostError:
            # no survivor right now: park it — the respawn completion
            # replays orphans, and stop()/budget-exhaustion fails them
            with self._lock:
                self._orphans.append(seq)
            return
        except BaseException as exc:  # noqa: BLE001 — typed refusal
            seq.fail(exc)
            return
        with self._lock:
            if planned:
                self.total_migrated += 1
            else:
                self.total_resumed += 1
        if planned:
            metrics.MIGRATIONS.labels(reason="drain").inc()
        else:
            metrics.RESUMED_SEQUENCES.inc()

    def _drain_orphans(self) -> None:
        with self._lock:
            orphans, self._orphans = self._orphans, []
        for seq in orphans:
            if not seq.done_event.is_set():
                try:
                    self._dispatch_submit(seq)
                except BaseException as exc:  # noqa: BLE001
                    seq.fail(
                        exc
                        if isinstance(exc, WorkerLostError)
                        else WorkerLostError(
                            f"orphan replay failed: {exc}",
                            retry_after=self.retry_after_s,
                        )
                    )

    def _fail_orphans(self, detail: str) -> None:
        with self._lock:
            orphans, self._orphans = self._orphans, []
        for seq in orphans:
            if not seq.done_event.is_set():
                with self._lock:
                    self.total_lost += 1
                metrics.LOST_SEQUENCES.labels(reason="no_replica").inc()
                seq.fail(WorkerLostError(detail))

    def _respawn_loop(self, idx: int) -> None:
        """Supervised respawn with the shared sliding restart budget and
        capped exponential backoff; a respawned worker passes the
        canary gate before it becomes routable."""
        w = self.workers[idx]
        rec = self._recovery
        while not self._stopping:
            now = time.monotonic()
            with self._lock:
                if w.respawning:
                    return
                if restart_budget_remaining(
                    self._restart_times, rec, now
                ) <= 0:
                    w.state = "dead"
                    budget_gone = True
                else:
                    budget_gone = False
                    w.respawning = True
                    self._restart_times.append(now)
                    backoff = min(
                        rec.backoff_cap_s,
                        rec.backoff_base_s
                        * (2 ** len(self._restart_times)),
                    )
            if budget_gone:
                logger.error(
                    "worker respawn budget exhausted",
                    extra={"extra_data": {"worker": idx}},
                )
                if self.state is HealthState.DEAD:
                    self._fail_orphans(
                        "pod is dead: worker respawn budget exhausted"
                    )
                return
            time.sleep(backoff)
            try:
                self._spawn_and_gate(w)
                with self._lock:
                    w.respawning = False
                    self.total_restarts += 1
                metrics.POD_WORKER_RESTARTS.inc()
                logger.warning(
                    "engine worker respawned",
                    extra={
                        "extra_data": {
                            "worker": idx, "epoch": w.epoch,
                        }
                    },
                )
                return
            except BaseException as exc:  # noqa: BLE001 — retry loop
                logger.error(
                    "worker respawn attempt failed",
                    extra={
                        "extra_data": {
                            "worker": idx, "error": str(exc),
                        }
                    },
                )
                with self._lock:
                    w.respawning = False
                if w.proc is not None:
                    self._kill_proc(w.proc)
                if w.client is not None:
                    w.client.close()
                continue

    # ------------------------------------------------------------- health

    @property
    def state(self) -> HealthState:
        alive = sum(1 for w in self.workers if w.alive)
        if alive == 0:
            return HealthState.DEAD
        if alive < len(self.workers) or any(
            w.draining for w in self.workers
        ):
            return HealthState.DEGRADED
        return HealthState.SERVING

    @property
    def retry_after_s(self) -> float:
        rec = self._recovery
        with self._lock:
            n = len(self._restart_times)
        return max(
            1.0, min(rec.backoff_cap_s, rec.backoff_base_s * (2 ** n))
        )

    def _set_alive_gauge(self) -> None:
        alive = sum(1 for w in self.workers if w.alive)
        metrics.POD_WORKERS_ALIVE.set(alive)
        metrics.POD_WORKERS_TOTAL.set(len(self.workers))
        counts = {"prefill": 0, "decode": 0, "mixed": 0}
        for w in self.workers:
            if w.alive:
                counts[self._role(w.idx)] += 1
        for role, n in counts.items():
            metrics.POOL_WORKERS.labels(role=role).set(n)

    def _worker_entry(self, w: _Worker, now: float) -> Dict[str, Any]:
        if w.draining:
            state = "draining"
        elif w.alive:
            state = "serving"
        elif w.state == "dead":
            state = "dead"
        elif w.state in ("spawning",) or w.respawning:
            state = "recovering"
        else:
            with self._lock:
                remaining = restart_budget_remaining(
                    self._restart_times, self._recovery, now
                )
            state = "recovering" if remaining > 0 else "dead"
        entry: Dict[str, Any] = {
            "replica": w.idx,
            "state": state,
            "epoch": w.epoch,
            "role": self._role(w.idx),
            "pid": w.proc.pid if w.proc is not None else None,
        }
        if w.last_fatal:
            entry["last_fatal"] = w.last_fatal
        sig = (w.last_ping or {}).get("pressure") or {}
        if sig:
            entry["queue_depth"] = sig.get("engine_queue_depth", 0)
            entry["running"] = sig.get("running", 0)
        beat = (w.last_ping or {}).get("beat")
        if beat:
            entry["beat_age_s"] = round(float(beat.get("age_s", 0.0)), 3)
            entry["compiling"] = bool(beat.get("compiling", False))
        return entry

    def health(self) -> Dict[str, Any]:
        """The /health engine block — ReplicatedEngine's shape with
        per-WORKER detail (state, epoch, pid, last fatal, beat age) so
        operators see which process is out and which incarnation is
        live."""
        now = time.monotonic()
        state = self.state
        self._set_alive_gauge()
        with self._lock:
            draining = sorted(
                w.idx for w in self.workers if w.draining
            )
            restarts_remaining = restart_budget_remaining(
                self._restart_times, self._recovery, now
            )
        return {
            "state": state.value,
            "alive": state_is_alive(state.value),
            "ready": state_is_ready(state.value),
            "dp": len(self.workers),
            "workers": len(self.workers),
            "replicas_alive": sum(1 for w in self.workers if w.alive),
            "replicas_draining": len(draining),
            "draining": draining,
            "replicas": [
                self._worker_entry(w, now) for w in self.workers
            ],
            "failovers": self.total_failovers,
            "restarts": self.total_restarts,
            "restarts_remaining": restarts_remaining,
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "migrated": self.total_migrated,
            "lost": self.total_lost,
            "quarantined": 0,
            "fenced_frames": self.fenced_frames,
            "handoffs": self._handoff_stats(),
            "adoption": {
                "adopted": self.total_adopted,
                "orphans_found": self.total_orphans_found,
                "orphans_expired": self.total_orphans_expired,
                "adopted_inflight": len(self.adopted_request_ids),
            },
        }

    def device_health(self) -> Dict[str, Any]:
        entries = []
        for w in self.workers:
            dev = dict(w.hello.get("device_health") or {})
            dev["worker"] = w.idx
            dev["alive"] = bool(dev.get("alive", False)) and w.alive
            entries.append(dev)
        return {
            "alive": any(e.get("alive") for e in entries),
            "workers": entries,
        }

    # ---------------------------------------------------------- stats/perf

    def _collect(
        self, op: str, timeout: float = 5.0, **fields: Any
    ) -> List[Dict[str, Any]]:
        out = []
        for w in self._alive_workers():
            client = w.client
            if client is None:
                continue
            try:
                out.append(client.call(op, timeout=timeout, **fields))
            except Exception:  # noqa: BLE001 — introspection best-effort
                continue
        return out

    def get_stats(self) -> Dict[str, Any]:
        per_worker = self._collect("stats")
        agg: Dict[str, Any] = {
            key: sum(int(s.get(key, 0)) for s in per_worker)
            for key in (
                "steps",
                "prefills",
                "decode_tokens",
                "state_rebuilds",
                "kv_pages_total",
                "kv_token_capacity",
            )
        }
        agg["scheduler"] = {}
        if per_worker:
            for key, val in (per_worker[0].get("scheduler") or {}).items():
                if isinstance(val, bool):
                    agg["scheduler"][key] = val
                elif isinstance(val, (int, float)):
                    agg["scheduler"][key] = sum(
                        s.get("scheduler", {}).get(key, 0)
                        for s in per_worker
                    )
                elif isinstance(val, dict):
                    agg["scheduler"][key] = {
                        k2: (
                            sum(
                                s.get("scheduler", {})
                                .get(key, {})
                                .get(k2, 0)
                                for s in per_worker
                            )
                            if isinstance(v2, (int, float))
                            and not isinstance(v2, bool)
                            else v2
                        )
                        for k2, v2 in val.items()
                    }
        agg["model"] = self.spec.name
        agg["dp"] = len(self.workers)
        agg["failover"] = {
            "failovers": self.total_failovers,
            "restarts": self.total_restarts,
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "lost": self.total_lost,
            "replicas_alive": sum(1 for w in self.workers if w.alive),
        }
        agg["migration"] = {
            "migrated": self.total_migrated,
            "draining": sorted(
                w.idx for w in self.workers if w.draining
            ),
            "free_slices": 0,
        }
        perfs = [s["perf"] for s in per_worker if "perf" in s]
        if perfs:
            agg["perf"] = perf_attr.merge_stats(perfs)
        agg["mesh"] = dict(self.mesh.shape)
        agg["load_time_s"] = round(self.load_time_s, 2)
        agg["pod"] = {
            "workers": [
                {
                    "worker": w.idx,
                    "epoch": w.epoch,
                    "state": w.state,
                    "role": self._role(w.idx),
                    "draining": w.draining,
                    "pid": w.proc.pid if w.proc is not None else None,
                }
                for w in self.workers
            ],
            "transport": self._pod_cfg.transport,
            "fenced_frames": self.fenced_frames,
            "inflight": len(self._inflight),
            "orphans": len(self._orphans),
            "roles": list(self._roles),
            "handoffs": self._handoff_stats(),
            "adopted": self.total_adopted,
            "orphans_expired": self.total_orphans_expired,
        }
        crashes = [
            s["last_crash"]
            for s in per_worker
            if isinstance(s.get("last_crash"), dict)
        ]
        with self._lock:
            if self._last_crash is not None:
                crashes.append(self._last_crash)
        if crashes:
            # newest post-mortem wins the top-level slot the monolithic
            # supervisor exposes, so /stats → engine.last_crash reads
            # the same in pod mode (worker-internal engine crashes and
            # gateway-declared worker losses both land here)
            agg["last_crash"] = max(
                crashes, key=lambda c: float(c.get("time") or 0.0)
            )
        agg["replicas"] = per_worker
        return agg

    def pressure_signals(self) -> Dict[str, Any]:
        """Worst-of / summed admission gauges from the cached heartbeat
        payloads (never an extra RPC on the admission path)."""
        ratios = []
        depth = running = 0
        for w in self._alive_workers():
            sig = (w.last_ping or {}).get("pressure") or {}
            if "kv_free_ratio" in sig:
                ratios.append(sig["kv_free_ratio"])
            depth += int(sig.get("engine_queue_depth", 0))
            running += int(sig.get("running", 0))
        out: Dict[str, Any] = {
            "engine_queue_depth": depth,
            "running": running,
        }
        if ratios:
            out["kv_free_ratio"] = min(ratios)
        return out

    def perf_snapshot(self) -> Dict[str, Any]:
        snaps = self._collect("perf")
        merged = perf_attr.merge_snapshots(snaps) if snaps else {}
        # stamp the pod topology + handoff outcome counters onto the
        # merged view: loadlab's per-cell /debug/perf delta then lands
        # worker count and handoff outcomes next to the phase seconds,
        # so a disaggregated sweep row shows how many transfers the
        # cell's tok/s number actually paid for
        stats = self._handoff_stats()
        merged["pod"] = {
            "workers": len(self.workers),
            "workers_alive": len(self._alive_workers()),
            "handoffs": {
                key: stats[key]
                for key in ("completed", "fallback_monolithic", "failed")
            },
        }
        return merged

    @property
    def flight(self) -> _PodFlight:
        """The merged pod flight view — app.py's ``_flight_recorder``
        picks this up exactly like dp's ``_MergedFlight``, so
        /debug/flight and /debug/requests work unchanged in pod mode."""
        return self._flight

    def collect_spans(self) -> List[Dict[str, Any]]:
        """Workers' in-memory span recorders (``spans`` verb, armed by
        ``VGT_MEMTRACE=1`` in the worker env), worker-stamped — the
        gateway's /debug/spans merges these with its own recorder so a
        drill can assert cross-process span parentage from one page."""
        out: List[Dict[str, Any]] = []
        for w in self._alive_workers():
            client = w.client
            if client is None:
                continue
            try:
                reply = client.call("spans")
            except Exception:  # noqa: BLE001 — introspection best-effort
                continue
            for span in reply.get("spans") or []:
                span = dict(span)
                span["worker"] = w.idx
                out.append(span)
        return out

    def pod_debug(self) -> Dict[str, Any]:
        """The /debug/pod payload: live topology (per-worker
        incarnation + liveness detail + in-flight load), the mid-air
        handoff table, and the fencing/orphan counters — one page
        answering "which process is sick and what is in the air"."""
        now = time.monotonic()
        entries = [self._worker_entry(w, now) for w in self.workers]
        with self._lock:
            by_worker: Dict[int, int] = {}
            for s in self._inflight.values():
                by_worker[s._worker_idx] = (
                    by_worker.get(s._worker_idx, 0) + 1
                )
            table = [
                {
                    "sid": rec.sid,
                    "request_id": rec.seq.request_id,
                    "state": rec.state,
                    "prefill": rec.prefill_idx,
                    "prefill_epoch": rec.prefill_epoch,
                    "target": (
                        rec.target_idx if rec.target_idx >= 0 else None
                    ),
                    "pages": rec.pages,
                    "nbytes": rec.nbytes,
                    "attempts": rec.attempts,
                    "age_s": round(now - rec.t0, 3),
                }
                for rec in self._handoffs.values()
            ]
            inflight = len(self._inflight)
            orphans = len(self._orphans)
            fenced = self.fenced_frames
            last_crash = self._last_crash
        for entry in entries:
            entry["inflight"] = by_worker.get(entry["replica"], 0)
        return {
            "workers": entries,
            "transport": self._pod_cfg.transport,
            "roles": list(self._roles),
            "inflight": inflight,
            "orphans": orphans,
            "fenced_frames": fenced,
            "handoffs": {**self._handoff_stats(), "table": table},
            "adoption": {
                "adopted": self.total_adopted,
                "orphans_found": self.total_orphans_found,
                "orphans_expired": self.total_orphans_expired,
                "adopted_inflight": sorted(
                    self.adopted_request_ids.values()
                ),
            },
            "last_crash": last_crash,
        }

    def warmup(self, buckets: Optional[List[int]] = None) -> float:
        return sum(
            float(r.get("seconds", 0.0))
            for r in self._collect(
                "warmup",
                timeout=self._pod_cfg.spawn_timeout_s,
                buckets=buckets,
            )
        )

    # ---------------------------------------------------- admin / topology

    def drain_replica(self, idx: int, timeout: float = 30.0) -> Dict[str, Any]:
        """/admin/replicas drain, per worker: evacuate its residents
        over RPC and replay them onto the other workers as planned
        movements.  A worker dying mid-drain falls back to the loss
        path — same fold, same replay, crash counters instead."""
        if not 0 <= idx < len(self.workers):
            raise MigrationRefusedError(f"no worker {idx}")
        w = self.workers[idx]
        if not w.alive:
            raise MigrationRefusedError(
                f"worker {idx} is not serving (state {w.state!r})"
            )
        if not self._alive_workers(exclude=idx):
            raise MigrationRefusedError(
                "no drain target: every other worker is down or "
                "draining"
            )
        with self._lock:
            w.draining = True
        client = w.client
        try:
            reply = client.call(
                "evacuate", timeout=timeout, reason="drain",
                sids=None, timeout_s=timeout,
            )
        except (WorkerLostError, TimeoutError) as exc:
            # the loss machinery (triggered by the same failure) owns
            # the residents; report the drain as degraded-but-handled
            return {
                "drained": 0,
                "fell_back_to_failover": True,
                "error": str(exc),
            }
        moved = 0
        for entry in reply.get("evacuated") or []:
            with self._lock:
                seq = self._inflight.pop(int(entry["sid"]), None)
            if seq is not None:
                self._replay(seq, exclude=idx, planned=True)
                moved += 1
        metrics.REPLICAS_DRAINING.set(
            sum(1 for x in self.workers if x.draining)
        )
        return {"drained": moved, "worker": idx, "epoch": w.epoch}

    def undrain_replica(self, idx: int) -> Dict[str, Any]:
        if not 0 <= idx < len(self.workers):
            raise MigrationRefusedError(f"no worker {idx}")
        with self._lock:
            self.workers[idx].draining = False
        metrics.REPLICAS_DRAINING.set(
            sum(1 for x in self.workers if x.draining)
        )
        return {"worker": idx, "draining": False}

    def add_replica(self, *args: Any, **kwargs: Any) -> None:
        raise MigrationRefusedError(
            "pod.workers is fixed at boot: worker processes own device "
            "slices assigned at spawn; scale the pod by restarting with "
            "a new pod.workers"
        )

    def remove_replica(self, *args: Any, **kwargs: Any) -> None:
        raise MigrationRefusedError(
            "pod.workers is fixed at boot; drain a worker instead "
            "(POST /admin/replicas/{i}/drain) to take it out of "
            "rotation"
        )

    # ---------------------------------------------------------- lifecycle

    def _kill_proc(self, proc: subprocess.Popen) -> None:
        try:
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        except OSError:
            pass

    def stop(self) -> None:
        self._stopping = True
        with self._lock:
            workers = list(getattr(self, "workers", []))
            fenced = list(self._fenced_clients)
            zombies = list(self._zombie_procs)
            self._fenced_clients.clear()
            self._zombie_procs.clear()
        self._fail_orphans("pod is shutting down")
        for w in workers:
            client = w.client
            if client is not None and not client.dead:
                try:
                    client.call("stop", timeout=2.0)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                client.close()
            w.client = None
            w.state = "down"
            if w.proc is not None:
                self._kill_proc(w.proc)
        for client in fenced:
            client.close()
        for proc in zombies:
            self._kill_proc(proc)
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        try:
            os.unlink(self._config_path)
        except OSError:
            pass
        if self._own_socket_dir:
            try:
                for name in os.listdir(self.socket_dir):
                    try:
                        os.unlink(os.path.join(self.socket_dir, name))
                    except OSError:
                        pass
                os.rmdir(self.socket_dir)
            except OSError:
                pass
