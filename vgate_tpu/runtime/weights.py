"""Checkpoint loading: HF safetensors -> stacked sharded device buffers.

The serving analogue of checkpoint/resume (SURVEY.md section 5.4): the
reference's only persistence is an HF model-cache volume consumed by vLLM;
here weights load directly into the engine's stacked-layer pytree, sharded
per the mesh rules at placement time (safetensors -> jax.device_put per
shard), so a v5e-8 load never materializes a full replica per host.

Name mapping follows the HF `Qwen2ForCausalLM` / `MixtralForCausalLM` /
`BertModel` conventions; torch linear weights are [out, in] and transposed
into the einsum-friendly [in, out] layout used by models/decoder.py.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vgate_tpu.logging_config import get_logger
from vgate_tpu.models.specs import ModelSpec

logger = get_logger(__name__)

Params = Dict[str, Any]
# get(name) -> np.ndarray accessor abstracting safetensors files / state dicts
TensorGetter = Callable[[str], np.ndarray]


def _stack(getter: TensorGetter, template: str, num_layers: int, transpose=False):
    arrs = []
    for i in range(num_layers):
        arr = np.asarray(getter(template.format(i)))
        arrs.append(arr.T if transpose else arr)
    return np.stack(arrs)


def params_from_getter(
    spec: ModelSpec, getter: TensorGetter, dtype=jnp.bfloat16
) -> Params:
    """Assemble the decoder pytree from HF-named tensors (host numpy)."""
    L = spec.num_layers
    pre = "model.layers.{}."
    layers: Dict[str, Any] = {
        "input_norm": _stack(getter, pre + "input_layernorm.weight", L),
        "post_norm": _stack(getter, pre + "post_attention_layernorm.weight", L),
        "q": {"w": _stack(getter, pre + "self_attn.q_proj.weight", L, True)},
        "k": {"w": _stack(getter, pre + "self_attn.k_proj.weight", L, True)},
        "v": {"w": _stack(getter, pre + "self_attn.v_proj.weight", L, True)},
        "o": {"w": _stack(getter, pre + "self_attn.o_proj.weight", L, True)},
    }
    if spec.qkv_bias:
        layers["q"]["b"] = _stack(getter, pre + "self_attn.q_proj.bias", L)
        layers["k"]["b"] = _stack(getter, pre + "self_attn.k_proj.bias", L)
        layers["v"]["b"] = _stack(getter, pre + "self_attn.v_proj.bias", L)
    if spec.ffn_sandwich:
        # Gemma-2 sandwich norms (HF Gemma2ForCausalLM names)
        layers["pre_ffn_norm"] = _stack(
            getter, pre + "pre_feedforward_layernorm.weight", L
        )
        layers["post_ffn_norm"] = _stack(
            getter, pre + "post_feedforward_layernorm.weight", L
        )
    if spec.is_moe:
        E = spec.num_experts
        layers["router"] = _stack(
            getter, pre + "block_sparse_moe.gate.weight", L, True
        )
        def stack_experts(w_name, transpose):
            per_layer = []
            for i in range(L):
                per_expert = [
                    np.asarray(
                        getter(
                            f"model.layers.{i}.block_sparse_moe.experts."
                            f"{e}.{w_name}.weight"
                        )
                    )
                    for e in range(E)
                ]
                stacked = np.stack(
                    [w.T if transpose else w for w in per_expert]
                )
                per_layer.append(stacked)
            return np.stack(per_layer)  # [L, E, ...]

        layers["gate"] = {"w": stack_experts("w1", True)}
        layers["down"] = {"w": stack_experts("w2", True)}
        layers["up"] = {"w": stack_experts("w3", True)}
    else:
        layers["gate"] = {"w": _stack(getter, pre + "mlp.gate_proj.weight", L, True)}
        layers["up"] = {"w": _stack(getter, pre + "mlp.up_proj.weight", L, True)}
        layers["down"] = {"w": _stack(getter, pre + "mlp.down_proj.weight", L, True)}

    params: Params = {
        "embed": np.asarray(getter("model.embed_tokens.weight")),
        "layers": layers,
        "final_norm": np.asarray(getter("model.norm.weight")),
    }
    if not spec.tie_embeddings:
        params["lm_head"] = np.asarray(getter("lm_head.weight")).T
    # Stay on the HOST: leaves are numpy (bf16 via ml_dtypes), so the single
    # device placement happens later at parallel/sharding.shard_params —
    # jax.device_put(np_leaf, NamedSharding) transfers each mesh shard
    # directly, never materializing a full replica in HBM (a 7B bf16
    # replica would OOM a 16 GB v5e chip before sharding could fix it).
    np_dtype = np.dtype(dtype)
    return jax.tree.map(lambda x: np.asarray(x).astype(np_dtype), params)


def params_from_torch_state_dict(
    spec: ModelSpec, state_dict, dtype=jnp.float32
) -> Params:
    """Build params from an in-memory torch state dict (used by the
    parity tests against transformers' reference implementation)."""

    def getter(name: str) -> np.ndarray:
        tensor = state_dict[name]
        return tensor.detach().to("cpu").float().numpy()

    return params_from_getter(spec, getter, dtype)


def safetensors_getter(checkpoint_path: str):
    """Index every ``*.safetensors`` shard under a directory.

    Returns ``(getter, files)`` — the getter resolves an HF tensor name to a
    host numpy array, tolerating an optional model prefix (e.g. ``bert.``)
    in the stored names."""
    from safetensors import safe_open

    files = sorted(
        os.path.join(checkpoint_path, f)
        for f in os.listdir(checkpoint_path)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(
            f"no .safetensors files under {checkpoint_path}"
        )
    handles = [safe_open(f, framework="np") for f in files]
    index: Dict[str, Any] = {}
    for handle in handles:
        for name in handle.keys():
            index[name] = handle
    prefixes = ("", "model.", "bert.")

    def getter(name: str) -> np.ndarray:
        if name not in index:
            for p in prefixes:
                if p + name in index:
                    name = p + name
                    break
            else:
                # e.g. tied-embedding checkpoints omit lm_head
                raise KeyError(f"tensor {name} missing from checkpoint")
        return index[name].get_tensor(name)

    return getter, files


def params_from_safetensors(
    spec: ModelSpec,
    checkpoint_path: str,
    dtype=jnp.bfloat16,
) -> Params:
    """Load from a local directory of ``*.safetensors`` shards.

    Returns HOST numpy leaves; the engine's ``shard_params`` performs the
    one and only device placement with each tensor's NamedSharding."""
    getter, files = safetensors_getter(checkpoint_path)
    params = params_from_getter(spec, getter, dtype)
    logger.info(
        "checkpoint loaded",
        extra={
            "extra_data": {
                "path": checkpoint_path,
                "files": len(files),
                "params_mb": round(
                    sum(
                        x.size * x.dtype.itemsize
                        for x in jax.tree.leaves(params)
                    )
                    / 1e6
                ),
            }
        },
    )
    return params


def load_digests(params: Params) -> dict:
    """Per-shard load-time digests (host numpy, same positional-sum
    formula as the device-side integrity sweep — integrity.py
    host_leaf_digest) logged as load provenance: when a later checksum
    sweep flags a shard, the load-time digest answers "was it already
    wrong on disk, or did HBM flip it?".  The AUTHORITATIVE serving
    baseline is recorded post-placement (post-quantize/shard) by
    EngineIntegrity; these digests describe the host tree as loaded."""
    from vgate_tpu.integrity import digest_summary, host_leaf_digest

    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    digests = {
        jax.tree_util.keystr(p): host_leaf_digest(np.asarray(x))
        for p, x in leaves
    }
    return digest_summary(digests)


def load_or_init_params(
    spec: ModelSpec,
    checkpoint_path: Optional[str],
    dtype=jnp.bfloat16,
    seed: int = 0,
    log_digests: bool = False,
) -> Params:
    """Checkpoint when available, random init otherwise (zero-egress path).

    ``log_digests`` (integrity.enabled callers) logs the per-shard
    load-time digest summary — one full host pass over the tree, paid
    once at load."""
    from vgate_tpu import faults

    faults.check("weight_load", payload=checkpoint_path)
    if checkpoint_path and os.path.isdir(checkpoint_path):
        params = params_from_safetensors(spec, checkpoint_path, dtype)
    else:
        from vgate_tpu.models.decoder import init_params

        logger.warning(
            "no checkpoint found; using random-init weights",
            extra={
                "extra_data": {"model": spec.name, "path": checkpoint_path}
            },
        )
        params = init_params(spec, jax.random.PRNGKey(seed), dtype)
    if log_digests:
        try:
            logger.info(
                "load-time weight digests",
                extra={"extra_data": load_digests(params)},
            )
        except Exception:  # digest provenance must never block a load
            logger.warning("load-time digest pass failed", exc_info=True)
    return params
