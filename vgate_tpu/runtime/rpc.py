"""Length-prefixed JSON frame protocol for the gateway ↔ worker plane.

One frame = an 8-byte header (``>II``: magic, payload length) followed
by a UTF-8 JSON payload.  The magic word catches cross-talk and garbled
streams immediately instead of letting a corrupted length prefix turn
into a multi-gigabyte allocation or a silent desync; the length cap
(``pod.max_frame_bytes``) bounds allocation before any byte of the
payload is read.

Every violation raises :class:`FrameError` — the contract both sides
follow is *typed error then connection teardown, never a hang and never
a resync attempt*: once framing is lost there is no trustworthy record
boundary left on the stream, so the reader closes the socket and the
reconnect/fencing machinery (pod_engine.py / worker.py) takes over.

Fencing epochs ride *inside* the payload (key ``"e"``) rather than the
header so that every verb — control and stream alike — carries one and
the epoch check happens after structural validation: a garbled frame is
a framing violation, a well-formed frame from a dead incarnation is a
fencing violation (:class:`StaleEpochError`), and the two are counted
and handled differently (teardown vs. discard-and-count).

Fault points ``rpc_send`` / ``rpc_recv`` (vgate_tpu/faults.py) probe
every frame in wire mode: ``drop`` discards it, ``garble`` scrambles
the raw bytes (the peer then hits the framing violation path for real),
``delay`` stalls, ``raise`` fails the call site.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional

from vgate_tpu import faults

# "VG16" — changes when the frame layout does, so a version-skewed peer
# fails loudly at the first frame instead of misparsing stream state
MAGIC = 0x56471601
_HEADER = struct.Struct(">II")
HEADER_BYTES = _HEADER.size

DEFAULT_MAX_FRAME_BYTES = 8 * 1024 * 1024


class FrameError(RuntimeError):
    """Structural protocol violation — truncated stream, bad magic,
    oversized or undecodable payload.  The connection that produced it
    is unusable and must be torn down by the caller."""


class StaleEpochError(RuntimeError):
    """A well-formed frame stamped with a fencing epoch other than the
    current incarnation's — a zombie's late frame (gateway side) or a
    stale RPC addressed to a dead incarnation (worker side).  Discarded
    and counted, never acted on."""

    def __init__(self, got: int, want: int) -> None:
        super().__init__(f"stale fencing epoch {got} (current {want})")
        self.got = got
        self.want = want


def _garble(data: bytes) -> bytes:
    """Deterministic byte scramble for the ``garble`` wire fault: flip
    bits across the whole frame (header included) so magic, length, and
    payload are all suspect — exactly what a torn TCP stream looks
    like."""
    return bytes(b ^ 0xA5 for b in data)


def encode_frame(obj: Dict[str, Any], max_frame_bytes: int) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > max_frame_bytes:
        raise FrameError(
            f"outbound frame {len(payload)}B exceeds cap {max_frame_bytes}B"
        )
    return _HEADER.pack(MAGIC, len(payload)) + payload


def send_frame(
    sock: socket.socket,
    obj: Dict[str, Any],
    max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
) -> int:
    """Serialize and send one frame; returns the encoded byte count
    (header + payload) so callers can account wire volume
    (vgt_rpc_bytes) without re-encoding.  NOT thread-safe per socket —
    both pod_engine and worker serialize writers behind a per-connection
    send lock so a token frame can never interleave into a reply
    frame."""
    data = encode_frame(obj, max_frame_bytes)
    if faults.is_active():
        verdict = faults.wire_action("rpc_send", obj.get("op"))
        if verdict == "drop":
            return len(data)
        if verdict == "garble":
            data = _garble(data)
    sock.sendall(data)
    return len(data)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise :class:`FrameError` on EOF /
    truncation.  Socket timeouts propagate as ``socket.timeout`` so the
    caller can distinguish a dead peer (EOF → teardown) from a slow one
    (timeout → its own liveness policy)."""
    chunks = []
    remaining = n
    while remaining > 0:
        chunk = sock.recv(min(remaining, 1 << 16))
        if not chunk:
            raise FrameError(
                f"stream truncated: EOF with {remaining}/{n} bytes unread"
            )
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def decode_payload(payload: bytes) -> Dict[str, Any]:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise FrameError(f"undecodable frame payload: {exc}") from exc
    if not isinstance(obj, dict):
        raise FrameError(
            f"frame payload must be a JSON object, got {type(obj).__name__}"
        )
    return obj


def recv_frame(
    sock: socket.socket,
    max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
    size_cb: Optional[Any] = None,
) -> Optional[Dict[str, Any]]:
    """Read one frame.  Returns the decoded dict, or ``None`` on clean
    EOF at a frame boundary (peer closed deliberately).  Raises
    :class:`FrameError` on any structural violation — the caller must
    tear the connection down, not retry the read.  ``size_cb`` (when
    given) is invoked with the frame's on-wire byte count for telemetry;
    its failures never fail the read."""
    try:
        first = sock.recv(HEADER_BYTES)
    except ConnectionResetError as exc:
        raise FrameError(f"connection reset mid-stream: {exc}") from exc
    if not first:
        return None  # clean EOF between frames
    header = (
        first if len(first) == HEADER_BYTES
        else first + recv_exact(sock, HEADER_BYTES - len(first))
    )
    raw = None
    if faults.is_active():
        verdict = faults.wire_action("rpc_recv")
        if verdict == "garble":
            header = _garble(header)
        elif verdict == "drop":
            raw = "drop"
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(
            f"bad frame magic 0x{magic:08x} (want 0x{MAGIC:08x}) — "
            "stream desynced or peer version-skewed"
        )
    if length > max_frame_bytes:
        raise FrameError(
            f"inbound frame {length}B exceeds cap {max_frame_bytes}B"
        )
    payload = recv_exact(sock, length)
    if raw == "drop":
        # consume the bytes (framing stays intact) but discard the frame
        return recv_frame(sock, max_frame_bytes, size_cb)
    if size_cb is not None:
        try:
            size_cb(HEADER_BYTES + length)
        except Exception:  # noqa: BLE001 — telemetry never fails a read
            pass
    return decode_payload(payload)


def check_epoch(frame: Dict[str, Any], want: int) -> None:
    """Enforce the fencing epoch on a decoded frame.  Frames without an
    ``"e"`` key are structural violations (every verb stamps one);
    frames with the wrong one are fencing violations."""
    got = frame.get("e")
    if not isinstance(got, int):
        raise FrameError(f"frame missing fencing epoch: {frame.get('op')!r}")
    if got != want:
        raise StaleEpochError(got, want)
