"""Data parallelism for serving: replica engines + a least-loaded router.

Decode for independent requests is embarrassingly parallel, so the
TPU-native data-parallel design is **replication, not collectives**: each
``dp`` shard of the device mesh runs its own :class:`EngineCore` (weights
replicated, KV pool and continuous-batching state private) and a router
spreads requests across replicas by load.  Throughput scales with ``dp``
while tp/ep/sp collectives stay *inside* each replica's submesh, riding the
fastest ICI loops (SURVEY.md section 2.2 row 1; the reference exposes no DP
at all — vLLM hides replica management behind external orchestration).

``ReplicatedEngine`` exposes the same surface the backend drives on
``EngineCore`` (submit/generate/warmup/stats/health), so ``dp=1`` and
``dp>1`` are interchangeable behind ``JaxTPUBackend``.

**Replica failover** (recovery.enabled): a replica whose engine died —
fatal crash OR a watchdog-declared stall (the repair thread classifies
each replica's heartbeat like the dp=1 supervisor does) — has its
checkpointed in-flight sequences redistributed to surviving replicas
(recovery.resume_in_flight), so clients see a latency blip instead of
losing every resident request with the replica.  The repair thread then
rebuilds the dead replica in place (weights kept, capped backoff, the
recovery.* restart budget shared across replicas) and ``/health``
reports per-replica state: DEGRADED while n_alive < dp, SERVING once
recovery restores the full complement, DEAD only when no replica can
serve."""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

import jax

from vgate_tpu import faults, metrics
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import VGTConfig, get_config
from vgate_tpu.errors import (
    EngineRecoveringError,
    EngineStalledError,
    PoisonRequestError,
)
from vgate_tpu.logging_config import get_logger
from vgate_tpu.runtime.engine_core import (
    EngineCore,
    rebuild_core,
    replay_into,
)
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.supervisor import (
    HealthState,
    classify_fatal,
    classify_heartbeat,
)

logger = get_logger(__name__)


class _MergedFlight:
    """Read-only view merging the replicas' flight recorders so /debug
    works on dp>1 pods (each replica records independently; entries are
    stamped with their replica index and merged by wall time)."""

    def __init__(self, replicas: List[EngineCore]) -> None:
        self._replicas = replicas

    @property
    def enabled(self) -> bool:
        return any(r.flight.enabled for r in self._replicas)

    def _merged(self, method: str, n: Optional[int]) -> List[Dict[str, Any]]:
        out = []
        for i, core in enumerate(self._replicas):
            for entry in getattr(core.flight, method)():
                entry = dict(entry)
                entry["replica"] = i
                out.append(entry)
        out.sort(key=lambda e: e.get("t") or e.get("arrival_t") or 0.0)
        if n is not None and n >= 0:
            out = out[-n:]
        return out

    def ticks(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("ticks", n)

    def requests(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("requests", n)

    def live_requests(self) -> List[Dict[str, Any]]:
        return self._merged("live_requests", None)

    def find_request(self, ident: str) -> Optional[Dict[str, Any]]:
        # newest attempt wins ACROSS replicas too (a retry may land on
        # a different replica than the failed original)
        best: Optional[Dict[str, Any]] = None
        for i, core in enumerate(self._replicas):
            record = core.flight.find_request(ident)
            if record is None:
                continue
            record = dict(record)
            record["replica"] = i
            if best is None or (record.get("arrival_t") or 0.0) > (
                best.get("arrival_t") or 0.0
            ):
                best = record
        return best

    def get_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "replicas": [r.flight.get_stats() for r in self._replicas],
        }


class ReplicatedEngine:
    """``dp`` EngineCore replicas over disjoint submeshes + a load router."""

    def __init__(
        self,
        config: Optional[VGTConfig] = None,
        devices: Optional[list] = None,
    ) -> None:
        self.config = config or get_config()
        dp = max(1, self.config.tpu.dp)
        devices = list(devices if devices is not None else jax.devices())
        limit = self.config.tpu.num_devices
        if limit and limit < len(devices):
            devices = devices[:limit]
        if len(devices) % dp:
            raise ValueError(
                f"{len(devices)} devices not divisible by dp={dp}"
            )
        per = len(devices) // dp
        # each replica sees a dp=1 copy of the config; its submesh carries
        # the remaining ep/sp/tp axes
        replica_cfg = self.config.model_copy(deep=True)
        replica_cfg.tpu.dp = 1
        replica_cfg.tpu.num_devices = per
        self._replica_cfg = replica_cfg
        self._device_slices = [
            devices[i * per : (i + 1) * per] for i in range(dp)
        ]
        self.replicas: List[EngineCore] = [
            EngineCore(replica_cfg, devices=self._device_slices[i])
            for i in range(dp)
        ]
        self._rr = itertools.count()
        self._route_lock = threading.Lock()
        # ---- replica failover / repair (recovery.enabled) ----
        self._recovery = self.config.recovery
        self._failover_enabled = bool(self._recovery.enabled)
        self._stopping = False
        self._repair_event = threading.Event()
        self._repair_thread: Optional[threading.Thread] = None
        # rebuild backoff: replica idx -> next attempt monotonic time;
        # the restart budget window is SHARED across replicas (a pod
        # crash-looping any subset of its replicas is one sick pod)
        self._next_attempt: Dict[int, float] = {}
        self._restart_times: List[float] = []
        # replicas with a rebuild thread in flight: EngineCore
        # construction takes tens of seconds on real hardware, and
        # running it inline in _sweep would block stall detection and
        # failover for every OTHER replica that long.  stop() joins
        # these before stopping replicas, or a rebuild finishing after
        # shutdown would start() an engine nothing owns.
        self._rebuilding: set = set()
        self._rebuild_threads: Dict[int, threading.Thread] = {}
        # poison quarantine, pod-wide (the dp=1 supervisor's, minus the
        # repeat-offender streak — max_resume_attempts bounds replays
        # here): a fingerprint a poison-classified replica fatal names
        # (or its residents, when unnamed) is excluded from failover
        # redistribution AND rejected at submission, so one
        # crash-inducing request cannot serially kill healthy replicas
        self._quarantine: set = set()
        self.total_failovers = 0
        self.total_restarts = 0
        self.total_stalls = 0
        self.total_resumed = 0
        self.total_lost = 0
        if self._failover_enabled:
            for i, core in enumerate(self.replicas):
                self._attach(i, core)
        metrics.DP_REPLICAS_TOTAL.set(dp)
        metrics.DP_REPLICAS_ALIVE.set(dp)
        # /debug surface parity with dp=1: one merged recorder view
        self.flight = _MergedFlight(self.replicas)
        # convenience aliases: identical across replicas
        lead = self.replicas[0]
        self.spec = lead.spec
        self.tokenizer = lead.tokenizer
        self.geometry = lead.geometry
        self.mesh = lead.mesh
        self.load_time_s = sum(r.load_time_s for r in self.replicas)
        logger.info(
            "replicated engine ready",
            extra={
                "extra_data": {
                    "dp": dp,
                    "devices_per_replica": per,
                    "model": lead.spec.name,
                }
            },
        )

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        for core in self.replicas:
            core.start()
        if self._failover_enabled and self._repair_thread is None:
            self._repair_thread = threading.Thread(
                target=self._repair_loop,
                name="vgt-dp-repair",
                daemon=True,
            )
            self._repair_thread.start()

    def stop(self) -> None:
        self._stopping = True
        self._repair_event.set()
        if self._repair_thread is not None:
            self._repair_thread.join(timeout=30)
            self._repair_thread = None
        # settle in-flight rebuilds BEFORE stopping replicas: a rebuild
        # finishing after the sweep below would start() a fresh engine
        # (and its HBM KV pool) that nothing ever stops
        for thread in list(self._rebuild_threads.values()):
            thread.join(timeout=30)
        for core in self.replicas:
            core.stop()

    # --------------------------------------------------- failover / repair

    def _attach(self, idx: int, core: EngineCore) -> None:
        # on_fatal makes the core CHECKPOINT its residents at a fatal
        # (resume_in_flight) instead of failing them raw — the repair
        # thread redistributes them to surviving replicas.  The hook
        # runs on the dying replica's engine thread (or the repair
        # thread itself for watchdog stalls), so it only signals.
        core.on_fatal = lambda exc, i=idx: self._on_replica_fatal(i, exc)

    def _on_replica_fatal(self, idx: int, exc: BaseException) -> None:
        logger.error(
            "dp replica engine fatal",
            extra={
                "extra_data": {
                    "replica": idx,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            },
        )
        self._repair_event.set()

    def _repair_loop(self) -> None:
        while not self._stopping:
            self._repair_event.wait(timeout=0.25)
            self._repair_event.clear()
            if self._stopping:
                return
            try:
                self._sweep()
            except Exception:  # pragma: no cover - defensive
                logger.error("dp repair sweep failed", exc_info=True)

    def _sweep(self) -> None:
        """One repair pass: declare stalled replicas (hang watchdog,
        same heartbeat classification as the dp=1 supervisor),
        redistribute dead replicas' checkpointed residents to
        survivors, and rebuild dead replicas once their backoff is
        due."""
        rec = self._recovery
        for i in range(len(self.replicas)):
            # fresh clock per replica: heartbeat verdicts and backoff
            # stamps must not age by however long earlier replicas'
            # handling took
            now = time.monotonic()
            core = self.replicas[i]
            if i in self._rebuilding:
                continue  # a rebuild thread owns this slot
            if core._fatal is None:
                if core._running and rec.step_stall_s > 0:
                    verdict = classify_heartbeat(
                        getattr(core, "_heartbeat", None),
                        now,
                        rec.step_stall_s,
                        rec.compile_grace_s,
                    )
                    if verdict is not None:
                        exc = EngineStalledError(
                            f"dp replica {i} heartbeat stale for "
                            f"{verdict['stalled_s']:.1f}s (limit "
                            f"{verdict['limit_s']:.1f}s) at phase "
                            f"{verdict['phase']!r}",
                            stalled_s=verdict["stalled_s"],
                            phase=verdict["phase"],
                        )
                        logger.error(
                            "dp replica stall detected",
                            extra={
                                "extra_data": {
                                    "replica": i, **verdict,
                                }
                            },
                        )
                        if core.declare_stalled(exc):
                            self.total_stalls += 1
                            metrics.ENGINE_STALLS.inc()
                continue
            if not core._containment_done:
                # _fatal publishes before the checkpoint sweep
                # finishes: acting now would take an empty checkpoint
                # and the rebuild's old.stop() would then claim the
                # late-published sequences as shutdown-lost.  Skip this
                # pass; containment's final act is on_fatal, which
                # re-fires the repair event (no spin).
                continue
            # dead replica: classify for the poison quarantine, move
            # its checkpointed residents (they complete on survivors
            # while the rebuild happens), then rebuild when the
            # backoff comes due
            self._update_quarantine(core)
            pending = core.take_checkpointed()
            self.total_lost += core.take_resume_losses()
            if pending:
                self._redistribute(i, pending)
            self._maybe_rebuild(i, now)
        metrics.DP_REPLICAS_ALIVE.set(
            sum(1 for c in self.replicas if self._alive(c))
        )

    def _update_quarantine(self, core: EngineCore) -> None:
        """Quarantine what a poison-classified replica fatal implicates
        (idempotent per fatal — the fingerprint set dedupes): the named
        victim when the fault carries one, every resident otherwise —
        the dp=1 supervisor's poison path, minus the repeat-offender
        streak (max_resume_attempts bounds automatic replays here)."""
        exc = core._fatal
        if exc is None or classify_fatal(exc) != "poison":
            return
        named = getattr(exc, "fingerprint", None)
        suspects = (
            [named] if named else [fp for fp, _ in core._fatal_suspects]
        )
        for fp in suspects:
            if fp and fp not in self._quarantine:
                self._quarantine.add(fp)
                metrics.QUARANTINED_REQUESTS.inc()
                logger.error(
                    "request quarantined as dp replica poison",
                    extra={"extra_data": {"fingerprint": fp}},
                )

    def _redistribute(
        self, dead_idx: int, pending: List[Sequence]
    ) -> None:
        """Failover: hand a dead replica's checkpointed sequences to the
        least-loaded SURVIVING replicas (prepare_resume already folded
        each partial generation, so they re-admit as prefill-continues
        with their original deadlines).  Quarantined fingerprints are
        excluded (replay_into) — replaying the request that killed this
        replica would serially kill the survivors.  With no survivor
        the client gets the retryable 503 — the rebuild path cannot be
        waited on without holding futures hostage to a possibly-
        exhausted budget."""
        moved = 0
        # submissions land in the target's _submit_q, which _load
        # cannot see until its engine thread drains it — account for
        # them here or every sequence would pile onto the same
        # "least-loaded" survivor
        extra: Dict[int, int] = {}
        for seq in pending:
            alive = [
                c for c in self.replicas
                if self._alive(c) and c is not self.replicas[dead_idx]
            ]
            if not alive:
                self.total_lost += 1
                metrics.LOST_SEQUENCES.labels(reason="no_replica").inc()
                seq.fail(
                    EngineRecoveringError(
                        "every dp replica is down; retry shortly",
                        retry_after=self.retry_after_s,
                    )
                )
                continue
            target = min(
                alive,
                key=lambda c: self._load(c) + extra.get(id(c), 0),
            )
            outcome = replay_into(
                target, seq, self._quarantine,
                retry_after=self.retry_after_s,
                from_replica=dead_idx,
            )
            if outcome != "replayed":
                self.total_lost += 1
                continue
            extra[id(target)] = extra.get(id(target), 0) + 1
            moved += 1
            self.total_resumed += 1
        if moved:
            self.total_failovers += 1
            logger.warning(
                "dp failover: redistributed dead replica's residents",
                extra={
                    "extra_data": {
                        "replica": dead_idx,
                        "checkpointed": len(pending),
                        "moved": moved,
                    }
                },
            )

    def _backoff(self) -> float:
        """Capped exponential backoff from the shared restart history —
        the one formula behind rebuild scheduling AND the Retry-After
        hint (retry_after_s), so they cannot diverge."""
        rec = self._recovery
        return min(
            rec.backoff_cap_s,
            rec.backoff_base_s * (2 ** len(self._restart_times)),
        )

    def _maybe_rebuild(self, idx: int, now: float) -> None:
        rec = self._recovery
        self._restart_times = [
            t for t in self._restart_times
            if now - t < rec.restart_window_s
        ]
        if len(self._restart_times) >= rec.max_restarts:
            return  # budget exhausted; retried once the window slides
        due = self._next_attempt.get(idx)
        if due is None:
            # first detection: schedule the rebuild after backoff
            self._next_attempt[idx] = now + self._backoff()
            self._repair_event.set()  # re-sweep promptly
            return
        if now < due:
            return
        self._restart_times.append(now)
        # rebuild OFF the sweep thread: construction blocks for tens of
        # seconds on real hardware (KV-pool sizing, mesh setup —
        # potentially minutes when the device itself is sick), and the
        # single repair thread must keep watching the OTHER replicas'
        # heartbeats and failovers meanwhile.  _rebuilding guards the
        # slot; the checkpoint was already redistributed above.
        self._rebuilding.add(idx)
        thread = threading.Thread(
            target=self._do_rebuild,
            args=(idx,),
            name=f"vgt-dp-rebuild-{idx}",
            daemon=True,
        )
        self._rebuild_threads[idx] = thread
        thread.start()

    def _do_rebuild(self, idx: int) -> None:
        try:
            try:
                # shared teardown/rebuild sequence (engine_core.
                # rebuild_core): stop, free the dead incarnation's
                # device KV pool before the new one sizes, weights
                # kept, brownout spec-suspension carried over
                new_core = rebuild_core(
                    self.replicas[idx],
                    self._replica_cfg,
                    self._device_slices[idx],
                )
            except Exception:
                logger.error(
                    "dp replica rebuild attempt failed",
                    extra={"extra_data": {"replica": idx}},
                    exc_info=True,
                )
                self._next_attempt[idx] = (
                    time.monotonic() + self._backoff()
                )
                return
            self._attach(idx, new_core)
            self.replicas[idx] = new_core
            self._next_attempt.pop(idx, None)
            if self._stopping:
                new_core.stop()
                return
            new_core.start()
            if self._stopping:
                # stop() raced the start (its join timed out): never
                # leave an engine running that shutdown already swept
                new_core.stop()
                return
            self.total_restarts += 1
            metrics.ENGINE_RESTARTS.inc()
            logger.warning(
                "dp replica rebuilt",
                extra={"extra_data": {"replica": idx}},
            )
        finally:
            self._rebuilding.discard(idx)
            self._rebuild_threads.pop(idx, None)
            self._repair_event.set()  # re-sweep with the fresh state

    def abort_in_flight(self, reason: str = "drain") -> None:
        """Graceful-drain straggler sweep: fan the abort out to every
        replica (without this, dp>1 pods would drop their in-flight
        responses at drain timeout instead of settling them)."""
        for core in self.replicas:
            if self._alive(core):
                core.abort_in_flight(reason)

    def set_spec_suspended(self, flag: bool) -> None:
        """Brownout L3 fan-out: every replica suspends/resumes
        speculative decoding together (dead replicas included — the
        flag is a plain bool store, and a replica revived later must
        not come back drafting under the load being shed)."""
        for core in self.replicas:
            core.set_spec_suspended(flag)

    def set_prefix_insert_suspended(self, flag: bool) -> None:
        """Brownout L4 fan-out: every replica stops/resumes prefix-tree
        inserts together (dead replicas included, same rationale as the
        spec-suspension fan-out)."""
        for core in self.replicas:
            core.set_prefix_insert_suspended(flag)

    def pressure_signals(self) -> Dict[str, Any]:
        """Admission/brownout gauges aggregated across replicas: the
        WORST KV free ratio (one full replica is where new work lands
        when routing prefers prefix affinity) and summed queue depth."""
        ratios = []
        depth = running = 0
        for core in self.replicas:
            if not self._alive(core):
                continue
            sig = core.pressure_signals()
            if "kv_free_ratio" in sig:
                ratios.append(sig["kv_free_ratio"])
            depth += sig.get("engine_queue_depth", 0)
            running += sig.get("running", 0)
        out: Dict[str, Any] = {
            "engine_queue_depth": depth, "running": running,
        }
        if ratios:
            out["kv_free_ratio"] = min(ratios)
        return out

    # ----------------------------------------------------------- health

    @property
    def state(self) -> HealthState:
        """Pod-level health: SERVING with the full replica complement,
        DEGRADED while any replica is down (survivors still serve —
        readiness stays green), DEAD only when no replica can accept
        work (liveness then recycles the pod)."""
        alive = sum(1 for c in self.replicas if self._alive(c))
        if alive == 0:
            return HealthState.DEAD
        if alive < len(self.replicas):
            return HealthState.DEGRADED
        return HealthState.SERVING

    def _replica_state(self, idx: int, now: float) -> str:
        core = self.replicas[idx]
        if self._alive(core):
            return "serving"
        if not self._failover_enabled:
            return "dead"
        window = [
            t for t in self._restart_times
            if now - t < self._recovery.restart_window_s
        ]
        if len(window) >= self._recovery.max_restarts:
            return "dead"  # budget exhausted until the window slides
        return "recovering"

    def health(self) -> Dict[str, Any]:
        """The /health engine block for dp>1 pods: pod state machine
        position plus per-replica detail (state, last fatal, queue
        depth) so operators see WHICH replica is out, not just that
        one is."""
        from vgate_tpu.errors import state_is_alive, state_is_ready

        now = time.monotonic()
        state = self.state
        replicas = []
        for i, core in enumerate(self.replicas):
            entry: Dict[str, Any] = {
                "replica": i,
                "state": self._replica_state(i, now),
            }
            fatal = core._fatal
            if fatal is not None:
                entry["last_fatal"] = (
                    f"{type(fatal).__name__}: {fatal}"
                )
            try:
                sched = core.scheduler.get_stats()
                entry["queue_depth"] = sched["waiting"]
                entry["running"] = sched["running"]
            except Exception:  # pragma: no cover - mid-rebuild
                pass
            replicas.append(entry)
        alive = sum(1 for r in replicas if r["state"] == "serving")
        metrics.DP_REPLICAS_ALIVE.set(alive)
        return {
            "state": state.value,
            "alive": state_is_alive(state.value),
            "ready": state_is_ready(state.value),
            "dp": len(self.replicas),
            "replicas_alive": alive,
            "replicas": replicas,
            "failovers": self.total_failovers,
            "restarts": self.total_restarts,
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "lost": self.total_lost,
            "quarantined": len(self._quarantine),
        }

    @property
    def retry_after_s(self) -> float:
        """Client backoff suggestion while degraded (the batcher reads
        this off the backend core for its 503s, like the supervisor's)."""
        return max(1.0, self._backoff())

    # ------------------------------------------------------------ routing

    @staticmethod
    def _load(core: EngineCore) -> int:
        return len(core.scheduler.waiting) + len(core.scheduler.running)

    @staticmethod
    def _alive(core: EngineCore) -> bool:
        return core._fatal is None

    def _pick_replica(
        self, prompt_ids: Optional[List[int]] = None
    ) -> EngineCore:
        """Least-loaded replica (queued + resident sequences), round-robin
        on ties so idle replicas fill evenly — with **prefix affinity**:
        each replica's KV prefix cache is private, so requests sharing a
        first prompt page stick to the same replica (cache hits) unless
        that replica is meaningfully more loaded than the best one.

        Failure containment (SURVEY 5.3): a replica whose engine thread
        died (engine-fatal) is routed AROUND — in-flight sequences on it
        fail, but new requests ride the surviving replicas.  Only when
        every replica is dead does the submit surface the fatal."""
        with self._route_lock:
            offset = next(self._rr)
            n = len(self.replicas)
            order = [self.replicas[(offset + i) % n] for i in range(n)]
            alive = [c for c in order if self._alive(c)]
            if not alive:
                # all dead: let EngineCore.submit_tokens raise the fatal
                return order[0]
            best = min(alive, key=self._load)
            page = self.config.tpu.kv_page_size
            if (
                prompt_ids is not None
                and len(prompt_ids) >= page
                and self.replicas[0].prefix_cache_enabled
            ):
                import zlib

                block = bytes(
                    b for t in prompt_ids[:page] for b in t.to_bytes(4, "little")
                )
                sticky = self.replicas[zlib.crc32(block) % n]
                # affinity wins unless it costs real queueing headroom
                # (or the sticky replica is dead)
                if self._alive(sticky) and self._load(sticky) <= self._load(
                    best
                ) + max(2, self.config.tpu.max_batch_slots // 4):
                    return sticky
            return best

    def _gate(self, prompt_ids: List[int]) -> None:
        """Reject quarantined prompts at the door (the supervisor's
        gate, pod-wide): a request a poison-classified replica fatal
        implicated must not be given a fresh replica to kill.  Steady
        state (empty quarantine) skips the O(prompt) fingerprint."""
        if not self._quarantine:
            return
        fp = faults.fingerprint(prompt_ids)
        if fp in self._quarantine:
            raise PoisonRequestError(
                f"request {fp} is quarantined: a poison fault on a dp "
                "replica named it and it will not be admitted again"
            )

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        ids = list(prompt_ids)
        self._gate(ids)
        return self._pick_replica(ids).submit_tokens(
            prompt_ids, params, stream_cb, meta=meta
        )

    def submit_prompt(
        self,
        prompt: str,
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        ids = self.tokenizer.encode(prompt)
        max_prompt = self.config.model.max_model_len - 1
        if len(ids) > max_prompt:
            ids = ids[-max_prompt:]
        ids = ids or [self.tokenizer.bos_id]
        self._gate(ids)
        return self._pick_replica(ids).submit_tokens(
            ids, params, stream_cb, meta=meta
        )

    def generate(
        self, prompts: Seq[str], params: Seq[SamplingParams]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API: requests spread across replicas and decode
        concurrently (mirrors EngineCore.generate's result shape)."""
        seqs = [
            self.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            gen_time = (seq.finish_t or 0) - seq.arrival_t
            results.append(
                {
                    "text": self.final_text(seq),
                    "token_ids": list(seq.generated_ids),
                    "num_tokens": seq.num_output_tokens,
                    "prompt_tokens": seq.orig_prompt_len,
                    "finish_reason": seq.finish_reason,
                    "metrics": {
                        "ttft": seq.ttft or 0.0,
                        "tpot": seq.tpot or 0.0,
                        "gen_time": gen_time,
                        **seq.resume_metrics(),
                    },
                }
            )
        return results

    def final_text(self, seq: Sequence) -> str:
        if seq.text_override is not None:
            return seq.text_override
        return self.tokenizer.decode(seq.generated_ids)

    # ------------------------------------------------------------- utilities

    def warmup(self, buckets: Optional[List[int]] = None) -> float:
        return sum(core.warmup(buckets) for core in self.replicas)

    def capture_profile(
        self, duration_s: float = 1.0, out_dir: Optional[str] = None
    ) -> Dict[str, Any]:
        """jax.profiler traces are process-wide; one capture covers all
        replicas (they share the process and its device set)."""
        return self.replicas[0].capture_profile(duration_s, out_dir)

    def device_health(self) -> Dict[str, Any]:
        healths = [core.device_health() for core in self.replicas]
        alive = [
            h.get("alive", False) and self._alive(core)
            for h, core in zip(healths, self.replicas)
        ]
        # Report platform/device_kind from an ALIVE replica: replica 0
        # may be the dead one, and alive=true must describe a core that
        # can actually serve.  Fall back to healths[0] only when none
        # are alive.
        rep = next(
            (h for h, ok in zip(healths, alive) if ok), healths[0]
        )
        return {
            # serving-capable as long as ANY replica lives (the router
            # steers around dead ones); per-replica detail alongside
            "alive": any(alive),
            "replicas_alive": sum(alive),
            "platform": rep.get("platform"),
            "device_kind": rep.get("device_kind"),
            "num_devices": sum(h.get("num_devices", 0) for h in healths),
            "replicas": len(self.replicas),
        }

    def get_stats(self) -> Dict[str, Any]:
        per_replica = [core.get_stats() for core in self.replicas]
        agg = {
            key: sum(s[key] for s in per_replica)
            for key in (
                "steps",
                "prefills",
                "decode_tokens",
                "state_rebuilds",
                "kv_pages_total",
                "kv_token_capacity",
            )
        }
        agg["scheduler"] = {}
        for key, val in per_replica[0]["scheduler"].items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                agg["scheduler"][key] = sum(
                    s["scheduler"][key] for s in per_replica
                )
            elif isinstance(val, dict):
                # nested stat groups (e.g. prefix_cache): sum the numeric
                # sub-keys so DP deployments keep cache observability
                agg["scheduler"][key] = {
                    k2: (
                        sum(s["scheduler"][key][k2] for s in per_replica)
                        if isinstance(v2, (int, float))
                        and not isinstance(v2, bool)
                        else v2
                    )
                    for k2, v2 in val.items()
                }
        agg["model"] = self.spec.name
        agg["dp"] = len(self.replicas)
        # failover accounting mirrors the dp=1 supervisor block's shape
        agg["failover"] = {
            "failovers": self.total_failovers,
            "restarts": self.total_restarts,
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "lost": self.total_lost,
            "replicas_alive": sum(
                1 for c in self.replicas if self._alive(c)
            ),
        }
        agg["mesh"] = dict(per_replica[0]["mesh"], dp=len(self.replicas))
        agg["load_time_s"] = round(self.load_time_s, 2)
        agg["replicas"] = per_replica
        return agg
