"""Data parallelism for serving: replica engines + a least-loaded router.

Decode for independent requests is embarrassingly parallel, so the
TPU-native data-parallel design is **replication, not collectives**: each
``dp`` shard of the device mesh runs its own :class:`EngineCore` (weights
replicated, KV pool and continuous-batching state private) and a router
spreads requests across replicas by load.  Throughput scales with ``dp``
while tp/ep/sp collectives stay *inside* each replica's submesh, riding the
fastest ICI loops (SURVEY.md section 2.2 row 1; the reference exposes no DP
at all — vLLM hides replica management behind external orchestration).

``ReplicatedEngine`` exposes the same surface the backend drives on
``EngineCore`` (submit/generate/warmup/stats/health), so ``dp=1`` and
``dp>1`` are interchangeable behind ``JaxTPUBackend``.

**Replica failover** (recovery.enabled): a replica whose engine died —
fatal crash OR a watchdog-declared stall (the repair thread classifies
each replica's heartbeat like the dp=1 supervisor does) — has its
checkpointed in-flight sequences redistributed to surviving replicas
(recovery.resume_in_flight), so clients see a latency blip instead of
losing every resident request with the replica.  The repair thread then
rebuilds the dead replica in place (weights kept, capped backoff, the
recovery.* restart budget shared across replicas) and ``/health``
reports per-replica state: DEGRADED while n_alive < dp, SERVING once
recovery restores the full complement, DEAD only when no replica can
serve."""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

import jax

from vgate_tpu import faults, metrics
from vgate_tpu.analysis.annotations import requires_lock
from vgate_tpu.analysis.witness import named_lock
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import VGTConfig, get_config
from vgate_tpu.errors import (
    EngineRecoveringError,
    EngineStalledError,
    IntegrityError,
    MigrationError,
    MigrationRefusedError,
    PoisonRequestError,
)
from vgate_tpu.integrity import CanaryKeeper
from vgate_tpu.logging_config import get_logger
from vgate_tpu.observability import perf as perf_attr
from vgate_tpu.runtime.engine_core import (
    EngineCore,
    rebuild_core,
    replay_into,
)
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.supervisor import (
    HealthState,
    classify_fatal,
    classify_heartbeat,
    restart_budget_remaining,
)

logger = get_logger(__name__)

# Threading contract (scripts/vgt_lint.py, thread-discipline): fleet
# topology mutates only under _topology_lock (the PR-8 review-round
# invariant — structural ops additionally whole-op-serialize on
# _structural_lock, which this registry does not model).
VGT_LOCK_GUARDS = {
    "_draining": "_topology_lock",
    "_free_slices": "_topology_lock",
    "_rebuilding": "_topology_lock",
    "_next_attempt": "_topology_lock",
    "_rebuild_threads": "_topology_lock",
    "replicas": "_topology_lock",
}

# Lock-order contract (vgtlint lock-order checker): the @_structural
# decorator holds _structural_lock around the wrapped body — name
# resolution cannot see through the wrapper closure, so the hold is
# declared here and the structural->topology nesting edge lands in the
# static acquisition graph (declared in analysis/lock_order.py).
VGT_LOCK_WRAPPERS = {
    "_structural": "_structural_lock",
}


class _MergedFlight:
    """View merging the replicas' flight recorders so /debug works on
    dp>1 pods (each replica records independently; entries are stamped
    with their replica index and merged by wall time).  Pod-level
    writers (the batcher's overload tick) land on one live recorder so
    the merged timeline carries them exactly once."""

    def __init__(self, replicas: List[EngineCore]) -> None:
        self._replicas = replicas

    @property
    def enabled(self) -> bool:
        return any(r.flight.enabled for r in self._replicas)

    def record_tick(self, kind: str, **fields: Any) -> None:
        for core in self._replicas:
            if core.flight.enabled:
                core.flight.record_tick(kind, **fields)
                return

    def _merged(self, method: str, n: Optional[int]) -> List[Dict[str, Any]]:
        out = []
        for i, core in enumerate(self._replicas):
            for entry in getattr(core.flight, method)():
                entry = dict(entry)
                entry["replica"] = i
                out.append(entry)
        out.sort(key=lambda e: e.get("t") or e.get("arrival_t") or 0.0)
        if n is not None and n >= 0:
            out = out[-n:]
        return out

    def ticks(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("ticks", n)

    def requests(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("requests", n)

    def live_requests(self) -> List[Dict[str, Any]]:
        return self._merged("live_requests", None)

    def find_request(self, ident: str) -> Optional[Dict[str, Any]]:
        # newest attempt wins ACROSS replicas too (a retry may land on
        # a different replica than the failed original)
        best: Optional[Dict[str, Any]] = None
        for i, core in enumerate(self._replicas):
            record = core.flight.find_request(ident)
            if record is None:
                continue
            record = dict(record)
            record["replica"] = i
            if best is None or (record.get("arrival_t") or 0.0) > (
                best.get("arrival_t") or 0.0
            ):
                best = record
        return best

    def get_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "replicas": [r.flight.get_stats() for r in self._replicas],
        }


class RebalancePolicy:
    """Hysteresis-gated, rate-limited rebalancing decisions (pure
    policy, injectable clock — fake-clock unit-testable without an
    engine).  A replica is **hot** while its ``kv_free_ratio`` /
    ``engine_queue_depth`` pressure signals cross the migration.*
    watermarks; a move is decided only when a replica has been
    CONTINUOUSLY hot for ``rebalance_hold_s`` (one pressured tick is
    admission's job, not migration's), an **idle** sibling exists to
    receive the work, and the last move is at least
    ``rebalance_cooldown_s`` old — so the policy can never thrash a
    sequence back and forth between two busy replicas."""

    def __init__(self, cfg: Any, clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.clock = clock
        # replica idx -> monotonic time it first turned hot; cleared on
        # ANY cool observation (hysteresis: sustained pressure only)
        self._hot_since: Dict[int, float] = {}
        self._last_move_t: Optional[float] = None

    def reset(self) -> None:
        """Topology changed (add/remove/undrain): stale per-index
        hysteresis state must not carry over to a renumbered fleet."""
        self._hot_since.clear()

    def observe(
        self, signals: Dict[int, Dict[str, Any]]
    ) -> Optional[tuple]:
        """One policy tick over {replica_idx: pressure_signals()}.
        Returns ``(hot_idx, cold_idx)`` when a move is due, else None.
        Mutates hysteresis/rate-limit state — call once per interval."""
        now = self.clock()
        cfg = self.cfg
        hot: list = []
        cold: list = []
        for idx, sig in signals.items():
            free = sig.get("kv_free_ratio", 1.0)
            depth = sig.get("engine_queue_depth", 0)
            if (
                free <= cfg.hot_kv_free_ratio
                or depth >= cfg.hot_queue_depth
            ):
                self._hot_since.setdefault(idx, now)
                hot.append((free, idx))
            else:
                self._hot_since.pop(idx, None)
                if free >= cfg.idle_kv_free_ratio and depth == 0:
                    cold.append((free, idx))
        # drop hysteresis state for replicas no longer reporting
        # (dead/draining/removed) so they cannot ripen while absent
        for idx in list(self._hot_since):
            if idx not in signals:
                self._hot_since.pop(idx)
        if not hot or not cold:
            return None
        if (
            self._last_move_t is not None
            and now - self._last_move_t < cfg.rebalance_cooldown_s
        ):
            return None
        ripe = [
            (free, idx)
            for free, idx in hot
            if now - self._hot_since[idx] >= cfg.rebalance_hold_s
        ]
        if not ripe:
            return None
        hot_idx = min(ripe)[1]  # hottest: lowest free ratio
        cold_idx = max(cold)[1]  # coldest: highest free ratio
        self._last_move_t = now
        return hot_idx, cold_idx

    def note_move_failed(self) -> None:
        """The executor moved NOTHING for the decision just issued (no
        eligible victims, kv-dtype mismatch, evacuation failure):
        release the rate-limit stamp so the still-pressured replica is
        re-eligible next tick instead of silently burning a full
        cooldown.  Thrash-safe — nothing moved, so there is nothing to
        ping-pong; retries are bounded by the policy tick interval."""
        self._last_move_t = None


def _structural(fn):
    """Serialize a whole structural op (drain/undrain/add/remove) on
    ``self._structural_lock``.  These ops release ``_topology_lock``
    for the long evacuation/build phase (seconds to minutes on real
    hardware), but decisions keyed on replica indices or the fleet
    size taken BEFORE that phase are reused after it — two concurrent
    removes on dp=2 would otherwise both pass the last-replica guard,
    and a drain's draining-mark could land on a renumbered index.
    Short readers (router, sweep, health, rebalance snapshot) stay on
    ``_topology_lock`` and are never blocked by this."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._structural_lock:
            return fn(self, *args, **kwargs)
    return wrapper


class ReplicatedEngine:
    """``dp`` EngineCore replicas over disjoint submeshes + a load router."""

    def __init__(
        self,
        config: Optional[VGTConfig] = None,
        devices: Optional[list] = None,
    ) -> None:
        self.config = config or get_config()
        dp = max(1, self.config.tpu.dp)
        devices = list(devices if devices is not None else jax.devices())
        limit = self.config.tpu.num_devices
        if limit and limit < len(devices):
            devices = devices[:limit]
        if len(devices) % dp:
            raise ValueError(
                f"{len(devices)} devices not divisible by dp={dp}"
            )
        per = len(devices) // dp
        # each replica sees a dp=1 copy of the config; its submesh carries
        # the remaining ep/sp/tp axes
        replica_cfg = self.config.model_copy(deep=True)
        replica_cfg.tpu.dp = 1
        replica_cfg.tpu.num_devices = per
        self._replica_cfg = replica_cfg
        self._device_slices = [
            devices[i * per : (i + 1) * per] for i in range(dp)
        ]
        self.replicas: List[EngineCore] = [
            EngineCore(replica_cfg, devices=self._device_slices[i])
            for i in range(dp)
        ]
        self._rr = itertools.count()
        self._route_lock = named_lock("ReplicatedEngine._route_lock")
        # ---- replica failover / repair (recovery.enabled) ----
        self._recovery = self.config.recovery
        self._failover_enabled = bool(self._recovery.enabled)
        self._stopping = False
        self._repair_event = threading.Event()
        self._repair_thread: Optional[threading.Thread] = None
        # rebuild backoff: dead core identity -> next attempt monotonic
        # time (identity, not index — elastic dp can renumber replicas
        # while a rebuild is pending); the restart budget window is
        # SHARED across replicas (a pod crash-looping any subset of its
        # replicas is one sick pod)
        self._next_attempt: Dict[int, float] = {}
        self._restart_times: List[float] = []
        # dead-core identities with a rebuild thread in flight:
        # EngineCore construction takes tens of seconds on real
        # hardware, and running it inline in _sweep would block stall
        # detection and failover for every OTHER replica that long.
        # stop() joins these before stopping replicas, or a rebuild
        # finishing after shutdown would start() an engine nothing
        # owns.
        self._rebuilding: set = set()
        self._rebuild_threads: Dict[int, threading.Thread] = {}
        # ---- planned live migration (migration.*) ----
        self._mig = self.config.migration
        # replica indices marked draining: no NEW placements (router
        # skips them); residents were live-migrated to survivors.
        # DEGRADED-with-detail health until undrained or removed.
        self._draining: set = set()
        # structural changes (replicas list, device slices, draining
        # marks) and the repair sweep serialize on this — index-keyed
        # state must never shift under an iterating thread
        self._topology_lock = named_lock(
            "ReplicatedEngine._topology_lock", reentrant=True
        )
        # whole-op serialization for drain/undrain/add/remove (see
        # _structural): held across the evacuation phase that
        # _topology_lock deliberately releases
        self._structural_lock = named_lock(
            "ReplicatedEngine._structural_lock", reentrant=True
        )
        # device slices banked by remove_replica for add_replica to
        # reuse: elastic dp within the boot-time device partition
        self._free_slices: List[list] = []
        self._policy = RebalancePolicy(self._mig)
        self._balance_event = threading.Event()
        self._balance_thread: Optional[threading.Thread] = None
        self.total_migrated = 0
        # poison quarantine, pod-wide (the dp=1 supervisor's, minus the
        # repeat-offender streak — max_resume_attempts bounds replays
        # here): a fingerprint a poison-classified replica fatal names
        # (or its residents, when unnamed) is excluded from failover
        # redistribution AND rejected at submission, so one
        # crash-inducing request cannot serially kill healthy replicas
        self._quarantine: set = set()
        # repeat-offender streaks for sentinel-ATTRIBUTED corrupt
        # fatals (fingerprint -> consecutive trips); see
        # _update_quarantine — the dp twin of the supervisor's
        # transient streak, scoped to attributed sequences only
        self._corrupt_streaks: Dict[str, int] = {}
        # ---- silent-corruption defense (vgate_tpu/integrity.py) ----
        # replica indices quarantined for suspected corruption: routed
        # around (like draining), excluded as failover/migration
        # targets, and unquarantined only by a post-reload canary pass.
        # Renumbered with the fleet (remove_replica), like _draining.
        self._integrity_cfg = self.config.integrity
        self._corrupt: set = set()
        # one pod-wide canary keeper: replicas share weights, so a
        # greedy pinned probe has ONE correct fingerprint — recorded on
        # the first probe, verified everywhere after
        self._canary: Optional[CanaryKeeper] = (
            CanaryKeeper(self._integrity_cfg)
            if self._integrity_cfg.enabled
            and self._integrity_cfg.canary_enabled
            else None
        )
        # slow-timer probe schedule, replica-index keyed; probes run
        # off the repair thread, at most one in flight fleet-wide
        self._next_canary: Dict[int, float] = {}
        self._canary_probe: Optional[threading.Thread] = None
        self.total_corrupt_reloads = 0
        self.total_canary_failures = 0
        self.last_integrity: Optional[Dict[str, Any]] = None
        self.total_failovers = 0
        self.total_restarts = 0
        self.total_stalls = 0
        self.total_resumed = 0
        self.total_lost = 0
        if self._failover_enabled:
            for i, core in enumerate(self.replicas):
                self._attach(i, core)
        metrics.DP_REPLICAS_TOTAL.set(dp)
        metrics.DP_REPLICAS_ALIVE.set(dp)
        # /debug surface parity with dp=1: one merged recorder view
        self.flight = _MergedFlight(self.replicas)
        # convenience aliases: identical across replicas
        lead = self.replicas[0]
        self.spec = lead.spec
        self.tokenizer = lead.tokenizer
        self.geometry = lead.geometry
        self.mesh = lead.mesh
        self.load_time_s = sum(r.load_time_s for r in self.replicas)
        logger.info(
            "replicated engine ready",
            extra={
                "extra_data": {
                    "dp": dp,
                    "devices_per_replica": per,
                    "model": lead.spec.name,
                }
            },
        )

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        for core in self.replicas:
            core.start()
        if (
            self._canary is not None
            and self._integrity_cfg.canary_record_on_start
            and self._canary.expected is None
        ):
            # one boot-time baseline for the fleet (replicas share
            # weights, greedy ⇒ one correct fingerprint): every later
            # gate VERIFIES instead of re-recording — see the dp=1
            # supervisor's twin for why
            self._canary.check(self.replicas[0], context="boot")
        if self._failover_enabled and self._repair_thread is None:
            self._repair_thread = threading.Thread(
                target=self._repair_loop,
                name="vgt-dp-repair",
                daemon=True,
            )
            self._repair_thread.start()
        if (
            self._mig.enabled
            and self._mig.rebalance_enabled
            and self._balance_thread is None
        ):
            self._balance_thread = threading.Thread(
                target=self._balance_loop,
                name="vgt-dp-balance",
                daemon=True,
            )
            self._balance_thread.start()

    def stop(self) -> None:
        self._stopping = True
        self._repair_event.set()
        self._balance_event.set()
        if self._balance_thread is not None:
            self._balance_thread.join(timeout=30)
            self._balance_thread = None
        if self._repair_thread is not None:
            self._repair_thread.join(timeout=30)
            self._repair_thread = None
        # settle in-flight rebuilds BEFORE stopping replicas: a rebuild
        # finishing after the sweep below would start() a fresh engine
        # (and its HBM KV pool) that nothing ever stops
        for thread in list(self._rebuild_threads.values()):
            thread.join(timeout=30)
        for core in self.replicas:
            core.stop()

    # --------------------------------------------------- failover / repair

    def _attach(self, idx: int, core: EngineCore) -> None:
        # on_fatal makes the core CHECKPOINT its residents at a fatal
        # (resume_in_flight) instead of failing them raw — the repair
        # thread redistributes them to surviving replicas.  The hook
        # runs on the dying replica's engine thread (or the repair
        # thread itself for watchdog stalls), so it only signals.
        core.on_fatal = lambda exc, i=idx: self._on_replica_fatal(i, exc)

    def _on_replica_fatal(self, idx: int, exc: BaseException) -> None:
        logger.error(
            "dp replica engine fatal",
            extra={
                "extra_data": {
                    "replica": idx,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            },
        )
        self._repair_event.set()

    def _repair_loop(self) -> None:
        while not self._stopping:
            self._repair_event.wait(timeout=0.25)
            self._repair_event.clear()
            if self._stopping:
                return
            try:
                self._sweep()
            except Exception:  # pragma: no cover - defensive
                logger.error("dp repair sweep failed", exc_info=True)
            try:
                # slow-timer canary probes run OUTSIDE the sweep's
                # topology lock: a greedy probe takes real decode time
                # and must not block structural ops or stall detection
                self._maybe_canaries()
            except Exception:  # pragma: no cover - defensive
                logger.error("dp canary pass failed", exc_info=True)

    def _sweep(self) -> None:
        """One repair pass: declare stalled replicas (hang watchdog,
        same heartbeat classification as the dp=1 supervisor),
        redistribute dead replicas' checkpointed residents to
        survivors, and rebuild dead replicas once their backoff is
        due.  Holds the topology lock: elastic dp (add/remove_replica)
        must never renumber the fleet under this iteration."""
        rec = self._recovery
        with self._topology_lock:
            self._sweep_locked(rec)

    @requires_lock("_topology_lock")
    def _sweep_locked(self, rec) -> None:
        for i in range(len(self.replicas)):
            # fresh clock per replica: heartbeat verdicts and backoff
            # stamps must not age by however long earlier replicas'
            # handling took
            now = time.monotonic()
            core = self.replicas[i]
            if id(core) in self._rebuilding:
                continue  # a rebuild thread owns this slot
            if core._fatal is None:
                if core._running and rec.step_stall_s > 0:
                    verdict = classify_heartbeat(
                        getattr(core, "_heartbeat", None),
                        now,
                        rec.step_stall_s,
                        rec.compile_grace_s,
                    )
                    if verdict is not None:
                        exc = EngineStalledError(
                            f"dp replica {i} heartbeat stale for "
                            f"{verdict['stalled_s']:.1f}s (limit "
                            f"{verdict['limit_s']:.1f}s) at phase "
                            f"{verdict['phase']!r}",
                            stalled_s=verdict["stalled_s"],
                            phase=verdict["phase"],
                        )
                        logger.error(
                            "dp replica stall detected",
                            extra={
                                "extra_data": {
                                    "replica": i, **verdict,
                                }
                            },
                        )
                        if core.declare_stalled(exc):
                            self.total_stalls += 1
                            metrics.ENGINE_STALLS.inc()
                continue
            if not core._containment_done:
                # _fatal publishes before the checkpoint sweep
                # finishes: acting now would take an empty checkpoint
                # and the rebuild's old.stop() would then claim the
                # late-published sequences as shutdown-lost.  Skip this
                # pass; containment's final act is on_fatal, which
                # re-fires the repair event (no spin).
                continue
            # dead replica: classify for the poison quarantine, move
            # its checkpointed residents (they complete on survivors
            # while the rebuild happens), then rebuild when the
            # backoff comes due
            self._update_quarantine(core)
            if (
                self._integrity_cfg.enabled
                and i not in self._corrupt
                and classify_fatal(core._fatal) == "corrupt"
            ):
                # corrupt-classified fatal (sentinel trip / checksum
                # mismatch / canary failure): quarantine the index —
                # routed around, never a failover/migration target —
                # until the post-reload canary passes in _do_rebuild
                self._mark_corrupt(i, core._fatal)
            pending = core.take_checkpointed()
            self.total_lost += core.take_resume_losses()
            if pending:
                self._redistribute(i, pending)
            if i not in self._draining:
                # a draining replica is deliberately leaving (rolling
                # deploy / scale-down): auto-rebuilding it would fight
                # the operator — undrain re-arms repair
                self._maybe_rebuild(i, core, now)
        metrics.DP_REPLICAS_ALIVE.set(
            sum(1 for c in self.replicas if self._alive(c))
        )

    def _update_quarantine(self, core: EngineCore) -> None:
        """Quarantine what a poison-classified replica fatal implicates
        (idempotent per fatal — the fingerprint set dedupes): the named
        victim when the fault carries one, every resident otherwise —
        the dp=1 supervisor's poison path, minus the general transient
        repeat-offender streak (max_resume_attempts bounds automatic
        replays here).  Sentinel-ATTRIBUTED corrupt fatals do run a
        streak (the supervisor's twin): a prompt that deterministically
        NaN-overflows would otherwise corrupt-reload its way through
        every replica — sentinel trip → reload → failover replay /
        client retry → trip again — burning the shared restart budget
        with no containment."""
        exc = core._fatal
        if exc is None:
            return
        kind = classify_fatal(exc)
        if kind == "corrupt":
            attributed = {
                s.get("fingerprint")
                for s in getattr(exc, "sequences", ())
                if s.get("fingerprint")
            }
            threshold = self._recovery.poison_threshold
            new_streaks: Dict[str, int] = {}
            for fp, resume_count in core._fatal_suspects:
                if fp not in attributed:
                    continue
                # replays keep their streak; only fresh submissions
                # (resume_count == 0: the client re-sending the prompt)
                # advance it — the supervisor's transient-streak rule
                count = self._corrupt_streaks.get(fp, 0) + (
                    1 if resume_count == 0 else 0
                )
                if count >= threshold:
                    if fp not in self._quarantine:
                        self._quarantine.add(fp)
                        metrics.QUARANTINED_REQUESTS.inc()
                        logger.error(
                            "request quarantined: repeatedly attributed "
                            "by corrupt-sentinel trips",
                            extra={"extra_data": {
                                "fingerprint": fp, "trips": count,
                            }},
                        )
                elif count > 0:
                    new_streaks[fp] = count
            self._corrupt_streaks = new_streaks
            return
        if kind != "poison":
            return
        named = getattr(exc, "fingerprint", None)
        suspects = (
            [named] if named else [fp for fp, _ in core._fatal_suspects]
        )
        for fp in suspects:
            if fp and fp not in self._quarantine:
                self._quarantine.add(fp)
                metrics.QUARANTINED_REQUESTS.inc()
                logger.error(
                    "request quarantined as dp replica poison",
                    extra={"extra_data": {"fingerprint": fp}},
                )

    def _redistribute(
        self, dead_idx: int, pending: List[Sequence]
    ) -> None:
        """Failover: hand a dead replica's checkpointed sequences to the
        least-loaded SURVIVING replicas (prepare_resume already folded
        each partial generation, so they re-admit as prefill-continues
        with their original deadlines).  Quarantined fingerprints are
        excluded (replay_into) — replaying the request that killed this
        replica would serially kill the survivors.  With no survivor
        the client gets the retryable 503 — the rebuild path cannot be
        waited on without holding futures hostage to a possibly-
        exhausted budget."""
        moved = 0
        # submissions land in the target's _submit_q, which _load
        # cannot see until its engine thread drains it — account for
        # them here or every sequence would pile onto the same
        # "least-loaded" survivor
        extra: Dict[int, int] = {}
        with self._topology_lock:
            dead_core = self.replicas[dead_idx]
        warned_draining = False
        for seq in pending:
            with self._topology_lock:
                eligible = [
                    (j, c) for j, c in enumerate(self.replicas)
                    if self._alive(c)
                    and c is not dead_core
                    # a corrupt-quarantined replica must never receive
                    # failover work — its outputs are suspect until the
                    # post-reload canary passes
                    and j not in self._corrupt
                ]
                draining = set(self._draining)
            # the no-new-placements drain invariant first; but when
            # every survivor is draining, completing the request on
            # one beats failing it — remove_replica re-evacuates, so
            # nothing is lost even if that replica is later torn down
            alive = [c for j, c in eligible if j not in draining]
            if not alive and eligible:
                alive = [c for _, c in eligible]
                if not warned_draining:
                    warned_draining = True
                    logger.warning(
                        "failover placing onto DRAINING replicas: "
                        "no non-draining survivor exists; re-issue "
                        "the drain once the fleet recovers",
                        extra={"extra_data": {
                            "dead_replica": dead_idx,
                            "draining": sorted(draining),
                        }},
                    )
            if not alive:
                self.total_lost += 1
                metrics.LOST_SEQUENCES.labels(reason="no_replica").inc()
                seq.fail(
                    EngineRecoveringError(
                        "every dp replica is down; retry shortly",
                        retry_after=self.retry_after_s,
                    )
                )
                continue
            target = min(
                alive,
                key=lambda c: self._load(c) + extra.get(id(c), 0),
            )
            outcome = replay_into(
                target, seq, self._quarantine,
                retry_after=self.retry_after_s,
                from_replica=dead_idx,
            )
            if outcome != "replayed":
                self.total_lost += 1
                continue
            extra[id(target)] = extra.get(id(target), 0) + 1
            moved += 1
            self.total_resumed += 1
        if moved:
            self.total_failovers += 1
            logger.warning(
                "dp failover: redistributed dead replica's residents",
                extra={
                    "extra_data": {
                        "replica": dead_idx,
                        "checkpointed": len(pending),
                        "moved": moved,
                    }
                },
            )

    def _backoff(self) -> float:
        """Capped exponential backoff from the shared restart history —
        the one formula behind rebuild scheduling AND the Retry-After
        hint (retry_after_s), so they cannot diverge."""
        rec = self._recovery
        return min(
            rec.backoff_cap_s,
            rec.backoff_base_s * (2 ** len(self._restart_times)),
        )

    @requires_lock("_topology_lock")
    def _maybe_rebuild(
        self, idx: int, core: EngineCore, now: float
    ) -> None:
        rec = self._recovery
        self._restart_times = [
            t for t in self._restart_times
            if now - t < rec.restart_window_s
        ]
        if len(self._restart_times) >= rec.max_restarts:
            return  # budget exhausted; retried once the window slides
        due = self._next_attempt.get(id(core))
        if due is None:
            # first detection: schedule the rebuild after backoff
            self._next_attempt[id(core)] = now + self._backoff()
            self._repair_event.set()  # re-sweep promptly
            return
        if now < due:
            return
        self._restart_times.append(now)
        # rebuild OFF the sweep thread: construction blocks for tens of
        # seconds on real hardware (KV-pool sizing, mesh setup —
        # potentially minutes when the device itself is sick), and the
        # single repair thread must keep watching the OTHER replicas'
        # heartbeats and failovers meanwhile.  _rebuilding guards the
        # dead core (by identity — elastic dp can renumber the fleet
        # while this runs); the checkpoint was already redistributed
        # above.  The device slice is captured NOW, under the topology
        # lock, for the same reason.
        self._rebuilding.add(id(core))
        devices = self._device_slices[idx]
        thread = threading.Thread(
            target=self._do_rebuild,
            args=(idx, core, devices),
            name=f"vgt-dp-rebuild-{idx}",
            daemon=True,
        )
        self._rebuild_threads[id(core)] = thread
        thread.start()

    def _do_rebuild(
        self, idx: int, old: EngineCore, devices: list
    ) -> None:
        # reload-on-corrupt: a corrupt-classified fatal must not keep
        # the old tree (the corruption would survive the rebuild); a
        # kept tree is checksum-verified inside rebuild_core and a
        # mismatch escalates this rebuild to a reload too
        reload_weights = (
            self._integrity_cfg.enabled
            and old._fatal is not None
            and classify_fatal(old._fatal) == "corrupt"
        )
        try:
            try:
                # shared teardown/rebuild sequence (engine_core.
                # rebuild_core): stop, free the dead incarnation's
                # device KV pool before the new one sizes, weights
                # kept (verified) or reloaded, brownout
                # spec-suspension carried over
                new_core = rebuild_core(
                    old, self._replica_cfg, devices,
                    reload_weights=reload_weights,
                )
            except IntegrityError:
                logger.error(
                    "dp replica kept-weights rebuild failed checksum "
                    "verification; escalating to weight reload",
                    extra={"extra_data": {"replica": idx}},
                    exc_info=True,
                )
                with self._topology_lock:
                    try:
                        slot = self.replicas.index(old)
                    except ValueError:
                        slot = -1
                    if slot >= 0 and slot not in self._corrupt:
                        self._mark_corrupt(slot, old._fatal)
                try:
                    new_core = rebuild_core(
                        old, self._replica_cfg, devices,
                        reload_weights=True,
                    )
                except Exception:
                    logger.error(
                        "dp replica reload rebuild failed",
                        extra={"extra_data": {"replica": idx}},
                        exc_info=True,
                    )
                    with self._topology_lock:
                        self._next_attempt[id(old)] = (
                            time.monotonic() + self._backoff()
                        )
                    return
                reload_weights = True
            except Exception:
                logger.error(
                    "dp replica rebuild attempt failed",
                    extra={"extra_data": {"replica": idx}},
                    exc_info=True,
                )
                with self._topology_lock:
                    self._next_attempt[id(old)] = (
                        time.monotonic() + self._backoff()
                    )
                return
            with self._topology_lock:
                self._next_attempt.pop(id(old), None)
            # swap by IDENTITY, under the topology lock: the fleet may
            # have been renumbered (remove_replica) while this built —
            # a stale index would overwrite the wrong slot
            with self._topology_lock:
                try:
                    slot = self.replicas.index(old)
                except ValueError:
                    slot = -1  # replica was removed mid-rebuild
                if slot >= 0:
                    self._attach(slot, new_core)
                    self.replicas[slot] = new_core
            if slot < 0 or self._stopping:
                new_core.stop()
                return
            new_core.start()
            if self._stopping:
                # stop() raced the start (its join timed out): never
                # leave an engine running that shutdown already swept
                new_core.stop()
                return
            self.total_restarts += 1
            metrics.ENGINE_RESTARTS.inc()
            if reload_weights:
                # counted per reload REBUILD (not per canary verdict)
                # so /stats integrity.corrupt_reloads tracks the
                # vgt_corrupt_reloads Prometheus counter exactly
                self.total_corrupt_reloads += 1
            if slot in self._corrupt:
                # quarantined rebuild: the replica rejoins the
                # placement rotation ONLY after its canary matches the
                # recorded fingerprint.  A failing canary declares a
                # fresh corrupt fatal on the new incarnation — the
                # sweep then schedules another reload under the shared
                # restart budget, and the quarantine holds meanwhile.
                if self._canary is None:
                    self._clear_corrupt(slot, reason="no_canary")
                else:
                    result = self._canary.check(
                        new_core, context=f"reload:replica{slot}"
                    )
                    self.last_integrity = dict(
                        self.last_integrity or {}, canary=result
                    )
                    if result["ok"]:
                        self._clear_corrupt(slot, reason="canary_pass")
                    else:
                        self.total_canary_failures += 1
                        logger.error(
                            "dp replica post-reload canary FAILED; "
                            "replica stays quarantined and reloads "
                            "again",
                            extra={"extra_data": {
                                "replica": slot, **result,
                            }},
                        )
                        new_core.declare_stalled(
                            IntegrityError(
                                "post-reload canary failed: "
                                + str(
                                    result.get("error")
                                    or "fingerprint mismatch"
                                ),
                                kind="canary",
                            )
                        )
            logger.warning(
                "dp replica rebuilt",
                extra={"extra_data": {
                    "replica": slot,
                    **(
                        {"weights_reloaded": True}
                        if reload_weights
                        else {}
                    ),
                }},
            )
        finally:
            with self._topology_lock:
                self._rebuilding.discard(id(old))
                self._rebuild_threads.pop(id(old), None)
            self._repair_event.set()  # re-sweep with the fresh state

    # ------------------------------- silent-corruption defense helpers

    def _mark_corrupt(self, idx: int, exc: Optional[BaseException]) -> None:
        """Quarantine replica ``idx`` as suspected-corrupt: no routing,
        no failover/migration placements, auto-repair reloads weights.
        Callers hold (or are inside) the topology lock OR pass an index
        they just resolved under it."""
        self._corrupt.add(idx)
        metrics.CORRUPT_QUARANTINED.set(len(self._corrupt))
        self.last_integrity = {
            "replica": idx,
            "cause": (
                f"{type(exc).__name__}: {exc}" if exc is not None else None
            ),
            "kind": getattr(exc, "integrity_kind", "unknown"),
            "time": time.time(),
        }
        logger.error(
            "dp replica quarantined for suspected silent corruption",
            extra={"extra_data": self.last_integrity},
        )

    def _clear_corrupt(self, idx: int, reason: str) -> None:
        self._corrupt.discard(idx)
        metrics.CORRUPT_QUARANTINED.set(len(self._corrupt))
        logger.warning(
            "dp replica corruption quarantine lifted",
            extra={"extra_data": {"replica": idx, "reason": reason}},
        )

    def _maybe_canaries(self) -> None:
        """Slow-timer canary pass (integrity.canary_interval_s > 0):
        probe each healthy in-rotation replica on its own schedule.  A
        failing probe quarantines the replica, live-migrates its
        residents OFF (the planned-evacuation path — suspect cores
        never get replays), and declares the corrupt fatal so the
        repair loop reloads its weights."""
        interval = self._integrity_cfg.canary_interval_s
        if self._canary is None or interval <= 0 or self._stopping:
            return
        if self._canary_probe is not None and self._canary_probe.is_alive():
            return  # at most one probe in flight fleet-wide
        now = time.monotonic()
        with self._topology_lock:
            candidates = [
                (i, c) for i, c in enumerate(self.replicas)
                if self._alive(c)
                and i not in self._draining
                and i not in self._corrupt
                and id(c) not in self._rebuilding
            ]
        for i, core in candidates:
            due = self._next_canary.get(i)
            if due is None:
                # stagger first probes one interval out (boot is
                # already covered by the record-on-first-probe rule)
                self._next_canary[i] = now + interval
                continue
            if now < due:
                continue
            try:
                if core.scheduler.has_work():
                    # busy replica: the sentinels already watch its
                    # every readback, and a probe queued behind live
                    # load would time out and read as corruption —
                    # re-probe at the next interval
                    self._next_canary[i] = now + interval
                    continue
            except Exception:  # pragma: no cover - mid-rebuild
                continue
            self._next_canary[i] = now + interval
            # OFF the repair thread: a probe blocked on a wedged core
            # must not suspend fleet-wide stall detection / rebuild
            # scheduling (the watchdog's job is noticing that wedge)
            self._canary_probe = threading.Thread(
                target=self._run_timer_canary,
                args=(i, core),
                name=f"vgt-dp-canary-{i}",
                daemon=True,
            )
            self._canary_probe.start()
            break

    def _run_timer_canary(self, idx: int, core: EngineCore) -> None:
        result = self._canary.check(core, context=f"timer:replica{idx}")
        if result["ok"]:
            return
        self.total_canary_failures += 1
        self._quarantine_corrupt_live(core, result)

    def _quarantine_corrupt_live(
        self, core: EngineCore, result: Dict[str, Any]
    ) -> None:
        """A LIVE replica failed its canary: quarantine by identity
        (indices may have shifted since the caller snapshotted),
        evacuate its residents via the planned-migration path onto
        healthy siblings, then declare the corrupt fatal for a
        reload rebuild."""
        exc = IntegrityError(
            "canary self-probe failed on a live dp replica: "
            + str(result.get("error") or "fingerprint mismatch"),
            kind="canary",
            detail={k: v for k, v in result.items() if k != "ok"},
        )
        with self._topology_lock:
            try:
                slot = self.replicas.index(core)
            except ValueError:
                return  # removed while we probed
            self._mark_corrupt(slot, exc)
        # PR-8 evacuation path: residents migrate OFF the suspect core
        # as planned movements — never replayed back onto it
        try:
            seqs, kind = self._evacuate_all(core, "corrupt")
        except MigrationError:
            seqs, kind = [], "migrate"  # stuck evacuation: containment
            # will checkpoint the residents when the fatal lands below
        if seqs:
            with self._topology_lock:
                targets = [
                    c for j, c in enumerate(self.replicas)
                    if c is not core
                    and self._alive(c)
                    and j not in self._draining
                    and j not in self._corrupt
                ]
                if not targets:
                    # every clean survivor is draining: placing onto an
                    # alive DRAINING sibling beats 503ing the work (the
                    # file-wide zero-loss-beats-drain-purity rule —
                    # _redistribute and _fallback_targets do the same).
                    # Still NEVER the corrupt source or siblings.
                    targets = [
                        c for j, c in enumerate(self.replicas)
                        if c is not core
                        and self._alive(c)
                        and j not in self._corrupt
                    ]
                    if targets:
                        logger.warning(
                            "corrupt-replica evacuation placing onto "
                            "DRAINING replicas: no clean in-rotation "
                            "survivor exists",
                            extra={"extra_data": {"replica": slot}},
                        )
            moved, lost, _ = self._place(
                seqs, targets, "corrupt", slot, kind=kind
            )
            logger.warning(
                "evacuated residents off corrupt-quarantined replica",
                extra={"extra_data": {
                    "replica": slot, "moved": moved, "lost": lost,
                }},
            )
        core.declare_stalled(exc)

    # ------------------------------------ planned migration / elastic dp

    def _require_migration(self) -> None:
        if not self._mig.enabled:
            raise MigrationRefusedError(
                "live migration is disabled (migration.enabled=false)"
            )

    @staticmethod
    def _kv_dtype_of(core: Any) -> Optional[str]:
        geo = getattr(core, "geometry", None)
        return getattr(geo, "kv_dtype", None)

    def _check_placement(
        self, src_core: Any, targets: List[Any]
    ) -> List[Any]:
        """Placement-time migration gate, applied BEFORE any sequence
        is evacuated: raises the typed MigrationRefusedError when no
        live target can accept the source's checkpoints — either none
        exists, or every candidate serves a different kv_cache.dtype
        than the one the source's generations were sampled under
        (submit_existing would refuse each replay with a 503; refusing
        the whole operation up front moves nothing and loses nothing).
        Returns the eligible targets."""
        alive = [c for c in targets if self._alive(c)]
        if not alive:
            raise MigrationRefusedError(
                "no eligible target replica: every other replica is "
                "dead or draining"
            )
        src = self._kv_dtype_of(src_core)
        ok = [
            c for c in alive
            if src is None
            or self._kv_dtype_of(c) is None
            or self._kv_dtype_of(c) == src
        ]
        if not ok:
            have = sorted(
                {str(self._kv_dtype_of(c)) for c in alive}
            )
            raise MigrationRefusedError(
                f"kv-dtype mismatch: the source replica serves "
                f"kv_cache.dtype={src!r} but every live target serves "
                f"{have}; a generation sampled against one KV storage "
                "format cannot continue against another — refusing at "
                "placement time"
            )
        return ok

    def _place(
        self,
        seqs: List[Sequence],
        targets: List[Any],
        reason: str,
        from_replica: int,
        kind: str = "migrate",
        fallback: Optional[EngineCore] = None,
    ) -> tuple:
        """Replay evacuated sequences onto the least-loaded eligible
        targets (the PR-5 redistribution accounting: in-loop `extra`
        counts submissions _load cannot see yet, so a batch never piles
        onto one survivor).  Per-sequence kv-dtype eligibility is
        re-checked here as the backstop — _check_placement gated the
        operation, but a mixed fleet could lose its last compatible
        target mid-flight.  ``kind`` carries provenance: sequences a
        planned operation claimed from a CRASHED replica were folded by
        prepare_resume, so they replay as resumes (resumed:true,
        vgt_resumed_sequences) — stamping them "migrate" would make
        metrics, flight ticks and response flags disagree.  ``fallback``
        is the alive SOURCE when it stays in the fleet (drain,
        rebalance): a sequence whose every target died between the gate
        and this placement folds back where it was running fine instead
        of 503ing — a planned operation must not turn healthy requests
        into errors.  Returns (moved, lost, requeued)."""
        moved = lost = requeued = 0
        extra: Dict[int, int] = {}
        for seq in seqs:
            eligible = [
                c for c in targets
                if self._alive(c)
                and (
                    seq.kv_dtype is None
                    or self._kv_dtype_of(c) is None
                    or self._kv_dtype_of(c) == seq.kv_dtype
                )
            ]
            if not eligible and fallback is not None and self._alive(
                fallback
            ):
                try:
                    fallback.submit_existing(seq)
                    requeued += 1
                    continue
                except (RuntimeError, ValueError):
                    pass  # the source went down too: fall through
            if not eligible:
                lost += 1
                self.total_lost += 1
                metrics.LOST_SEQUENCES.labels(reason="no_replica").inc()
                seq.fail(
                    EngineRecoveringError(
                        "no eligible replica for the migrated request; "
                        "retry shortly",
                        retry_after=self.retry_after_s,
                    )
                )
                continue
            target = min(
                eligible,
                key=lambda c: self._load(c) + extra.get(id(c), 0),
            )
            outcome = replay_into(
                target, seq, self._quarantine,
                retry_after=self.retry_after_s,
                kind=kind,
                reason=reason,
                from_replica=from_replica,
            )
            if outcome != "replayed":
                lost += 1
                self.total_lost += 1
                continue
            extra[id(target)] = extra.get(id(target), 0) + 1
            moved += 1
            if kind == "resume":
                self.total_resumed += 1
            else:
                self.total_migrated += 1
                metrics.MIGRATIONS.labels(reason=reason).inc()
        return moved, lost, requeued

    def _claim_dead(self, core: EngineCore) -> List[Sequence]:
        """A replica died while (or just before) a planned migration:
        wait briefly for containment to publish its checkpoint, then
        claim it — the crash checkpoint carries the same fold/epoch
        guarantees as an evacuation, so the placement path is shared."""
        deadline = time.monotonic() + 5.0
        while (
            not core._containment_done
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        self.total_lost += core.take_resume_losses()
        return core.take_checkpointed()

    def _evacuate_all(
        self, core: EngineCore, reason: str
    ) -> tuple:
        """Returns ``(sequences, kind)`` — kind is "migrate" for a live
        planned evacuation (prepare_migrate folded them) and "resume"
        when the residents had to be claimed from a crash checkpoint
        (prepare_resume folded them); _place forwards it so provenance
        flags/metrics/ticks stay truthful."""
        if not self._alive(core):
            return self._claim_dead(core), "resume"
        try:
            return core.evacuate(
                None, reason=reason,
                timeout=self._mig.evacuate_timeout_s,
            ), "migrate"
        except MigrationError:
            # TIMEOUT on a live engine is not death: the sequences
            # stayed put (or the core folds an abandoned evacuation
            # back into its own scheduler).  Propagate so the caller
            # aborts the operation — remove_replica must NOT proceed
            # to stop() a replica still full of live work.  The
            # replica stays marked draining; the operator retries.
            raise
        except RuntimeError:
            # died mid-evacuation: the containment checkpoint owns the
            # residents now — claim and place them the same way
            return self._claim_dead(core), "resume"

    def _fallback_targets(self, idx: int, core: EngineCore) -> List[Any]:
        """A DEAD replica's checkpoints must go somewhere: when every
        non-draining survivor is gone, placing onto alive DRAINING
        survivors (same call _redistribute makes in this situation)
        beats failing the requests — remove_replica re-evacuates, so
        nothing is lost even if that survivor is later torn down.  A
        LIVE source never takes this path: _check_placement refuses
        typed before anything moves."""
        with self._topology_lock:
            fallback = [
                c for j, c in enumerate(self.replicas)
                if j != idx and self._alive(c)
            ]
        if fallback:
            logger.warning(
                "placing dead replica residents onto DRAINING "
                "survivors: no non-draining target exists; re-issue "
                "the drain once the fleet recovers",
                extra={"extra_data": {"replica": idx}},
            )
        return fallback

    @_structural
    def drain_replica(
        self, idx: int, reason: str = "drain"
    ) -> Dict[str, Any]:
        """Mark replica ``idx`` draining (no new placements), then
        live-migrate its residents to the least-loaded eligible
        survivors.  The replica keeps serving anything that raced the
        mark and reports DEGRADED-with-detail health until undrained or
        removed — a rolling deploy drains, replaces the process behind
        the replica, then undrains.  Raises ValueError for an unknown
        index, MigrationRefusedError when no survivor can take the
        work (nothing moves in that case), and MigrationError when the
        evacuation times out — the replica then STAYS marked draining
        with its residents still serving on it; retry the drain."""
        self._require_migration()
        core, targets, already, moved, lost, requeued = (
            self._drain_and_place(idx, reason)
        )
        logger.warning(
            "dp replica draining",
            extra={
                "extra_data": {
                    "replica": idx, "reason": reason,
                    "migrated": moved, "lost": lost,
                    "requeued": requeued,
                    "already_draining": already,
                }
            },
        )
        return {
            "replica": idx,
            "draining": True,
            "migrated": moved,
            "lost": lost,
            "requeued": requeued,
            "already_draining": already,
        }

    def _drain_and_place(
        self, idx: int, reason: str, removing: bool = False
    ) -> tuple:
        """The shared gate → mark → evacuate → place sequence behind
        drain_replica and remove_replica (ONE copy, so placement fixes
        land once).  Returns (core, targets, already, moved, lost,
        requeued).  Raises before anything moves: ValueError for a bad
        index, MigrationRefusedError from the placement gate — plus
        the remove-specific last-replica/mid-rebuild guards when
        ``removing``."""
        with self._topology_lock:
            if not 0 <= idx < len(self.replicas):
                raise ValueError(
                    f"no replica {idx} (dp={len(self.replicas)})"
                )
            if removing:
                if len(self.replicas) <= 1:
                    raise MigrationRefusedError(
                        "cannot remove the last replica; stop the "
                        "server instead"
                    )
                if id(self.replicas[idx]) in self._rebuilding:
                    raise MigrationRefusedError(
                        "replica is mid-rebuild; retry once it settles"
                    )
            already = idx in self._draining
            core = self.replicas[idx]
            targets = [
                c for j, c in enumerate(self.replicas)
                if j != idx and j not in self._draining
            ]
        if self._alive(core):
            # typed placement gate BEFORE the mark: a refused op
            # leaves the fleet exactly as it was
            targets = self._check_placement(core, targets)
        elif not any(self._alive(c) for c in targets):
            # a dead source's checkpoint must not be lost just because
            # every NON-DRAINING sibling is also dead — alive draining
            # survivors can still serve it (same call _redistribute
            # makes; zero-loss beats drain purity)
            targets = self._fallback_targets(idx, core)
        with self._topology_lock:
            self._draining.add(idx)
            metrics.REPLICAS_DRAINING.set(len(self._draining))
        t0 = time.monotonic()
        seqs, kind = self._evacuate_all(core, reason)
        # a drained source STAYS in the fleet: residents whose target
        # died mid-op fold back into it rather than 503.  A removed
        # source is leaving — no fold-back (stop() fails stragglers
        # typed).
        moved, lost, requeued = self._place(
            seqs, targets, reason, idx, kind=kind,
            fallback=None if removing else core,
        )
        if seqs:
            metrics.MIGRATION_SECONDS.observe(time.monotonic() - t0)
        return core, targets, already, moved, lost, requeued

    @_structural
    def undrain_replica(self, idx: int) -> Dict[str, Any]:
        """Return a drained replica to the placement rotation (the
        rolling deploy's rejoin step) and re-arm its auto-repair."""
        self._require_migration()
        with self._topology_lock:
            if not 0 <= idx < len(self.replicas):
                raise ValueError(
                    f"no replica {idx} (dp={len(self.replicas)})"
                )
            was = idx in self._draining
            core = self.replicas[idx]
        canary = None
        if self._canary is not None and was and self._alive(core):
            # an undrained replica sat out of rotation (rolling deploy:
            # possibly a whole new binary/weights under it) — prove it
            # BEFORE it becomes routable: the probe runs while the
            # draining mark still excludes the replica from placement,
            # so corrupt output can never race real traffic.  A failure
            # quarantines it (also rotation-excluding) and triggers the
            # reload path; the undrain below then merely hands it from
            # one exclusion to the other.
            canary = self._canary.check(core, context=f"undrain:{idx}")
            if not canary["ok"]:
                self.total_canary_failures += 1
                self._quarantine_corrupt_live(core, canary)
        with self._topology_lock:
            self._draining.discard(idx)
            metrics.REPLICAS_DRAINING.set(len(self._draining))
        self._policy.reset()
        self._repair_event.set()  # a dead drained replica rebuilds now
        logger.warning(
            "dp replica undrained",
            extra={"extra_data": {"replica": idx, "was_draining": was}},
        )
        out = {"replica": idx, "draining": False, "was_draining": was}
        if canary is not None:
            out["canary"] = {
                k: canary[k] for k in ("ok", "recorded") if k in canary
            }
        return out

    @_structural
    def add_replica(self) -> Dict[str, Any]:
        """Grow the dp degree at runtime by building a fresh replica on
        a banked device slice (remove_replica returns its slice here).
        Growing beyond the boot-time device partition still needs a
        restart with a larger tpu.num_devices — slices are reused, not
        invented."""
        self._require_migration()
        with self._topology_lock:
            if not self._free_slices:
                raise MigrationRefusedError(
                    "no free device slice to build a replica on "
                    "(remove_replica banks its slice for reuse; "
                    "growing past the boot-time partition requires a "
                    "restart)"
                )
            devices = self._free_slices.pop()
        try:
            # construction OUTSIDE the lock: it blocks for seconds to
            # minutes on real hardware and the sweep/router must run
            core = EngineCore(self._replica_cfg, devices=devices)
        except Exception:
            with self._topology_lock:
                self._free_slices.append(devices)
            raise
        core.start()
        canary = None
        if self._canary is not None:
            # a fresh replica (new load on a banked slice) must match
            # the fleet's recorded fingerprint BEFORE it joins the
            # fleet: the probe runs while the core is still unattached
            # (unroutable), so an unproven replica never sees traffic
            canary = self._canary.check(core, context="add")
        with self._topology_lock:
            idx = len(self.replicas)
            self.replicas.append(core)
            self._device_slices.append(devices)
            if self._failover_enabled:
                self._attach(idx, core)
            metrics.DP_REPLICAS_TOTAL.set(len(self.replicas))
            if canary is not None and not canary["ok"]:
                # attach quarantined: visible to the operator, excluded
                # from routing, and the repair loop reloads it
                self._mark_corrupt(idx, None)
        if canary is not None and not canary["ok"]:
            self.total_canary_failures += 1
            core.declare_stalled(
                IntegrityError(
                    "add_replica canary failed: "
                    + str(canary.get("error") or "fingerprint mismatch"),
                    kind="canary",
                )
            )
        self._policy.reset()
        logger.warning(
            "dp replica added",
            extra={"extra_data": {"replica": idx, "dp": idx + 1}},
        )
        out = {"replica": idx, "dp": len(self.replicas)}
        if canary is not None:
            out["canary"] = {
                k: canary[k] for k in ("ok", "recorded") if k in canary
            }
        return out

    @_structural
    def remove_replica(self, idx: int) -> Dict[str, Any]:
        """Shrink the dp degree at runtime: drain + live-migrate the
        replica's residents, tear the engine down, and bank its device
        slice for a later add_replica.  The last replica is never
        removable (that is process shutdown's job)."""
        self._require_migration()
        core, targets, _already, moved, lost, _req = (
            self._drain_and_place(idx, "scale_down", removing=True)
        )
        # final sweep right before teardown: a concurrent drain whose
        # target list was snapshotted before this replica was marked
        # draining (or failover's draining fallback) may have placed
        # work onto it AFTER the evacuation above — stop() would fail
        # those as shutdown losses.  Anything that still lands in the
        # (now tiny) window gets the retryable 503 from stop().
        if self._alive(core):
            seqs2, kind2 = self._evacuate_all(core, "scale_down")
            if seqs2:
                m2, l2, _ = self._place(
                    seqs2, targets, "scale_down", idx, kind=kind2
                )
                moved += m2
                lost += l2
        core.stop()
        with self._topology_lock:
            # the slot cannot have shifted: structural ops hold
            # _structural_lock for their full duration and the sweep
            # skips draining replicas' rebuilds
            slot = self.replicas.index(core)
            self.replicas.pop(slot)
            self._free_slices.append(self._device_slices.pop(slot))
            self._draining.discard(slot)
            # renumber the index-keyed draining marks above the gap
            self._draining = {
                i - 1 if i > slot else i for i in self._draining
            }
            # corrupt quarantine and canary schedule are index-keyed
            # too: renumber the same way (the removed replica's marks
            # simply disappear with it)
            self._corrupt.discard(slot)
            self._corrupt = {
                i - 1 if i > slot else i for i in self._corrupt
            }
            metrics.CORRUPT_QUARANTINED.set(len(self._corrupt))
            self._next_canary = {
                (i - 1 if i > slot else i): t
                for i, t in self._next_canary.items()
                if i != slot
            }
            self._next_attempt.pop(id(core), None)
            if self._failover_enabled:
                for j, c in enumerate(self.replicas):
                    self._attach(j, c)
            metrics.DP_REPLICAS_TOTAL.set(len(self.replicas))
            metrics.REPLICAS_DRAINING.set(len(self._draining))
            dp_now = len(self.replicas)
        self._policy.reset()
        logger.warning(
            "dp replica removed",
            extra={
                "extra_data": {
                    "replica": idx, "dp": dp_now,
                    "migrated": moved, "lost": lost,
                }
            },
        )
        return {
            "replica": idx, "dp": dp_now,
            "migrated": moved, "lost": lost,
        }

    # --------------------------------------- hot-replica rebalancing

    def _balance_loop(self) -> None:
        while not self._stopping:
            self._balance_event.wait(
                timeout=max(0.1, self._mig.rebalance_interval_s)
            )
            self._balance_event.clear()
            if self._stopping:
                return
            try:
                self.maybe_rebalance()
            except Exception:  # pragma: no cover - defensive
                logger.error("dp rebalance pass failed", exc_info=True)

    def maybe_rebalance(self) -> Optional[Dict[str, Any]]:
        """One rebalance policy tick: feed live pressure signals to the
        hysteresis policy and execute its decision (move the
        longest-running decodes off the hot replica onto the idle one).
        Returns the move summary, or None when the policy holds."""
        if not self._mig.enabled or not self._mig.rebalance_enabled:
            return None
        with self._topology_lock:
            reps = list(self.replicas)
            # corrupt-quarantined replicas neither shed nor receive
            # rebalance moves
            draining = set(self._draining) | set(self._corrupt)
        if len(reps) < 2:
            return None
        signals: Dict[int, Dict[str, Any]] = {}
        for i, core in enumerate(reps):
            if not self._alive(core) or i in draining:
                continue
            try:
                signals[i] = core.pressure_signals()
            except Exception:  # pragma: no cover - mid-rebuild
                continue
        decision = self._policy.observe(signals)
        if decision is None:
            return None
        hot_idx, cold_idx = decision
        return self._rebalance(reps[hot_idx], reps[cold_idx], hot_idx)

    def _rebalance(
        self, hot: EngineCore, cold: EngineCore, hot_idx: int
    ) -> Optional[Dict[str, Any]]:
        mig = self._mig
        if self._kv_dtype_of(hot) != self._kv_dtype_of(cold):
            self._policy.note_move_failed()
            return None  # mixed-dtype fleet: nothing to move safely
        victims = [
            s for s in hot.scheduler.running
            if s.status is SeqStatus.RUNNING
            and not s.abort_requested
            and s.num_generated >= mig.min_generated_tokens
        ]
        if not victims:
            self._policy.note_move_failed()
            logger.info(
                "rebalance decided but no eligible victim (all "
                "residents below migration.min_generated_tokens)",
                extra={"extra_data": {"replica": hot_idx}},
            )
            return None
        # longest-running decodes first: they free the most KV per
        # move and have the longest remaining co-tenancy with the
        # pressured pool
        victims.sort(key=lambda s: s.num_generated, reverse=True)
        victims = victims[: max(1, mig.max_moves_per_cycle)]
        t0 = time.monotonic()
        try:
            seqs = hot.evacuate(
                [s.seq_id for s in victims],
                reason="rebalance",
                timeout=mig.evacuate_timeout_s,
            )
        except Exception as exc:
            self._policy.note_move_failed()
            logger.warning(
                "rebalance evacuation failed; replica left as-is",
                extra={"extra_data": {
                    "replica": hot_idx,
                    "error": f"{type(exc).__name__}: {exc}",
                }},
            )
            return None
        if not seqs:
            self._policy.note_move_failed()
            return None
        if not self._alive(cold):
            # the target died between the policy decision and
            # placement: fold the victims straight back into the hot
            # replica they were running fine on — an optional
            # optimization must not turn healthy requests into 503s
            requeued = 0
            for seq in seqs:
                try:
                    hot.submit_existing(seq)
                    requeued += 1
                except (RuntimeError, ValueError):
                    self.total_lost += 1
                    metrics.LOST_SEQUENCES.labels(
                        reason="no_replica"
                    ).inc()
                    seq.fail(EngineRecoveringError(
                        "rebalance target died and the source could "
                        "not take the request back; retry shortly",
                        retry_after=self.retry_after_s,
                    ))
            self._policy.note_move_failed()
            logger.warning(
                "rebalance target died before placement; victims "
                "folded back into the source replica",
                extra={"extra_data": {
                    "from": hot_idx, "requeued": requeued,
                    "lost": len(seqs) - requeued,
                }},
            )
            return None
        moved, lost, requeued = self._place(
            seqs, [cold], "rebalance", hot_idx, fallback=hot
        )
        if moved == 0:
            self._policy.note_move_failed()
        metrics.MIGRATION_SECONDS.observe(time.monotonic() - t0)
        logger.warning(
            "dp rebalance moved long decodes off a pressured replica",
            extra={
                "extra_data": {
                    "from": hot_idx, "moved": moved, "lost": lost,
                    "requeued": requeued,
                }
            },
        )
        return {
            "from": hot_idx, "moved": moved, "lost": lost,
            "requeued": requeued,
        }

    def abort_in_flight(self, reason: str = "drain") -> None:
        """Graceful-drain straggler sweep: fan the abort out to every
        replica (without this, dp>1 pods would drop their in-flight
        responses at drain timeout instead of settling them)."""
        for core in self.replicas:
            if self._alive(core):
                core.abort_in_flight(reason)

    def set_spec_suspended(self, flag: bool) -> None:
        """Brownout L3 fan-out: every replica suspends/resumes
        speculative decoding together (dead replicas included — the
        flag is a plain bool store, and a replica revived later must
        not come back drafting under the load being shed)."""
        for core in self.replicas:
            core.set_spec_suspended(flag)

    def set_prefix_insert_suspended(self, flag: bool) -> None:
        """Brownout L4 fan-out: every replica stops/resumes prefix-tree
        inserts together (dead replicas included, same rationale as the
        spec-suspension fan-out)."""
        for core in self.replicas:
            core.set_prefix_insert_suspended(flag)

    def pressure_signals(self) -> Dict[str, Any]:
        """Admission/brownout gauges aggregated across replicas: the
        WORST KV free ratio (one full replica is where new work lands
        when routing prefers prefix affinity) and summed queue depth."""
        ratios = []
        depth = running = 0
        swap_used = swap_budget = swapped_seqs = 0
        swap_free_ratios = []
        with self._topology_lock:
            cores = [
                c for i, c in enumerate(self.replicas)
                if i not in self._draining
            ]
        for core in cores:
            if not self._alive(core):
                continue
            # draining replicas excluded above: their (possibly full)
            # pools take no new placements, so counting them would
            # brown out admission against capacity that isn't offered
            sig = core.pressure_signals()
            if "kv_free_ratio" in sig:
                ratios.append(sig["kv_free_ratio"])
            depth += sig.get("engine_queue_depth", 0)
            running += sig.get("running", 0)
            if sig.get("kv_swap_enabled"):
                # host swap tier: summed occupancy, WORST headroom —
                # admission's swap relief must not run a replica's
                # device pool hot against a sibling's empty host pool
                swap_used += sig.get("kv_host_pool_bytes", 0)
                swap_budget += sig.get("kv_host_pool_budget_bytes", 0)
                swapped_seqs += sig.get("kv_swapped_seqs", 0)
                swap_free_ratios.append(
                    sig.get("kv_host_free_ratio", 0.0)
                )
        out: Dict[str, Any] = {
            "engine_queue_depth": depth, "running": running,
        }
        if ratios:
            out["kv_free_ratio"] = min(ratios)
        if swap_free_ratios:
            out["kv_swap_enabled"] = True
            out["kv_host_pool_bytes"] = swap_used
            out["kv_host_pool_budget_bytes"] = swap_budget
            out["kv_host_free_ratio"] = min(swap_free_ratios)
            out["kv_swapped_seqs"] = swapped_seqs
        return out

    # ----------------------------------------------------------- health

    @property
    def state(self) -> HealthState:
        """Pod-level health: SERVING with the full replica complement,
        DEGRADED while any replica is down OR draining (survivors still
        serve — readiness stays green; the detail block names which
        replica is out and why), DEAD only when no replica can accept
        work (liveness then recycles the pod)."""
        alive = sum(1 for c in self.replicas if self._alive(c))
        if alive == 0:
            return HealthState.DEAD
        if (
            alive < len(self.replicas)
            or self._draining
            or self._corrupt
        ):
            return HealthState.DEGRADED
        return HealthState.SERVING

    def _replica_state(
        self,
        idx: int,
        core: EngineCore,
        draining: set,
        now: float,
        corrupt: set = frozenset(),
    ) -> str:
        # core + draining + corrupt come from the caller's under-lock
        # snapshot: a concurrent remove_replica renumber must not shift
        # the index-keyed marks under this iteration
        if idx in corrupt:
            # suspected silent corruption: out of rotation (alive or
            # mid-reload) until the post-reload canary passes
            return "quarantined_corrupt"
        if idx in draining:
            # deliberately out of rotation (alive or not): auto-repair
            # is suspended until undrain, so "draining" is the truth
            return "draining"
        if self._alive(core):
            return "serving"
        if not self._failover_enabled:
            return "dead"
        window = [
            t for t in self._restart_times
            if now - t < self._recovery.restart_window_s
        ]
        if len(window) >= self._recovery.max_restarts:
            return "dead"  # budget exhausted until the window slides
        return "recovering"

    def health(self) -> Dict[str, Any]:
        """The /health engine block for dp>1 pods: pod state machine
        position plus per-replica detail (state, last fatal, queue
        depth) so operators see WHICH replica is out, not just that
        one is."""
        from vgate_tpu.errors import state_is_alive, state_is_ready

        now = time.monotonic()
        state = self.state
        with self._topology_lock:
            reps = list(self.replicas)
            draining = set(self._draining)
            corrupt = set(self._corrupt)
        replicas = []
        for i, core in enumerate(reps):
            entry: Dict[str, Any] = {
                "replica": i,
                "state": self._replica_state(
                    i, core, draining, now, corrupt
                ),
            }
            fatal = core._fatal
            if fatal is not None:
                entry["last_fatal"] = (
                    f"{type(fatal).__name__}: {fatal}"
                )
            try:
                sched = core.scheduler.get_stats()
                entry["queue_depth"] = sched["waiting"]
                entry["running"] = sched["running"]
            except Exception:  # pragma: no cover - mid-rebuild
                pass
            replicas.append(entry)
        # ONE definition for the gauge (the repair sweep writes it
        # too): liveness, not rotation membership.  An alive draining
        # replica still counts — a planned drain must not sawtooth
        # vgt_dp_replicas_alive between /health scrapes and sweep
        # ticks or fire VgtDpReplicaDown for a deliberate operation.
        alive = sum(1 for c in reps if self._alive(c))
        metrics.DP_REPLICAS_ALIVE.set(alive)
        out = {
            "state": state.value,
            "alive": state_is_alive(state.value),
            "ready": state_is_ready(state.value),
            "dp": len(reps),
            "replicas_alive": alive,
            "replicas_draining": len(draining),
            "draining": sorted(draining),
            "replicas": replicas,
            "failovers": self.total_failovers,
            "restarts": self.total_restarts,
            # restart budget headroom (satellite fix; shared across
            # the fleet — one sick pod, one budget)
            "restarts_remaining": restart_budget_remaining(
                self._restart_times, self._recovery, now
            ),
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "migrated": self.total_migrated,
            "lost": self.total_lost,
            "quarantined": len(self._quarantine),
        }
        if self._integrity_cfg.enabled:
            out["integrity"] = {
                "quarantined_corrupt": sorted(corrupt),
                "corrupt_reloads": self.total_corrupt_reloads,
                "canary_failures": self.total_canary_failures,
                **(
                    {"canary": self._canary.stats()}
                    if self._canary is not None
                    else {}
                ),
                "last": self.last_integrity,
            }
        return out

    @property
    def retry_after_s(self) -> float:
        """Client backoff suggestion while degraded (the batcher reads
        this off the backend core for its 503s, like the supervisor's)."""
        return max(1.0, self._backoff())

    # ------------------------------------------------------------ routing

    @staticmethod
    def _load(core: EngineCore) -> int:
        return len(core.scheduler.waiting) + len(core.scheduler.running)

    @staticmethod
    def _alive(core: EngineCore) -> bool:
        # a cleanly-STOPPED core (remove_replica teardown) has
        # _fatal None but no loop: submit_existing into it would
        # enqueue into a queue nothing drains — the client's future
        # then hangs forever while metrics count a successful move
        return core._fatal is None and getattr(core, "_running", True)

    def _pick_replica(
        self, prompt_ids: Optional[List[int]] = None
    ) -> EngineCore:
        """Least-loaded replica (queued + resident sequences), round-robin
        on ties so idle replicas fill evenly — with **prefix affinity**:
        each replica's KV prefix cache is private, so requests sharing a
        first prompt page stick to the same replica (cache hits) unless
        that replica is meaningfully more loaded than the best one.

        Failure containment (SURVEY 5.3): a replica whose engine thread
        died (engine-fatal) is routed AROUND — in-flight sequences on it
        fail, but new requests ride the surviving replicas.  A replica
        marked DRAINING (rolling deploy / scale-down) is routed around
        the same way: it finishes what it has, takes nothing new.  Only
        when every replica is dead does the submit surface the fatal."""
        with self._route_lock:
            with self._topology_lock:
                reps = list(self.replicas)
                # corrupt-quarantined replicas route exactly like
                # draining ones: alive, but taking nothing new until
                # the post-reload canary clears them
                draining = set(self._draining) | set(self._corrupt)
            offset = next(self._rr)
            n = len(reps)
            order = [(offset + i) % n for i in range(n)]
            alive = [
                reps[i] for i in order
                if self._alive(reps[i]) and i not in draining
            ]
            if not alive:
                # no placeable replica: fall back to any live one (a
                # fully-draining fleet still serves rather than 500s),
                # else let EngineCore.submit_tokens raise the fatal
                live = [reps[i] for i in order if self._alive(reps[i])]
                return live[0] if live else reps[order[0]]
            best = min(alive, key=self._load)
            page = self.config.tpu.kv_page_size
            if (
                prompt_ids is not None
                and len(prompt_ids) >= page
                and reps[0].prefix_cache_enabled
            ):
                import zlib

                block = bytes(
                    b for t in prompt_ids[:page] for b in t.to_bytes(4, "little")
                )
                sticky_idx = zlib.crc32(block) % n
                sticky = reps[sticky_idx]
                # affinity wins unless it costs real queueing headroom
                # (or the sticky replica is dead/draining)
                if (
                    self._alive(sticky)
                    and sticky_idx not in draining
                    and self._load(sticky)
                    <= self._load(best)
                    + max(2, self.config.tpu.max_batch_slots // 4)
                ):
                    return sticky
            return best

    def _gate(self, prompt_ids: List[int]) -> None:
        """Reject quarantined prompts at the door (the supervisor's
        gate, pod-wide): a request a poison-classified replica fatal
        implicated must not be given a fresh replica to kill.  Steady
        state (empty quarantine) skips the O(prompt) fingerprint."""
        if not self._quarantine:
            return
        fp = faults.fingerprint(prompt_ids)
        if fp in self._quarantine:
            raise PoisonRequestError(
                f"request {fp} is quarantined: a poison fault on a dp "
                "replica named it and it will not be admitted again"
            )

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        ids = list(prompt_ids)
        self._gate(ids)
        return self._pick_replica(ids).submit_tokens(
            prompt_ids, params, stream_cb, meta=meta
        )

    def submit_prompt(
        self,
        prompt: str,
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        ids = self.tokenizer.encode(prompt)
        max_prompt = self.config.model.max_model_len - 1
        if len(ids) > max_prompt:
            ids = ids[-max_prompt:]
        ids = ids or [self.tokenizer.bos_id]
        self._gate(ids)
        return self._pick_replica(ids).submit_tokens(
            ids, params, stream_cb, meta=meta
        )

    def generate(
        self, prompts: Seq[str], params: Seq[SamplingParams]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API: requests spread across replicas and decode
        concurrently (mirrors EngineCore.generate's result shape)."""
        seqs = [
            self.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            gen_time = (seq.finish_t or 0) - seq.arrival_t
            results.append(
                {
                    "text": self.final_text(seq),
                    "token_ids": list(seq.generated_ids),
                    "num_tokens": seq.num_output_tokens,
                    "prompt_tokens": seq.orig_prompt_len,
                    "finish_reason": seq.finish_reason,
                    "metrics": {
                        "ttft": seq.ttft or 0.0,
                        "tpot": seq.tpot or 0.0,
                        "gen_time": gen_time,
                        **seq.resume_metrics(),
                    },
                }
            )
        return results

    def final_text(self, seq: Sequence) -> str:
        if seq.text_override is not None:
            return seq.text_override
        return self.tokenizer.decode(seq.generated_ids)

    # ------------------------------------------------------------- utilities

    def warmup(self, buckets: Optional[List[int]] = None) -> float:
        return sum(core.warmup(buckets) for core in self.replicas)

    def capture_profile(
        self, duration_s: float = 1.0, out_dir: Optional[str] = None
    ) -> Dict[str, Any]:
        """jax.profiler traces are process-wide; one capture covers all
        replicas (they share the process and its device set)."""
        return self.replicas[0].capture_profile(duration_s, out_dir)

    def device_health(self) -> Dict[str, Any]:
        healths = [core.device_health() for core in self.replicas]
        alive = [
            h.get("alive", False) and self._alive(core)
            for h, core in zip(healths, self.replicas)
        ]
        # Report platform/device_kind from an ALIVE replica: replica 0
        # may be the dead one, and alive=true must describe a core that
        # can actually serve.  Fall back to healths[0] only when none
        # are alive.
        rep = next(
            (h for h, ok in zip(healths, alive) if ok), healths[0]
        )
        return {
            # serving-capable as long as ANY replica lives (the router
            # steers around dead ones); per-replica detail alongside
            "alive": any(alive),
            "replicas_alive": sum(alive),
            "platform": rep.get("platform"),
            "device_kind": rep.get("device_kind"),
            "num_devices": sum(h.get("num_devices", 0) for h in healths),
            "replicas": len(self.replicas),
        }

    def get_stats(self) -> Dict[str, Any]:
        per_replica = [core.get_stats() for core in list(self.replicas)]
        agg = {
            key: sum(s[key] for s in per_replica)
            for key in (
                "steps",
                "prefills",
                "decode_tokens",
                "state_rebuilds",
                "kv_pages_total",
                "kv_token_capacity",
            )
        }
        agg["scheduler"] = {}
        for key, val in per_replica[0]["scheduler"].items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                agg["scheduler"][key] = sum(
                    s["scheduler"][key] for s in per_replica
                )
            elif isinstance(val, dict):
                # nested stat groups (e.g. prefix_cache): sum the numeric
                # sub-keys so DP deployments keep cache observability
                agg["scheduler"][key] = {
                    k2: (
                        sum(s["scheduler"][key][k2] for s in per_replica)
                        if isinstance(v2, (int, float))
                        and not isinstance(v2, bool)
                        else v2
                    )
                    for k2, v2 in val.items()
                }
        if "kv_swap" in per_replica[0]:
            # host swap tier: summed fleet occupancy + counters (the
            # per-replica blocks stay available under "replicas")
            swaps = [s["kv_swap"] for s in per_replica if "kv_swap" in s]
            agg["kv_swap"] = {
                "enabled": any(s["enabled"] for s in swaps),
                "budget_bytes": sum(s["budget_bytes"] for s in swaps),
                "used_bytes": sum(s["used_bytes"] for s in swaps),
                "swapped_seqs": sum(s["swapped_seqs"] for s in swaps),
                "prefix_tickets": sum(
                    s["prefix_tickets"] for s in swaps
                ),
                "swap_out_pages": {
                    k: sum(s["swap_out_pages"].get(k, 0) for s in swaps)
                    for k in ("preempt", "prefix")
                },
                "swap_in_pages": {
                    k: sum(s["swap_in_pages"].get(k, 0) for s in swaps)
                    for k in ("preempt", "prefix")
                },
                # the thrash-detection counter the runbook keys on
                # (rising discard[capacity] = pool too small): reasons
                # are open-ended, so sum over the union of keys
                "discard_pages": {
                    k: sum(s["discard_pages"].get(k, 0) for s in swaps)
                    for k in sorted(
                        {k for s in swaps for k in s["discard_pages"]}
                    )
                },
                "refused": sum(s["refused"] for s in swaps),
            }
        agg["model"] = self.spec.name
        agg["dp"] = len(self.replicas)
        # failover accounting mirrors the dp=1 supervisor block's shape
        agg["failover"] = {
            "failovers": self.total_failovers,
            "restarts": self.total_restarts,
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "lost": self.total_lost,
            "replicas_alive": sum(
                1 for c in self.replicas if self._alive(c)
            ),
        }
        agg["migration"] = {
            "migrated": self.total_migrated,
            "draining": sorted(self._draining),
            "free_slices": len(self._free_slices),
        }
        if self._integrity_cfg.enabled:
            agg["integrity"] = {
                "quarantined_corrupt": sorted(self._corrupt),
                "corrupt_reloads": self.total_corrupt_reloads,
                "canary_failures": self.total_canary_failures,
                **(
                    {"canary": self._canary.stats()}
                    if self._canary is not None
                    else {}
                ),
            }
        # perf attribution: pod aggregate next to the per-replica blocks
        # (observability/perf.py merge — additive sums, wall-weighted
        # ratios), mirroring the _MergedFlight pattern
        agg["perf"] = perf_attr.merge_stats(
            [s["perf"] for s in per_replica if "perf" in s]
        )
        agg["mesh"] = dict(per_replica[0]["mesh"], dp=len(self.replicas))
        agg["load_time_s"] = round(self.load_time_s, 2)
        agg["replicas"] = per_replica
        return agg

    def perf_snapshot(self) -> Dict[str, Any]:
        """The dp /debug/perf payload: every replica's attribution
        snapshot plus the merged pod view (observability/perf.py
        merge_snapshots — the _MergedFlight pattern for perf)."""
        return perf_attr.merge_snapshots(
            [core.perf.snapshot() for core in list(self.replicas)]
        )
