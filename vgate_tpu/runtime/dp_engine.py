"""Data parallelism for serving: replica engines + a least-loaded router.

Decode for independent requests is embarrassingly parallel, so the
TPU-native data-parallel design is **replication, not collectives**: each
``dp`` shard of the device mesh runs its own :class:`EngineCore` (weights
replicated, KV pool and continuous-batching state private) and a router
spreads requests across replicas by load.  Throughput scales with ``dp``
while tp/ep/sp collectives stay *inside* each replica's submesh, riding the
fastest ICI loops (SURVEY.md section 2.2 row 1; the reference exposes no DP
at all — vLLM hides replica management behind external orchestration).

``ReplicatedEngine`` exposes the same surface the backend drives on
``EngineCore`` (submit/generate/warmup/stats/health), so ``dp=1`` and
``dp>1`` are interchangeable behind ``JaxTPUBackend``.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

import jax

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import VGTConfig, get_config
from vgate_tpu.logging_config import get_logger
from vgate_tpu.runtime.engine_core import EngineCore
from vgate_tpu.runtime.sequence import Sequence, SeqStatus

logger = get_logger(__name__)


class _MergedFlight:
    """Read-only view merging the replicas' flight recorders so /debug
    works on dp>1 pods (each replica records independently; entries are
    stamped with their replica index and merged by wall time)."""

    def __init__(self, replicas: List[EngineCore]) -> None:
        self._replicas = replicas

    @property
    def enabled(self) -> bool:
        return any(r.flight.enabled for r in self._replicas)

    def _merged(self, method: str, n: Optional[int]) -> List[Dict[str, Any]]:
        out = []
        for i, core in enumerate(self._replicas):
            for entry in getattr(core.flight, method)():
                entry = dict(entry)
                entry["replica"] = i
                out.append(entry)
        out.sort(key=lambda e: e.get("t") or e.get("arrival_t") or 0.0)
        if n is not None and n >= 0:
            out = out[-n:]
        return out

    def ticks(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("ticks", n)

    def requests(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        return self._merged("requests", n)

    def live_requests(self) -> List[Dict[str, Any]]:
        return self._merged("live_requests", None)

    def find_request(self, ident: str) -> Optional[Dict[str, Any]]:
        # newest attempt wins ACROSS replicas too (a retry may land on
        # a different replica than the failed original)
        best: Optional[Dict[str, Any]] = None
        for i, core in enumerate(self._replicas):
            record = core.flight.find_request(ident)
            if record is None:
                continue
            record = dict(record)
            record["replica"] = i
            if best is None or (record.get("arrival_t") or 0.0) > (
                best.get("arrival_t") or 0.0
            ):
                best = record
        return best

    def get_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "replicas": [r.flight.get_stats() for r in self._replicas],
        }


class ReplicatedEngine:
    """``dp`` EngineCore replicas over disjoint submeshes + a load router."""

    def __init__(
        self,
        config: Optional[VGTConfig] = None,
        devices: Optional[list] = None,
    ) -> None:
        self.config = config or get_config()
        dp = max(1, self.config.tpu.dp)
        devices = list(devices if devices is not None else jax.devices())
        limit = self.config.tpu.num_devices
        if limit and limit < len(devices):
            devices = devices[:limit]
        if len(devices) % dp:
            raise ValueError(
                f"{len(devices)} devices not divisible by dp={dp}"
            )
        per = len(devices) // dp
        # each replica sees a dp=1 copy of the config; its submesh carries
        # the remaining ep/sp/tp axes
        replica_cfg = self.config.model_copy(deep=True)
        replica_cfg.tpu.dp = 1
        replica_cfg.tpu.num_devices = per
        self.replicas: List[EngineCore] = [
            EngineCore(replica_cfg, devices=devices[i * per : (i + 1) * per])
            for i in range(dp)
        ]
        self._rr = itertools.count()
        self._route_lock = threading.Lock()
        # /debug surface parity with dp=1: one merged recorder view
        self.flight = _MergedFlight(self.replicas)
        # convenience aliases: identical across replicas
        lead = self.replicas[0]
        self.spec = lead.spec
        self.tokenizer = lead.tokenizer
        self.geometry = lead.geometry
        self.mesh = lead.mesh
        self.load_time_s = sum(r.load_time_s for r in self.replicas)
        logger.info(
            "replicated engine ready",
            extra={
                "extra_data": {
                    "dp": dp,
                    "devices_per_replica": per,
                    "model": lead.spec.name,
                }
            },
        )

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        for core in self.replicas:
            core.start()

    def stop(self) -> None:
        for core in self.replicas:
            core.stop()

    def abort_in_flight(self, reason: str = "drain") -> None:
        """Graceful-drain straggler sweep: fan the abort out to every
        replica (without this, dp>1 pods would drop their in-flight
        responses at drain timeout instead of settling them)."""
        for core in self.replicas:
            if self._alive(core):
                core.abort_in_flight(reason)

    def set_spec_suspended(self, flag: bool) -> None:
        """Brownout L3 fan-out: every replica suspends/resumes
        speculative decoding together (dead replicas included — the
        flag is a plain bool store, and a replica revived later must
        not come back drafting under the load being shed)."""
        for core in self.replicas:
            core.set_spec_suspended(flag)

    def pressure_signals(self) -> Dict[str, Any]:
        """Admission/brownout gauges aggregated across replicas: the
        WORST KV free ratio (one full replica is where new work lands
        when routing prefers prefix affinity) and summed queue depth."""
        ratios = []
        depth = running = 0
        for core in self.replicas:
            if not self._alive(core):
                continue
            sig = core.pressure_signals()
            if "kv_free_ratio" in sig:
                ratios.append(sig["kv_free_ratio"])
            depth += sig.get("engine_queue_depth", 0)
            running += sig.get("running", 0)
        out: Dict[str, Any] = {
            "engine_queue_depth": depth, "running": running,
        }
        if ratios:
            out["kv_free_ratio"] = min(ratios)
        return out

    # ------------------------------------------------------------ routing

    @staticmethod
    def _load(core: EngineCore) -> int:
        return len(core.scheduler.waiting) + len(core.scheduler.running)

    @staticmethod
    def _alive(core: EngineCore) -> bool:
        return core._fatal is None

    def _pick_replica(
        self, prompt_ids: Optional[List[int]] = None
    ) -> EngineCore:
        """Least-loaded replica (queued + resident sequences), round-robin
        on ties so idle replicas fill evenly — with **prefix affinity**:
        each replica's KV prefix cache is private, so requests sharing a
        first prompt page stick to the same replica (cache hits) unless
        that replica is meaningfully more loaded than the best one.

        Failure containment (SURVEY 5.3): a replica whose engine thread
        died (engine-fatal) is routed AROUND — in-flight sequences on it
        fail, but new requests ride the surviving replicas.  Only when
        every replica is dead does the submit surface the fatal."""
        with self._route_lock:
            offset = next(self._rr)
            n = len(self.replicas)
            order = [self.replicas[(offset + i) % n] for i in range(n)]
            alive = [c for c in order if self._alive(c)]
            if not alive:
                # all dead: let EngineCore.submit_tokens raise the fatal
                return order[0]
            best = min(alive, key=self._load)
            page = self.config.tpu.kv_page_size
            if (
                prompt_ids is not None
                and len(prompt_ids) >= page
                and self.replicas[0].prefix_cache_enabled
            ):
                import zlib

                block = bytes(
                    b for t in prompt_ids[:page] for b in t.to_bytes(4, "little")
                )
                sticky = self.replicas[zlib.crc32(block) % n]
                # affinity wins unless it costs real queueing headroom
                # (or the sticky replica is dead)
                if self._alive(sticky) and self._load(sticky) <= self._load(
                    best
                ) + max(2, self.config.tpu.max_batch_slots // 4):
                    return sticky
            return best

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        return self._pick_replica(list(prompt_ids)).submit_tokens(
            prompt_ids, params, stream_cb, meta=meta
        )

    def submit_prompt(
        self,
        prompt: str,
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        ids = self.tokenizer.encode(prompt)
        max_prompt = self.config.model.max_model_len - 1
        if len(ids) > max_prompt:
            ids = ids[-max_prompt:]
        return self._pick_replica(ids).submit_tokens(
            ids or [self.tokenizer.bos_id], params, stream_cb, meta=meta
        )

    def generate(
        self, prompts: Seq[str], params: Seq[SamplingParams]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API: requests spread across replicas and decode
        concurrently (mirrors EngineCore.generate's result shape)."""
        seqs = [
            self.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            gen_time = (seq.finish_t or 0) - seq.arrival_t
            results.append(
                {
                    "text": self.final_text(seq),
                    "token_ids": list(seq.generated_ids),
                    "num_tokens": seq.num_output_tokens,
                    "prompt_tokens": seq.orig_prompt_len,
                    "finish_reason": seq.finish_reason,
                    "metrics": {
                        "ttft": seq.ttft or 0.0,
                        "tpot": seq.tpot or 0.0,
                        "gen_time": gen_time,
                    },
                }
            )
        return results

    def final_text(self, seq: Sequence) -> str:
        if seq.text_override is not None:
            return seq.text_override
        return self.tokenizer.decode(seq.generated_ids)

    # ------------------------------------------------------------- utilities

    def warmup(self, buckets: Optional[List[int]] = None) -> float:
        return sum(core.warmup(buckets) for core in self.replicas)

    def capture_profile(
        self, duration_s: float = 1.0, out_dir: Optional[str] = None
    ) -> Dict[str, Any]:
        """jax.profiler traces are process-wide; one capture covers all
        replicas (they share the process and its device set)."""
        return self.replicas[0].capture_profile(duration_s, out_dir)

    def device_health(self) -> Dict[str, Any]:
        healths = [core.device_health() for core in self.replicas]
        alive = [
            h.get("alive", False) and self._alive(core)
            for h, core in zip(healths, self.replicas)
        ]
        # Report platform/device_kind from an ALIVE replica: replica 0
        # may be the dead one, and alive=true must describe a core that
        # can actually serve.  Fall back to healths[0] only when none
        # are alive.
        rep = next(
            (h for h, ok in zip(healths, alive) if ok), healths[0]
        )
        return {
            # serving-capable as long as ANY replica lives (the router
            # steers around dead ones); per-replica detail alongside
            "alive": any(alive),
            "replicas_alive": sum(alive),
            "platform": rep.get("platform"),
            "device_kind": rep.get("device_kind"),
            "num_devices": sum(h.get("num_devices", 0) for h in healths),
            "replicas": len(self.replicas),
        }

    def get_stats(self) -> Dict[str, Any]:
        per_replica = [core.get_stats() for core in self.replicas]
        agg = {
            key: sum(s[key] for s in per_replica)
            for key in (
                "steps",
                "prefills",
                "decode_tokens",
                "state_rebuilds",
                "kv_pages_total",
                "kv_token_capacity",
            )
        }
        agg["scheduler"] = {}
        for key, val in per_replica[0]["scheduler"].items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                agg["scheduler"][key] = sum(
                    s["scheduler"][key] for s in per_replica
                )
            elif isinstance(val, dict):
                # nested stat groups (e.g. prefix_cache): sum the numeric
                # sub-keys so DP deployments keep cache observability
                agg["scheduler"][key] = {
                    k2: (
                        sum(s["scheduler"][key][k2] for s in per_replica)
                        if isinstance(v2, (int, float))
                        and not isinstance(v2, bool)
                        else v2
                    )
                    for k2, v2 in val.items()
                }
        agg["model"] = self.spec.name
        agg["dp"] = len(self.replicas)
        agg["mesh"] = dict(per_replica[0]["mesh"], dp=len(self.replicas))
        agg["load_time_s"] = round(self.load_time_s, 2)
        agg["replicas"] = per_replica
        return agg
