"""Paged KV cache: geometry, page allocator and device buffers.

First-party replacement for the paged-KV capability the reference gets
opaquely from vLLM (SURVEY.md section 2.1 "Paged KV cache + attention
kernels").  Layout: ``[num_layers, kv_heads, num_pages, page_size, head_dim]``
per K and V (head-major so one page of one head is a contiguous
``(page_size, head_dim)`` tile — the unit the Pallas decode kernel DMAs),
resident in TPU HBM; **page 0 is a reserved trash page** that
absorbs writes from padded positions and idle decode slots so device code
never branches on validity.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional

import jax
import jax.numpy as jnp

from vgate_tpu import faults, metrics
from vgate_tpu.logging_config import get_logger
from vgate_tpu.models.specs import ModelSpec
from vgate_tpu.utils.math import cdiv

logger = get_logger(__name__)


def _page_bytes(
    num_layers: int, page_size: int, kv_heads: int, head_dim: int,
    dtype_bytes: int, scale_bytes: int = 0,
) -> int:
    """Bytes one page occupies across all layers, K and V together — the
    single source of truth for page sizing (used by both KVGeometry and
    auto_num_pages).  ``scale_bytes`` is the per-token-per-head
    quantization-scale overhead (0 for plain bf16/f32 pools; int8 KV
    stores one bf16 scale per (page, head, slot) — ops/kv_quant.py)."""
    return (
        2 * num_layers * page_size * kv_heads
        * (head_dim * dtype_bytes + scale_bytes)
    )


@dataclass(frozen=True)
class KVGeometry:
    num_layers: int
    num_pages: int  # includes the reserved trash page(s)
    page_size: int
    kv_heads: int
    head_dim: int
    max_model_len: int
    dtype_bytes: int = 2  # bf16 default
    # reserved trash pages: 1 normally, sp under sequence-parallel decode
    # (one local trash per pool shard, parallel/sp_decode.py)
    num_reserved: int = 1
    # per-token-per-head scale bytes: 0 for plain pools, 2 (bf16) for
    # int8 KV (kv_cache.dtype: int8 — ops/kv_quant.py)
    scale_bytes: int = 0
    # reporting name for /stats, drills and bench artifacts
    kv_dtype: str = "bf16"

    @property
    def pages_per_seq(self) -> int:
        return cdiv(self.max_model_len, self.page_size)

    @property
    def page_bytes(self) -> int:
        return _page_bytes(
            self.num_layers, self.page_size, self.kv_heads, self.head_dim,
            self.dtype_bytes, self.scale_bytes,
        )

    @property
    def total_tokens(self) -> int:
        return (self.num_pages - self.num_reserved) * self.page_size


# Per-chip HBM when the runtime exposes no memory stats (TPU v5e class).
_DEFAULT_HBM_BYTES = 16 * 1024**3


def auto_num_pages(
    spec: ModelSpec,
    page_size: int,
    hbm_utilization: float,
    device=None,
    params_bytes: int = 0,
    fallback: int = 512,
    hard_cap: int = 65536,
    dtype_bytes: int = 2,
    hbm_bytes: int = 0,
    scale_bytes: int = 0,
) -> int:
    """Size the page pool from free device HBM after weights are resident
    (the serving analogue of vLLM's gpu_memory_utilization knob,
    reference config: vgate/config.py:47).

    When the runtime reports memory stats they are authoritative; otherwise
    on accelerators we budget against ``hbm_bytes`` (config
    ``tpu.hbm_bytes``; default 16 GiB/chip, the v5e part) minus the actual
    parameter bytes, and on CPU test platforms we return ``fallback``.
    ``dtype_bytes`` is the KV cache element width (fp32 KV needs twice the
    page budget of bf16); ``scale_bytes`` the per-token-per-head
    quantization-scale overhead (int8 KV: dtype_bytes=1, scale_bytes=2 —
    the same budget then yields ~2x the bf16 page count, the capacity
    half of the roofline lever).
    """
    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    page_bytes = _page_bytes(
        spec.num_layers, page_size, spec.num_kv_heads, spec.head_dim,
        dtype_bytes, scale_bytes,
    )
    if stats and "bytes_limit" in stats:
        limit = stats["bytes_limit"] * hbm_utilization
        free = max(0, limit - stats.get("bytes_in_use", 0))
    elif device.platform != "cpu":
        budget = hbm_bytes or _DEFAULT_HBM_BYTES
        free = max(0, budget * hbm_utilization - params_bytes)
    else:
        return fallback
    pages = int(free // page_bytes)
    return max(16, min(pages, hard_cap))


class PageAllocator:
    """Refcounting free-list allocator with a content-hash index for
    **automatic prefix caching**.

    Page 0 is the reserved trash page; with ``num_shards`` (sp) > 1 the
    first page of each contiguous pool shard ``{i * num_pages/sp}`` is
    reserved instead, so every sp shard has a LOCAL trash page
    (parallel/sp_decode.py) — the degenerate num_shards=1 case reserves
    exactly {0}.

    A page whose content corresponds to a full page of prompt tokens can be
    ``register``ed under a chain hash; a later prompt with the same prefix
    ``lookup``s the hash and shares the page (refcount++) instead of
    recomputing its KV.  Pages released to refcount 0 keep their content and
    park in an LRU of *evictable* cached pages — reusable until ``allocate``
    needs the space (vLLM's automatic-prefix-caching capability, which the
    reference can't reach because vLLM hides it; here it is first-party).
    """

    def __init__(self, num_pages: int, num_shards: int = 1) -> None:
        from vgate_tpu.parallel.sp_decode import reserved_page_ids

        self.num_pages = num_pages
        self.reserved = frozenset(reserved_page_ids(num_pages, num_shards))
        self._free: Deque[int] = deque(
            p for p in range(num_pages) if p not in self.reserved
        )
        self._refs: Dict[int, int] = {}
        self._hash_to_page: Dict[int, int] = {}
        self._page_hash: Dict[int, int] = {}
        # refcount-0 pages with live cached content, in LRU order
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        # radix-tree prefix cache (runtime/radix_cache.py): holds its own
        # references on cached pages and reclaims them on demand when
        # the free list runs short — the tree-mode replacement for the
        # flat _evictable LRU above
        self._reclaimer = None
        self.prefix_hits = 0
        self.prefix_evictions = 0
        # set by the engine when the pool stores int8 KV (kv_cache.dtype:
        # int8): every in-use page then holds quantized content, and the
        # vgt_kv_quantized_pages gauge tracks it alongside KV_PAGES_IN_USE
        self.quantized = False
        self._allocatable = num_pages - len(self.reserved)
        metrics.KV_PAGES_TOTAL.set(self._allocatable)
        self._set_in_use(0)

    def _set_in_use(self, used: int) -> None:
        metrics.KV_PAGES_IN_USE.set(used)
        metrics.KV_QUANTIZED_PAGES.set(used if self.quantized else 0)

    def set_reclaimer(self, reclaimer) -> None:
        """Attach a cache that can free refcounted pages on demand
        (``evictable_pages() -> int`` and ``reclaim(n) -> int freed``).
        Reclaimable pages count as obtainable in ``num_free``."""
        self._reclaimer = reclaimer

    @property
    def num_allocatable(self) -> int:
        """Total non-reserved pages (the pool size stats should report)."""
        return self._allocatable

    @property
    def num_free(self) -> int:
        """Pages obtainable by allocate(): truly free + evictable cached
        (flat LRU or reclaimable radix-tree pages)."""
        return len(self._free) + self.num_cached

    @property
    def num_truly_free(self) -> int:
        """Pages obtainable without evicting cache — the proactive-trim
        watermark (radix_cache.trim_to_watermark) keys off this so
        eviction cost is paid ahead of the allocation hot path."""
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self._allocatable - self.num_free

    @property
    def num_cached(self) -> int:
        if self._reclaimer is not None:
            return self._reclaimer.evictable_pages()
        return len(self._evictable)

    def allocate(self, n: int) -> Optional[List[int]]:
        """All-or-nothing allocation of n pages; None when insufficient.
        Evicts least-recently-used cached pages when the free list runs
        short."""
        faults.check("kv_alloc", payload=n)
        if n > self.num_free:
            return None
        if self._reclaimer is not None:
            short = n - len(self._free)
            if short > 0 and self._reclaimer.reclaim(short) < short:
                # a reclaimable page was still referenced (lock races
                # are excluded by design; defensive all-or-nothing)
                return None  # pragma: no cover - lock invariant holds
        pages = []
        for _ in range(n):
            if self._free:
                page = self._free.popleft()
            else:  # evict the LRU cached page (flat-chain mode)
                page, _ = self._evictable.popitem(last=False)
                self._drop_hash(page)
                self.prefix_evictions += 1
                metrics.PREFIX_EVICTIONS.labels(reason="lru").inc()
            self._refs[page] = 1
            pages.append(page)
        self._set_in_use(self.num_used)
        return pages

    def refcount(self, page: int) -> int:
        """Live reference count of a page (0 = free/parked)."""
        return self._refs.get(page, 0)

    def retain(self, pages: List[int]) -> None:
        """Take an extra reference on already-allocated pages (prefix
        sharing: the radix tree and each matching sequence hold their
        own reference; release() drops them symmetrically)."""
        for page in pages:
            refs = self._refs.get(page, 0)
            if refs <= 0:
                # a retained page must already be live — retaining a
                # free page would let allocate() hand it out again
                raise ValueError(f"retain of unreferenced page {page}")
            self._refs[page] = refs + 1
        self._set_in_use(self.num_used)

    def release(self, pages: List[int]) -> None:
        for page in pages:
            if not 0 <= page < self.num_pages or page in self.reserved:
                raise ValueError(f"bad page id {page}")
            refs = self._refs.get(page, 1) - 1
            if refs > 0:
                self._refs[page] = refs
                continue
            self._refs.pop(page, None)
            if page in self._page_hash:
                # content stays reusable until evicted
                self._evictable[page] = None
                self._evictable.move_to_end(page)
            else:
                self._free.append(page)
        self._set_in_use(self.num_used)
        if self._reclaimer is None and self._page_hash:
            metrics.PREFIX_CACHED_PAGES.set(len(self._evictable))

    # ----------------------------------------------------- prefix caching

    def register(self, page: int, content_hash: int) -> None:
        """Index a page's content under its prefix-chain hash.  On a hash
        collision with a live mapping, the existing page wins (both hold
        identical content by construction)."""
        if content_hash in self._hash_to_page:
            return
        self._hash_to_page[content_hash] = page
        self._page_hash[page] = content_hash

    def peek(self, content_hash: int) -> Optional[int]:
        """Check whether a page is cached for this hash WITHOUT taking a
        reference (scheduler admissibility probes must not mutate
        refcounts)."""
        return self._hash_to_page.get(content_hash)

    def is_evictable(self, page: int) -> bool:
        """True when the page is parked in the refcount-0 LRU: a prefix
        lookup() would revive it OUT of the allocatable pool, so
        admissibility math must not count it as free AND matched."""
        return page in self._evictable

    def lookup(self, content_hash: int) -> Optional[int]:
        """Find a cached page for this hash and take a reference to it."""
        page = self._hash_to_page.get(content_hash)
        if page is None:
            return None
        if page in self._evictable:  # revive a parked page
            del self._evictable[page]
            self._refs[page] = 1
            metrics.PREFIX_CACHED_PAGES.set(len(self._evictable))
        else:
            self._refs[page] = self._refs.get(page, 0) + 1
        self._set_in_use(self.num_used)
        return page

    def _drop_hash(self, page: int) -> None:
        h = self._page_hash.pop(page, None)
        if h is not None and self._hash_to_page.get(h) == page:
            del self._hash_to_page[h]


def make_kv_buffers(geometry: KVGeometry, dtype=jnp.bfloat16, sharding=None):
    """Allocate the K/V page pools (zeros) directly on device.

    With ``geometry.kv_dtype == "int8"`` each pool is a
    :class:`~vgate_tpu.ops.kv_quant.QuantPages` pair — int8 data plus
    the per-(page, head, slot) bf16 scale pool (initialized to 1, the
    scale :func:`~vgate_tpu.ops.kv_quant.quantize` assigns all-zero
    rows, so the zeroed pool dequantizes to exactly 0).  int8 KV
    requires a plain mesh (the engine enforces it), so ``sharding``
    is effectively single-device/replicated there.
    """
    from vgate_tpu.ops.kv_quant import SCALE_DTYPE, QuantPages

    shape = (
        geometry.num_layers,
        geometry.kv_heads,
        geometry.num_pages,
        geometry.page_size,
        geometry.head_dim,
    )

    def _place(arr, shard):
        return arr if shard is None else jax.device_put(arr, shard)

    if geometry.kv_dtype == "int8":
        scale_sharding = None
        if sharding is not None and hasattr(sharding, "spec"):
            # the scale pool drops the trailing head_dim: same spec
            # minus its last axis (all-None on the plain meshes int8
            # is restricted to, but keep the shapes honest)
            from jax.sharding import NamedSharding, PartitionSpec

            scale_sharding = NamedSharding(
                sharding.mesh, PartitionSpec(*tuple(sharding.spec)[:-1])
            )

        def pool():
            return QuantPages(
                data=_place(jnp.zeros(shape, jnp.int8), sharding),
                scale=_place(
                    jnp.ones(shape[:-1], SCALE_DTYPE), scale_sharding
                ),
            )

        k, v = pool(), pool()
    else:
        k = _place(jnp.zeros(shape, dtype), sharding)
        v = _place(jnp.zeros(shape, dtype), sharding)
    pool_bytes = 2 * sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(k)
    )
    logger.info(
        "kv cache allocated",
        extra={
            "extra_data": {
                "pages": geometry.num_pages,
                "tokens_capacity": geometry.total_tokens,
                "kv_dtype": geometry.kv_dtype,
                "mb": round(pool_bytes / 1e6),
            }
        },
    )
    return k, v
