"""The TPU engine runtime: weights, KV paging, scheduler, engine core."""
