"""Engine worker process — one engine behind a frame-protocol socket.

``python -m vgate_tpu.runtime.worker`` is the process the gateway's
PodEngine (runtime/pod_engine.py) spawns per worker slot when
``pod.workers > 0``: it builds the SAME engine stack the in-process
path builds (EngineCore, wrapped in EngineSupervisor + stall watchdog
when ``recovery.enabled``), binds a unix-domain or localhost-TCP
listener, and serves the length-prefixed JSON frame protocol
(runtime/rpc.py) to exactly one gateway connection.

Process-level contracts:

* **Fencing epoch** — the gateway assigns each worker *incarnation* a
  monotonically-increasing epoch (``--epoch``).  Every frame this
  process sends is stamped with it, and every inbound request frame is
  checked against it: a stale RPC (addressed to a previous incarnation
  of this slot) is answered with a typed ``WorkerFencedError`` reply
  and never touches the engine — the PR-5 stale-wake epoch guard,
  cross-process.
* **One connection, then exit (or orphan mode)** — the gateway owns
  the worker's lifecycle.  When the gateway connection reaches EOF
  (gateway died or declared this worker lost and moved on) and
  ``pod.orphan_grace_s`` is 0 (the default), the worker drains and
  exits rather than lingering as an unsupervised orphan; a respawn is
  always a fresh process with a fresh epoch.  With a grace > 0 the
  worker instead enters an explicit ORPHANED state: in-flight decodes
  run to completion (their token/done/err frames buffered, bounded,
  for ordered replay), new submits are refused with the typed
  retryable ``WorkerOrphanedError``, the registry record under the
  pod's socket dir keeps a liveness beat, and a successor gateway may
  re-accept the listener and take the incarnation over with the
  ``adopt`` verb (a bumped fencing epoch — stale successors are
  fenced).  Only when the grace expires does the worker self-terminate
  through the same drain fold as SIGTERM.
* **SIGTERM drain** — evacuate resident sequences (the PR-8 planned
  checkpoint fold), ship their checkpoints to the gateway in an
  ``evacuated`` notification, stop the engine, exit 0.
* **Engine thread never blocks on the network** — token/done/err
  frames are enqueued to a dedicated sender thread; a slow or dead
  gateway costs queue memory, never a stalled decode tick.

Wire protocol (all frames carry the fencing epoch ``"e"``):

* request:      ``{"op": <verb>, "id": n, "e": E, ...}`` → one reply
  ``{"op": "reply", "id": n, "e": E, "ok": bool, "data"|"error": ...}``
* notification (no ``"id"``, no reply): gateway→worker ``abort``,
  ``set_spec_suspended``, ``set_prefix_insert_suspended``;
  worker→gateway ``tok`` / ``done`` / ``err`` (keyed by the gateway's
  ``sid``) and ``evacuated``.
"""

from __future__ import annotations

import argparse
import base64
import binascii
import json
import logging
import os
import queue
import signal
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set

from vgate_tpu import faults, tracing
from vgate_tpu.analysis.annotations import requires_lock
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import VGTConfig, set_config
from vgate_tpu.errors import (
    HandoffStaleError,
    HandoffTransferError,
    WorkerFencedError,
    WorkerOrphanedError,
    state_is_alive,
    state_is_ready,
)
from vgate_tpu.logging_config import bound_request
from vgate_tpu.observability.reqtrace import RequestMeta, RequestTrace
from vgate_tpu.runtime import handoff as handoff_mod
from vgate_tpu.runtime import rpc
from vgate_tpu.runtime.sequence import Sequence, SeqStatus

logger = logging.getLogger(__name__)

# In-memory span recorder installed when the worker starts with
# VGT_MEMTRACE=1 (drills/tests): the ``spans`` verb exports what it
# recorded so cross-process span parentage is verifiable end to end.
_MEMTRACE: Optional[Any] = None

# Threading contract (scripts/vgt_lint.py, checker thread-discipline).
# Lock order: _send_lock is a LEAF — frame assembly happens before
# acquisition and nothing is called under it but socket.sendall.
# _seq_lock guards the sid→entry map; snapshot under it, act outside.
VGT_COMPONENTS: Dict[str, str] = {}
VGT_LOCK_GUARDS = {
    "_seqs": "_seq_lock",
    "_staged": "_seq_lock",
    "_xfers": "_seq_lock",
    "_xfer_committed": "_seq_lock",
    "_xfer_committing": "_seq_lock",
    "_orphan_frames": "_orphan_lock",
}

# Sender-queue ceiling: a gateway that stopped reading gets its worker
# torn down (queue overflow → connection abandoned) instead of growing
# the heap without bound.
_SEND_QUEUE_MAX = 8192

# Orphan-mode frame buffer ceiling (token frames only — done/err
# frames are kept unconditionally because the done frame carries the
# authoritative full text, which is what the successor's idempotency
# replay serves).  Overflow drops the OLDEST token frame: ring
# semantics, bounded memory, and the terminal frame still reconstructs
# the result.
_ORPHAN_BUF_MAX = 4096

# notification ops that buffer while orphaned; replies never do — the
# adoption handshake itself must reach the wire
_ORPHAN_BUFFERED_OPS = frozenset({"tok", "done", "err", "evacuated"})


def wire_error(exc: BaseException) -> Dict[str, Any]:
    """Serialize an exception for a reply/err frame — class name keyed
    into the errors-module taxonomy so the gateway rebuilds the TYPED
    error (503-with-reason mapping intact), plus the retryable hint."""
    out: Dict[str, Any] = {
        "type": type(exc).__name__,
        "message": str(exc),
        "reason": getattr(exc, "reason", None),
    }
    retry_after = getattr(exc, "retry_after", None)
    if retry_after is not None:
        out["retry_after"] = float(retry_after)
    return out


def unwire_error(err: Dict[str, Any]) -> BaseException:
    """Rebuild a typed exception from a wire error dict.  Unknown or
    unconstructible types degrade to a generic RuntimeError carrying
    the original class name — never a crash in the error path."""
    from vgate_tpu import errors as _errors

    name = str(err.get("type", "RuntimeError"))
    message = str(err.get("message", ""))
    retry_after = err.get("retry_after")
    cls = getattr(_errors, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        try:
            if retry_after is not None:
                return cls(message, retry_after=float(retry_after))
            return cls(message)
        except TypeError:
            try:
                return cls(message)
            except TypeError:
                pass
    if retry_after is not None:
        return _errors.RetryableError(
            f"{name}: {message}", retry_after=float(retry_after)
        )
    return RuntimeError(f"{name}: {message}")


def params_from_wire(raw: Dict[str, Any]) -> SamplingParams:
    """SamplingParams from a JSON dict: unknown keys dropped (version
    skew tolerance), ``logit_bias`` keys re-coerced to int (JSON object
    keys are strings)."""
    import dataclasses

    fields = {f.name for f in dataclasses.fields(SamplingParams)}
    kwargs = {k: v for k, v in raw.items() if k in fields}
    bias = kwargs.get("logit_bias")
    if bias:
        kwargs["logit_bias"] = {int(k): float(v) for k, v in bias.items()}
    return SamplingParams(**kwargs)


def params_to_wire(params: SamplingParams) -> Dict[str, Any]:
    import dataclasses

    return dataclasses.asdict(params)


class _Entry:
    """One in-flight sequence's worker-side bookkeeping."""

    __slots__ = ("sid", "seq", "cancelled")

    def __init__(self, sid: int, seq: Sequence) -> None:
        self.sid = sid
        self.seq = seq
        self.cancelled = False  # evacuated/aborted: waiter stays silent


class _Staged:
    """One staged prefill→decode handoff (runtime/handoff.py) awaiting
    the gateway's pull transfer.  ``payload`` is a direct reference to
    the swap ticket's KV pytree taken ON the engine thread at stage
    time, so a later discard nulling the ticket's own reference cannot
    race the packing; ``epoch`` is the sequence's preempt_count at
    stage — any fold since invalidates every fetch (HandoffStaleError).
    ``blob``/``digest`` cache the packed wire form lazily (first
    fetch)."""

    __slots__ = (
        "sid", "seq", "payload", "num_pages", "nbytes", "epoch",
        "blob", "digest",
    )

    def __init__(
        self, sid: int, seq: Sequence, payload: Any,
        num_pages: int, nbytes: int, epoch: int,
    ) -> None:
        self.sid = sid
        self.seq = seq
        self.payload = payload
        self.num_pages = num_pages
        self.nbytes = nbytes
        self.epoch = epoch
        self.blob: Optional[bytes] = None
        self.digest: Optional[int] = None


class WorkerServer:
    """The worker main object: engine + one-connection frame server."""

    def __init__(
        self,
        config: VGTConfig,
        epoch: int,
        index: int,
        registry_dir: Optional[str] = None,
        address: Optional[str] = None,
    ) -> None:
        self.config = config
        self.epoch = int(epoch)
        self.index = int(index)
        self.max_frame_bytes = int(config.pod.max_frame_bytes)
        # Gateway-crash survivability (pod.orphan_grace_s): registry
        # record + liveness beat so a successor gateway can find and
        # adopt this incarnation; orphan frame buffer for ordered
        # replay after adoption.
        self.registry_dir = registry_dir
        self.address = address
        self.orphan_grace_s = float(config.pod.orphan_grace_s)
        self._orphan_lock = threading.Lock()
        self._orphan_frames: List[Dict[str, Any]] = []
        self._orphan_tok_count = 0
        self._orphan_buffering = False
        self._orphaned = False
        self._orphan_deadline: Optional[float] = None
        self._adoptions = 0
        self._exit_reason: Optional[str] = None
        self._exit_recorded = False
        self._started_t = time.time()
        self._build_engine()
        self._seq_lock = threading.Lock()
        self._seqs: Dict[int, _Entry] = {}
        # Disaggregated prefill/decode (pod.roles) handoff state.  On a
        # prefill worker, _staged holds packed-KV staging records keyed
        # by sid; on a decode worker, _xfers holds in-progress chunk
        # reassemblies keyed by the gateway's per-attempt transfer id.
        # _xfer_committed remembers recently-committed transfer ids so
        # a gateway retry after a lost commit reply is answered
        # idempotently instead of double-admitting; _xfer_committing
        # rejects a CONCURRENT duplicate commit (two admissions of the
        # same sequence would diverge).
        self._staged: Dict[int, _Staged] = {}
        self._xfers: Dict[str, handoff_mod.ChunkAssembler] = {}
        self._xfer_committed: Set[str] = set()
        self._xfer_committing: Set[str] = set()
        self._staging_cap = max(
            int(config.pod.transfer_staging_bytes),
            int(config.kv_cache.host_swap_bytes),
        )
        self._send_lock = threading.Lock()
        self._send_q: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=_SEND_QUEUE_MAX
        )
        self._conn: Optional[socket.socket] = None
        self._stopping = threading.Event()
        self._fenced_rejects = 0

    # ------------------------------------------------------------ engine

    def _build_engine(self) -> None:
        # import here so ``--help`` / unit tests of the wire helpers
        # never pay the jax import
        from vgate_tpu.runtime.engine_core import EngineCore

        t0 = time.perf_counter()
        if self.config.recovery.enabled:
            from vgate_tpu.runtime.supervisor import EngineSupervisor

            self.engine: Any = EngineSupervisor(self.config)
        else:
            self.engine = EngineCore(self.config)
        self.engine.start()
        self.boot_s = time.perf_counter() - t0

    def _inner(self) -> Any:
        """The live EngineCore behind an optional supervisor wrapper —
        for surfaces the supervisor deliberately refuses or does not
        re-export (evacuate, the raw heartbeat)."""
        return getattr(self.engine, "core", self.engine)

    # ------------------------------------------------------------- wire out

    def _stamp(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        frame["e"] = self.epoch
        return frame

    def _enqueue(self, frame: Dict[str, Any]) -> None:
        """Queue a frame for the sender thread (never blocks the engine
        thread; overflow abandons the connection — the gateway has
        stopped reading and will declare us lost anyway).  While
        orphaned, notification frames are buffered UN-encoded instead
        (the epoch is stamped at encode time, so replay after adoption
        carries the successor's epoch, not the dead gateway's)."""
        if frame.get("op") in _ORPHAN_BUFFERED_OPS:
            with self._orphan_lock:
                if self._orphan_buffering:
                    self._buffer_orphan_frame_locked(frame)
                    return
        self._enqueue_wire(frame)

    @requires_lock("_orphan_lock")
    def _buffer_orphan_frame_locked(self, frame: Dict[str, Any]) -> None:
        if frame.get("op") == "tok":
            if self._orphan_tok_count >= _ORPHAN_BUF_MAX:
                # ring: drop the OLDEST token frame; the done frame's
                # full text survives regardless
                for i, old in enumerate(self._orphan_frames):
                    if old.get("op") == "tok":
                        del self._orphan_frames[i]
                        self._orphan_tok_count -= 1
                        break
            self._orphan_tok_count += 1
        self._orphan_frames.append(frame)

    def _enqueue_wire(self, frame: Dict[str, Any]) -> None:
        try:
            data = rpc.encode_frame(self._stamp(frame), self.max_frame_bytes)
        except rpc.FrameError:
            logger.error("outbound frame oversized; dropped", exc_info=True)
            return
        try:
            self._send_q.put_nowait(data)
        except queue.Full:
            logger.error(
                "sender queue overflow (gateway not reading); "
                "abandoning connection"
            )
            self._teardown_conn()

    def _sender_loop(self) -> None:
        while True:
            data = self._send_q.get()
            if data is None:
                return
            conn = self._conn
            if conn is None:
                continue
            try:
                # faults wire probe applies at the frame layer via
                # send_frame for requests; raw pre-encoded frames go
                # through the same probe here so token streams are
                # chaos-coverable too
                if faults.is_active():
                    verdict = faults.wire_action("rpc_send")
                    if verdict == "drop":
                        continue
                    if verdict == "garble":
                        data = rpc._garble(data)
                with self._send_lock:
                    conn.sendall(data)
            except OSError:
                self._teardown_conn()

    def _teardown_conn(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, cid: Any, data: Any) -> None:
        self._enqueue({"op": "reply", "id": cid, "ok": True, "data": data})

    def _reply_err(self, cid: Any, exc: BaseException) -> None:
        self._enqueue(
            {"op": "reply", "id": cid, "ok": False, "error": wire_error(exc)}
        )

    # ------------------------------------------------------------- verbs

    def _verb_hello(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        inner = self._inner()
        geometry = inner.geometry
        return {
            "pid": os.getpid(),
            "epoch": self.epoch,
            "index": self.index,
            "model": inner.spec.name,
            "vocab_size": int(inner.spec.vocab_size),
            "mesh": {k: int(v) for k, v in inner.mesh.shape.items()},
            "geometry": {
                "num_pages": int(geometry.num_pages),
                "page_size": int(getattr(geometry, "page_size", 0)),
                "kv_dtype": getattr(geometry, "kv_dtype", None),
            },
            "kv_dtype": getattr(geometry, "kv_dtype", None),
            "load_time_s": float(
                getattr(inner, "load_time_s", 0.0) or 0.0
            ),
            "boot_s": self.boot_s,
            "device_health": inner.device_health(),
        }

    def _verb_ping(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Liveness + engine beat + pressure in one cheap round-trip —
        the gateway's monitor classifies the beat with the PR-5
        classifier (compile-grace-aware), so the worker only reports
        raw age, never a verdict."""
        inner = self._inner()
        now = time.monotonic()
        beat = getattr(inner, "_heartbeat", None) or {}
        data: Dict[str, Any] = {
            "state": self._state(),
            "fenced_rejects": self._fenced_rejects,
            "orphaned": self._orphaned,
            "adoptions": self._adoptions,
        }
        if beat:
            data["beat"] = {
                "age_s": max(0.0, now - float(beat.get("t", now))),
                "kind": beat.get("kind"),
                "compiling": bool(beat.get("compiling", False)),
            }
        try:
            data["pressure"] = self.engine.pressure_signals()
        except Exception:
            pass
        with self._seq_lock:
            data["inflight"] = len(self._seqs)
        return data

    def _state(self) -> str:
        state = getattr(self.engine, "state", None)
        if state is not None:
            return state.value
        if getattr(self.engine, "_fatal", None) is not None:
            return "dead"
        return "serving"

    def _attach_trace(self, seq: Sequence, frame: Dict[str, Any]) -> None:
        """Rebuild the gateway's trace identity on a submitted sequence.

        ``submit_existing`` (unlike ``submit_tokens``) constructs no
        RequestTrace — it was built for in-process replays that already
        carry one.  A gateway submit is client traffic crossing a
        process boundary, so the engine spans this worker emits
        (engine.queue/prefill/decode/detokenize) would otherwise be
        orphaned roots: decode the W3C ``traceparent`` the gateway
        stamped on the frame into a remote parent context and open the
        queue span at the sequence's local arrival anchor.  Degrades to
        a silent no-op when the recorder is off or the frame carries no
        (or a malformed) trace header."""
        flight = getattr(self._inner(), "flight", None)
        if flight is None or not flight.enabled:
            return
        ctx = tracing.context_from_traceparent(frame.get("traceparent"))
        meta = RequestMeta(
            request_id=frame.get("request_id"), trace_ctx=ctx
        )
        seq.trace = RequestTrace(meta)
        seq.trace.start("queue", start_pc=seq.arrival_t)

    def _verb_submit(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        if self._orphaned:
            # an orphan that took new work could never be reconciled
            # against the successor gateway's journal
            raise WorkerOrphanedError(
                "worker is orphaned (gateway gone, grace running): "
                "finishing in-flight decodes, accepting no new submits"
            )
        sid = int(frame["sid"])
        raw_params = dict(frame.get("params") or {})
        remaining_s = frame.get("remaining_s")
        if remaining_s is not None:
            # the gateway ships the REMAINING budget so the absolute
            # deadline survives the process hop (clock domains differ);
            # fold it in before construction — SamplingParams is frozen
            raw_params["timeout_s"] = max(0.01, float(remaining_s))
        params = params_from_wire(raw_params)
        prompt_ids = [int(t) for t in frame.get("prompt_ids") or []]
        generated = [int(t) for t in frame.get("generated_ids") or []]
        handoff = bool(frame.get("handoff"))
        if handoff:
            # re-arm on every handoff submit: cheap, and it survives a
            # supervisor core rebuild (the rebuilt core starts with the
            # callback unset)
            self._inner().on_handoff_staged = self._on_handoff_staged

        entry_cell: List[_Entry] = []

        def on_token(token: int) -> None:
            entry = entry_cell[0]
            if entry.cancelled:
                return
            lp = None
            seq = entry.seq
            # _attach_logprob runs before append_token on every engine
            # path, so the just-appended token's data is the last entry
            if seq.params.logprobs and len(seq.logprob_data) >= len(
                seq.generated_ids
            ):
                lp = seq.logprob_data[len(seq.generated_ids) - 1]
            self._enqueue(
                {"op": "tok", "sid": sid, "t": int(token), "lp": lp}
            )

        # Build the Sequence ourselves (both fresh and resubmit paths)
        # and admit it via submit_existing: the entry is fully wired
        # BEFORE the engine thread can fire on_token, and a resubmit's
        # fold (prefill-continue; RNG continuation is implicit — see
        # SequenceCheckpoint's docstring) is just the generated prefix.
        seq = Sequence(
            prompt_ids=prompt_ids + generated,
            params=params,
            generated_ids=list(generated),
            orig_prompt_len=len(prompt_ids),
            resume_count=int(frame.get("resume_count", 0)),
            migrate_count=int(frame.get("migrate_count", 0)),
            preempt_count=int(frame.get("preempt_count", 0)),
            request_id=frame.get("request_id"),
            kv_dtype=frame.get("kv_dtype"),
            stream_cb=on_token,
        )
        seq.handoff_requested = handoff
        self._attach_trace(seq, frame)
        entry = _Entry(sid, seq)
        entry_cell.append(entry)
        # supervisor deployments: apply the same admission gate
        # submit_tokens runs (health state + poison quarantine) —
        # submit_existing deliberately skips it for in-process replays,
        # but a gateway submit is client traffic
        gate = getattr(self.engine, "_gate", None)
        if gate is not None:
            gate(list(prompt_ids))
        with self._seq_lock:
            self._seqs[sid] = entry
        try:
            self.engine.submit_existing(seq)
        except BaseException:
            with self._seq_lock:
                self._seqs.pop(sid, None)
            raise
        with bound_request(
            seq.request_id, getattr(seq.trace, "trace_id", None)
        ):
            # bound so a grep by the gateway's X-Request-ID finds the
            # worker-side admission too, not just the gateway log line
            logger.info(
                "submitted gateway sequence",
                extra={
                    "extra_data": {
                        "sid": sid,
                        "seq_id": seq.seq_id,
                        "prompt_tokens": len(prompt_ids),
                        "handoff": handoff,
                    }
                },
            )
        threading.Thread(
            target=self._waiter, args=(entry,), daemon=True,
            name=f"vgt-worker-waiter-{sid}",
        ).start()
        return {"sid": sid, "seq_id": seq.seq_id}

    def _waiter(self, entry: _Entry) -> None:
        """Settle observer for one sequence: ships the terminal frame
        when the engine finishes/fails it.  Polling wait so an
        evacuation (which never settles the sequence) releases the
        thread via the cancelled flag."""
        seq = entry.seq
        while not seq.done_event.wait(timeout=0.5):
            if entry.cancelled or self._stopping.is_set():
                return
        if entry.cancelled:
            return
        with self._seq_lock:
            self._seqs.pop(entry.sid, None)
        with bound_request(
            seq.request_id, getattr(seq.trace, "trace_id", None)
        ):
            logger.info(
                "sequence settled",
                extra={
                    "extra_data": {
                        "sid": entry.sid,
                        "status": seq.status.name,
                        "generated_tokens": seq.num_generated,
                        "finish_reason": seq.finish_reason,
                    }
                },
            )
        if seq.status is SeqStatus.FAILED:
            self._enqueue(
                {
                    "op": "err",
                    "sid": entry.sid,
                    "error": wire_error(
                        seq.error or RuntimeError("unknown failure")
                    ),
                }
            )
            return
        lp = list(seq.logprob_data) if seq.params.logprobs else None
        self._enqueue(
            {
                "op": "done",
                "sid": entry.sid,
                "finish_reason": seq.finish_reason,
                "text": self.engine.final_text(seq),
                "lp": lp,
                "resume_count": seq.resume_count,
                "migrate_count": seq.migrate_count,
                "preempt_count": seq.preempt_count,
            }
        )

    def _verb_abort(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        sid = int(frame["sid"])
        reason = str(frame.get("reason", "client_disconnect"))
        with self._seq_lock:
            entry = self._seqs.get(sid)
            # an aborted staged handoff will never be fetched again; the
            # scheduler's abort path reaps the swap ticket itself
            self._staged.pop(sid, None)
        if entry is not None and entry.seq is not None:
            entry.seq.request_abort(reason)
        return {"aborted": entry is not None}

    # ------------------------------------------------- handoff (pod.roles)
    #
    # Prefill side: the engine stages a finished prefill (KV folded to
    # the PR-11 host pool) and fires on_handoff_staged on its own
    # thread; we notify the gateway, which pulls the packed KV in
    # chunks (handoff_fetch) and finally tells us the outcome
    # (handoff_done / handoff_cancel).  Decode side: the gateway pushes
    # chunks (handoff_put) and commits (handoff_commit) — an atomic,
    # idempotent admission that adopts the KV pages with zero
    # recompute.  All transfer corruption surfaces as TYPED errors
    # (HandoffTransferError / HandoffStaleError); the gateway owns
    # retry and monolithic fallback.

    def _on_handoff_staged(self, seq: Sequence, staged: bool) -> None:
        """EngineCore callback, runs ON the engine thread: register the
        staging record and notify the gateway (or report fallback if
        the engine could not stage)."""
        with self._seq_lock:
            entry = None
            for e in self._seqs.values():
                if e.seq is seq:
                    entry = e
                    break
        if entry is None or entry.cancelled:
            return
        if not staged:
            self._enqueue({"op": "handoff_fallback", "sid": entry.sid})
            return
        ticket = getattr(seq, "_swap_ticket", None)
        if ticket is None or ticket.payload is None:
            # staged but the ticket vanished (defensive): tell the
            # gateway to fall back; the engine's release path resumes
            # local decode
            self._inner().handoff_cancel(seq)
            self._enqueue({"op": "handoff_fallback", "sid": entry.sid})
            return
        st = _Staged(
            entry.sid, seq, ticket.payload, int(ticket.num_pages),
            int(ticket.nbytes), int(seq.preempt_count),
        )
        with self._seq_lock:
            self._staged[entry.sid] = st
        self._enqueue(
            {
                "op": "handoff_staged",
                "sid": entry.sid,
                "pages": st.num_pages,
                "nbytes": st.nbytes,
                "base_len": len(seq.prompt_ids),
                "generated_ids": [int(t) for t in seq.generated_ids],
                "resume_count": seq.resume_count,
                "migrate_count": seq.migrate_count,
                "preempt_count": seq.preempt_count,
                "swap_count": seq.swap_count,
                "kv_dtype": seq.kv_dtype,
            }
        )

    def _verb_handoff_fetch(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Serve one chunk of the staged, packed KV blob.  Validity is
        re-checked per fetch: any fold/abort since staging (supervisor
        replay, deadline abort) invalidates the bytes — stale KV must
        never leave this process."""
        sid = int(frame["sid"])
        off = int(frame.get("off", 0))
        n = int(frame.get("n", 0))
        with self._seq_lock:
            st = self._staged.get(sid)
        if st is None:
            raise HandoffStaleError(f"no staged handoff for sid {sid}")
        seq = st.seq
        if (
            not getattr(seq, "_handoff_hold", False)
            or seq.preempt_count != st.epoch
            or seq.status is not SeqStatus.WAITING
        ):
            with self._seq_lock:
                self._staged.pop(sid, None)
            raise HandoffStaleError(
                f"staged handoff for sid {sid} invalidated "
                f"(status={seq.status.name}, epoch {seq.preempt_count} "
                f"vs staged {st.epoch})"
            )
        blob = st.blob
        digest = st.digest
        if blob is None:
            packed = handoff_mod.pack_payload(st.payload)
            packed_digest = handoff_mod.payload_digest(packed)
            # CAS under the lock: a retry racing a timed-out fetch may
            # pack concurrently; first publication wins so every chunk
            # of one transfer comes from ONE byte-identical blob
            with self._seq_lock:
                if st.blob is None:
                    st.blob = packed
                    st.digest = packed_digest
                blob = st.blob
                digest = st.digest
        if off < 0 or off > len(blob):
            raise HandoffTransferError(
                f"fetch offset {off} out of bounds (blob {len(blob)}B)"
            )
        # b64 expands 4/3; leave frame headroom for the JSON envelope
        limit = max(1, (self.max_frame_bytes * 3) // 5)
        n = min(n if n > 0 else limit, limit)
        data = base64.b64encode(blob[off:off + n]).decode("ascii")
        return {
            "total": len(blob),
            "digest": digest,
            "pages": st.num_pages,
            "data": data,
        }

    def _verb_handoff_cancel(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Gateway gave up on the transfer: drop staging and resume the
        sequence locally (monolithic decode via swap-in, zero
        recompute)."""
        sid = int(frame["sid"])
        with self._seq_lock:
            st = self._staged.pop(sid, None)
            entry = self._seqs.get(sid)
        if st is not None and entry is not None and not entry.cancelled:
            self._inner().handoff_cancel(st.seq)
            return {"resumed": True}
        return {"resumed": False}

    def _verb_handoff_done(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Transfer accepted by the decode worker: this worker's copy is
        now surplus.  Cancel the entry (the waiter stays silent — the
        sequence never settles here; the gateway owns the client) and
        let the engine evacuate the held sequence + discard its swap
        ticket."""
        sid = int(frame["sid"])
        with self._seq_lock:
            st = self._staged.pop(sid, None)
            entry = self._seqs.pop(sid, None)
        if entry is not None:
            entry.cancelled = True
        if st is not None:
            self._inner().handoff_done(st.seq)
        return {"ok": st is not None}

    def _verb_handoff_put(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Accept one chunk of an inbound KV transfer (decode side).
        Byte-identical redelivery is idempotent; conflicting overlap,
        truncation past total, or undecodable data is a typed error."""
        xid = str(frame["xfer"])
        off = int(frame.get("off", 0))
        total = int(frame.get("total", 0))
        try:
            data = base64.b64decode(
                str(frame.get("data", "")), validate=True
            )
        except (binascii.Error, ValueError) as exc:
            raise HandoffTransferError(
                f"undecodable transfer chunk: {exc}"
            ) from exc
        with self._seq_lock:
            if xid in self._xfer_committed:
                return {"got": total, "dup": True}
            asm = self._xfers.get(xid)
            if asm is None:
                asm = handoff_mod.ChunkAssembler(total, self._staging_cap)
                self._xfers[xid] = asm
        if asm.total != total:
            raise HandoffTransferError(
                f"transfer {xid}: total mismatch "
                f"({total} vs first-seen {asm.total})"
            )
        got = asm.put(off, data)
        return {"got": got}

    def _verb_handoff_commit(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Finalize an inbound transfer: verify completeness + digest,
        unpack the KV pytree, and admit the sequence with the adopted
        pages (zero recompute).  Idempotent on retry; a concurrent
        duplicate is refused (double admission would diverge)."""
        xid = str(frame["xfer"])
        sid = int(frame["sid"])
        with self._seq_lock:
            if xid in self._xfer_committed or sid in self._seqs:
                # retry of a commit whose reply was lost — the sequence
                # is already (or still) admitted; re-accepting is a
                # no-op for the gateway
                return {"accepted": True, "dup": True}
            if xid in self._xfer_committing:
                raise HandoffTransferError(
                    f"transfer {xid}: commit already in progress"
                )
            self._xfer_committing.add(xid)
        try:
            return self._handoff_commit_locked_out(xid, sid, frame)
        finally:
            with self._seq_lock:
                self._xfer_committing.discard(xid)

    def _handoff_commit_locked_out(
        self, xid: str, sid: int, frame: Dict[str, Any]
    ) -> Dict[str, Any]:
        with self._seq_lock:
            asm = self._xfers.get(xid)
        if asm is None:
            raise HandoffTransferError(f"unknown transfer {xid}")
        blob = asm.complete()  # typed error on gaps → gateway retries
        want_digest = int(frame.get("digest", 0))
        got_digest = handoff_mod.payload_digest(blob)
        if got_digest != want_digest:
            # drop the assembler so the retry rebuilds from scratch —
            # we cannot tell WHICH chunk was garbled
            with self._seq_lock:
                self._xfers.pop(xid, None)
            raise HandoffTransferError(
                f"transfer {xid}: payload digest mismatch "
                f"(got {got_digest}, want {want_digest})"
            )
        payload = handoff_mod.unpack_payload(blob)

        raw_params = dict(frame.get("params") or {})
        remaining_s = frame.get("remaining_s")
        if remaining_s is not None:
            raw_params["timeout_s"] = max(0.01, float(remaining_s))
        params = params_from_wire(raw_params)
        prompt_ids = [int(t) for t in frame.get("prompt_ids") or []]
        generated = [int(t) for t in frame.get("generated_ids") or []]
        base_len = int(frame.get("base_len", len(prompt_ids)))
        num_pages = int(frame.get("pages", 0))
        full = prompt_ids + generated
        if base_len <= 0 or base_len > len(full):
            raise HandoffTransferError(
                f"transfer {xid}: base_len {base_len} out of range"
            )
        inner = self._inner()
        page_size = int(getattr(inner.geometry, "page_size", 0) or 1)
        want_pages = (max(1, len(full) - 1) + page_size - 1) // page_size
        if num_pages != want_pages:
            raise HandoffTransferError(
                f"transfer {xid}: page-count mismatch "
                f"({num_pages} shipped, geometry wants {want_pages})"
            )

        entry_cell: List[_Entry] = []

        def on_token(token: int) -> None:
            entry = entry_cell[0]
            if entry.cancelled:
                return
            lp = None
            seq = entry.seq
            if seq.params.logprobs and len(seq.logprob_data) >= len(
                seq.generated_ids
            ):
                lp = seq.logprob_data[len(seq.generated_ids) - 1]
            self._enqueue(
                {"op": "tok", "sid": sid, "t": int(token), "lp": lp}
            )

        # swap-shape construction: prompt/output split at the PREFILL
        # worker's fold point so total_len ↔ shipped page count agree;
        # orig_prompt_len keeps the client-visible text boundary
        seq = Sequence(
            prompt_ids=full[:base_len],
            params=params,
            output_ids=full[base_len:],
            generated_ids=list(generated),
            orig_prompt_len=len(prompt_ids),
            resume_count=int(frame.get("resume_count", 0)),
            migrate_count=int(frame.get("migrate_count", 0)),
            preempt_count=int(frame.get("preempt_count", 0)),
            swap_count=int(frame.get("swap_count", 0)),
            handoff_count=int(frame.get("handoff_count", 1)),
            request_id=frame.get("request_id"),
            kv_dtype=frame.get("kv_dtype"),
            stream_cb=on_token,
        )
        seq._handoff_adopt = (payload, num_pages)
        self._attach_trace(seq, frame)
        entry = _Entry(sid, seq)
        entry_cell.append(entry)
        gate = getattr(self.engine, "_gate", None)
        if gate is not None:
            gate(list(prompt_ids))
        with self._seq_lock:
            self._seqs[sid] = entry
        try:
            self.engine.submit_existing(seq)
        except BaseException:
            with self._seq_lock:
                self._seqs.pop(sid, None)
            raise
        with bound_request(
            seq.request_id, getattr(seq.trace, "trace_id", None)
        ):
            logger.info(
                "handoff commit: adopted sequence",
                extra={
                    "extra_data": {
                        "sid": sid,
                        "xfer": xid,
                        "pages": num_pages,
                        "generated_tokens": len(generated),
                    }
                },
            )
        threading.Thread(
            target=self._waiter, args=(entry,), daemon=True,
            name=f"vgt-worker-waiter-{sid}",
        ).start()
        with self._seq_lock:
            self._xfers.pop(xid, None)
            self._xfer_committed.add(xid)
            if len(self._xfer_committed) > 4096:
                self._xfer_committed.clear()
        return {"accepted": True, "seq_id": seq.seq_id}

    def _verb_handoff_abort(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Drop a partial inbound transfer (gateway retry or give-up).
        Post-commit cancellation goes through the normal abort verb —
        the sequence is registered in _seqs by then."""
        xid = str(frame["xfer"])
        with self._seq_lock:
            dropped = self._xfers.pop(xid, None) is not None
        return {"dropped": dropped}

    def _verb_abort_all(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        fn = getattr(self.engine, "abort_in_flight", None)
        if fn is not None:
            fn(str(frame.get("reason", "drain")))
        return {}

    def _verb_evacuate(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """PR-8 planned movement across the process boundary: checkpoint
        the named (or all) resident sequences without a fatal; the
        gateway owns the replay.  Bypasses the supervisor's dp=1
        refusal deliberately — here there IS a migration target, it
        just lives in another process."""
        sids = frame.get("sids")
        reason = str(frame.get("reason", "drain"))
        # "timeout_s" on the wire: the bare name would collide with the
        # client-side call() deadline kwarg
        timeout = float(frame.get("timeout_s", 30.0))
        with self._seq_lock:
            entries = dict(self._seqs)
        if sids is not None:
            wanted = {int(s) for s in sids}
            entries = {s: e for s, e in entries.items() if s in wanted}
        seq_ids = [
            e.seq.seq_id for e in entries.values() if e.seq is not None
        ]
        evacuated = self._inner().evacuate(
            None if sids is None else seq_ids,
            reason=reason,
            timeout=timeout,
        )
        out = []
        by_seq_id = {
            e.seq.seq_id: e for e in entries.values() if e.seq is not None
        }
        for seq in evacuated:
            entry = by_seq_id.get(seq.seq_id)
            if entry is None:
                continue
            entry.cancelled = True
            with self._seq_lock:
                self._seqs.pop(entry.sid, None)
            out.append({"sid": entry.sid, **seq.checkpoint().as_dict()})
        return {"evacuated": out}

    def _verb_health(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        health_fn = getattr(self.engine, "health", None)
        if health_fn is not None:
            return health_fn()
        state = self._state()
        return {
            "state": state,
            "alive": state_is_alive(state),
            "ready": state_is_ready(state),
        }

    def _verb_stats(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        return self.engine.get_stats()

    def _verb_pressure(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        return self.engine.pressure_signals()

    def _verb_flight(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Flight-recorder tick ring + stats for the gateway's merged
        pod view (/debug/flight).  Bounded by the recorder's own ring
        size, so the reply always fits the frame cap."""
        flight = getattr(self._inner(), "flight", None)
        if flight is None:
            return {"enabled": False, "ticks": [], "stats": {}}
        n = frame.get("n")
        return {
            "enabled": bool(flight.enabled),
            "ticks": flight.ticks(int(n) if n is not None else None),
            "stats": flight.get_stats(),
        }

    def _verb_requests(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Per-request flight records (live + completed) for the
        gateway's merged /debug/requests view."""
        flight = getattr(self._inner(), "flight", None)
        if flight is None:
            return {"enabled": False, "live": [], "completed": []}
        n = frame.get("n")
        return {
            "enabled": bool(flight.enabled),
            "live": flight.live_requests(),
            "completed": flight.requests(
                int(n) if n is not None else None
            ),
        }

    def _verb_spans(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Export memtrace-recorded spans (drill/test tooling: empty
        unless the pod was launched with VGT_MEMTRACE=1) so span
        parentage across the RPC boundary is verifiable from outside
        this process."""
        rec = _MEMTRACE
        if rec is None:
            return {"enabled": False, "spans": []}
        out = []
        for s in rec.spans():
            out.append(
                {
                    "name": s.name,
                    "trace_id": s.trace_id_hex,
                    "span_id": s.span_id_hex,
                    "parent_span_id": s.parent_span_id_hex,
                    "start_ns": s.start_time,
                    "end_ns": s.end_time,
                    "attributes": {
                        k: v
                        for k, v in s.attributes.items()
                        if isinstance(v, (str, int, float, bool))
                    },
                }
            )
        return {"enabled": True, "spans": out}

    def _verb_perf(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        fn = getattr(self._inner(), "perf_snapshot", None)
        return fn() if fn is not None else {}

    def _verb_warmup(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        buckets = frame.get("buckets")
        return {"seconds": float(self._inner().warmup(buckets))}

    def _verb_canary(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Pinned greedy self-probe (PR-9), run on demand for the
        gateway's respawn gate: returns the output fingerprint; the
        gateway compares against the fleet's recorded one."""
        from vgate_tpu.integrity import (
            canary_fingerprint,
            canary_prompt_ids,
        )

        inner = self._inner()
        cfg = self.config.integrity
        ids = canary_prompt_ids(
            inner.spec.vocab_size, cfg.canary_prompt_len
        )
        params = SamplingParams(
            temperature=0.0, max_tokens=cfg.canary_max_tokens
        )
        seq = Sequence(prompt_ids=ids, params=params, canary=True)
        timeout = cfg.canary_timeout_s
        if getattr(inner, "total_steps", 1) == 0:
            timeout += cfg.canary_compile_grace_s
        inner.submit_existing(seq)
        if not seq.done_event.wait(timeout=timeout):
            seq.request_abort(reason="drain")
            raise TimeoutError(
                f"canary self-probe timed out after {timeout}s"
            )
        if seq.status is SeqStatus.FAILED:
            raise RuntimeError(
                f"canary self-probe failed: {seq.error}"
            )
        out = list(seq.generated_ids)
        return {
            "fingerprint": canary_fingerprint(out),
            "tokens": len(out),
        }

    def _verb_set_spec_suspended(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        fn = getattr(self.engine, "set_spec_suspended", None)
        if fn is not None:
            fn(bool(frame.get("flag", False)))
        return {}

    def _verb_set_prefix_insert_suspended(
        self, frame: Dict[str, Any]
    ) -> Dict[str, Any]:
        fn = getattr(self.engine, "set_prefix_insert_suspended", None)
        if fn is not None:
            fn(bool(frame.get("flag", False)))
        return {}

    def _verb_stop(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        self._exit_reason = self._exit_reason or "gateway_stop"
        self._stopping.set()
        return {"stopping": True}

    # ------------------------------------- orphan mode / adoption (PR 20)

    def _verb_orphan_status(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Read-only adoption-handshake probe — epoch-EXEMPT (a
        successor gateway holding a bumped epoch must be able to ask
        before it adopts)."""
        with self._seq_lock:
            inflight = len(self._seqs)
        with self._orphan_lock:
            buffered = len(self._orphan_frames)
        remaining = None
        if self._orphan_deadline is not None:
            remaining = max(0.0, self._orphan_deadline - time.monotonic())
        return {
            "pid": os.getpid(),
            "index": self.index,
            "epoch": self.epoch,
            "orphaned": self._orphaned,
            "orphan_grace_s": self.orphan_grace_s,
            "grace_remaining_s": remaining,
            "inflight": inflight,
            "buffered_frames": buffered,
            "adoptions": self._adoptions,
            "state": self._state(),
        }

    def _verb_adopt(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Take this incarnation over for a successor gateway.  Epoch-
        exempt from the strict-equality check, but the proposed epoch
        must be STRICTLY NEWER than the current one — a stale successor
        (or a double adopt racing a fresher one) is fenced exactly like
        a zombie worker frame.  The reply carries everything the
        successor needs to reconcile: in-flight sids with their request
        ids and progress, plus the buffered-frame count.  Buffered
        frames do NOT flush here — the successor registers the adopted
        sequences first and then sends ``orphan_flush``, so no frame
        can arrive before its sid is routable."""
        proposed = frame.get("e")
        if not isinstance(proposed, int):
            raise ValueError("adopt frame missing a fencing epoch")
        if proposed <= self.epoch:
            raise WorkerFencedError(
                f"adopt epoch {proposed} is not newer than the current "
                f"incarnation epoch {self.epoch}"
            )
        with self._orphan_lock:
            buffered = len(self._orphan_frames)
            buffered_toks: Dict[int, int] = {}
            for f in self._orphan_frames:
                if f.get("op") == "tok":
                    sid = f.get("sid")
                    buffered_toks[sid] = buffered_toks.get(sid, 0) + 1
        with self._seq_lock:
            inflight = [
                {
                    "sid": entry.sid,
                    "request_id": entry.seq.request_id,
                    # tokens already DELIVERED to the predecessor (total
                    # minus still-buffered): the successor pads its shell
                    # to this and the orphan_flush replay appends the
                    # rest, so its count reconciles to the true total
                    "generated_tokens": max(
                        0,
                        entry.seq.num_generated
                        - buffered_toks.get(entry.sid, 0),
                    ),
                    "cancelled": entry.cancelled,
                }
                for entry in self._seqs.values()
            ]
        was_orphaned = self._orphaned
        self.epoch = proposed
        self._orphaned = False
        self._orphan_deadline = None
        self._adoptions += 1
        logger.warning(
            "adopted by successor gateway",
            extra={
                "extra_data": {
                    "epoch": proposed,
                    "inflight": len(inflight),
                    "buffered_frames": buffered,
                    "was_orphaned": was_orphaned,
                }
            },
        )
        self._write_registry("serving")
        return {
            "pid": os.getpid(),
            "index": self.index,
            "epoch": self.epoch,
            "was_orphaned": was_orphaned,
            "inflight": inflight,
            "buffered_frames": buffered,
            "adoptions": self._adoptions,
        }

    def _verb_orphan_flush(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Replay the orphan-buffered frames in order (notification —
        the successor sends it AFTER registering the adopted sids).
        Drain-loop shape (the PR-17 handoff-buffer pattern): keep
        draining until a pass finds the buffer empty, THEN drop the
        buffering flag under the lock, so a frame enqueued concurrently
        by the engine thread can never jump ahead of a buffered one."""
        while True:
            with self._orphan_lock:
                frames = self._orphan_frames
                if not frames:
                    self._orphan_buffering = False
                    self._orphan_tok_count = 0
                    break
                self._orphan_frames = []
                self._orphan_tok_count = 0
            for buffered in frames:
                self._enqueue_wire(buffered)
        return {}

    def _enter_orphan_mode(self, reason: str) -> None:
        self._teardown_conn()
        with self._orphan_lock:
            self._orphan_buffering = True
        self._orphaned = True
        self._orphan_deadline = time.monotonic() + self.orphan_grace_s
        logger.warning(
            "gateway connection lost; entering orphan mode",
            extra={
                "extra_data": {
                    "reason": reason,
                    "grace_s": self.orphan_grace_s,
                    "epoch": self.epoch,
                }
            },
        )
        self._write_registry("orphaned")

    # ------------------------------------------------- registry records

    def _registry_path(self) -> Optional[str]:
        if not self.registry_dir:
            return None
        return os.path.join(self.registry_dir, f"w{self.index}.json")

    def _write_registry(
        self,
        status: Optional[str] = None,
        exit_reason: Optional[str] = None,
        checkpoints: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        """Atomically (re)write this worker's registry record.  The
        record is how a successor gateway finds a live orphan (socket
        path + pid + epoch + a liveness beat) and how an exited worker
        leaves post-mortem evidence (exit reason + final checkpoint
        summary) instead of silently vanishing from /debug/pod."""
        path = self._registry_path()
        if path is None:
            return
        if status is None:
            status = "orphaned" if self._orphaned else "serving"
        with self._seq_lock:
            inflight = len(self._seqs)
        remaining = None
        if self._orphan_deadline is not None:
            remaining = max(0.0, self._orphan_deadline - time.monotonic())
        record: Dict[str, Any] = {
            "pid": os.getpid(),
            "index": self.index,
            "epoch": self.epoch,
            "address": self.address,
            "status": status,
            "beat": time.time(),
            "started_t": self._started_t,
            "orphan_grace_s": self.orphan_grace_s,
            "grace_remaining_s": remaining,
            "inflight": inflight,
            "adoptions": self._adoptions,
        }
        if exit_reason is not None:
            record["exit_reason"] = exit_reason
        if checkpoints is not None:
            record["checkpoints"] = checkpoints
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(record, fh, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError:
            logger.warning("registry record write failed", exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _registry_beat_loop(self) -> None:
        """Refresh the registry beat while the worker lives — serving
        AND orphaned alike (a successor judges orphan liveness by this
        beat plus the pid)."""
        while not self._stopping.wait(1.0):
            self._write_registry()

    _SLOW_VERBS = frozenset(
        {
            "evacuate", "warmup", "canary", "stats", "perf",
            # fetch packs the KV pytree (CPU-bound, MBs); commit
            # unpacks + admits — neither may stall the ping path
            "handoff_fetch", "handoff_commit",
            # span export can serialize thousands of records
            "spans",
        }
    )

    _VERBS = {
        "hello": _verb_hello,
        "ping": _verb_ping,
        "submit": _verb_submit,
        "abort": _verb_abort,
        "abort_all": _verb_abort_all,
        "evacuate": _verb_evacuate,
        "handoff_fetch": _verb_handoff_fetch,
        "handoff_cancel": _verb_handoff_cancel,
        "handoff_done": _verb_handoff_done,
        "handoff_put": _verb_handoff_put,
        "handoff_commit": _verb_handoff_commit,
        "handoff_abort": _verb_handoff_abort,
        "health": _verb_health,
        "stats": _verb_stats,
        "pressure": _verb_pressure,
        "perf": _verb_perf,
        "flight": _verb_flight,
        "requests": _verb_requests,
        "spans": _verb_spans,
        "warmup": _verb_warmup,
        "canary": _verb_canary,
        "set_spec_suspended": _verb_set_spec_suspended,
        "set_prefix_insert_suspended": _verb_set_prefix_insert_suspended,
        "stop": _verb_stop,
        "orphan_status": _verb_orphan_status,
        "adopt": _verb_adopt,
        "orphan_flush": _verb_orphan_flush,
    }

    # Adoption-handshake verbs are exempt from the strict-equality
    # epoch check: a successor gateway NECESSARILY holds an epoch this
    # incarnation has never seen (it bumps before it adopts).  adopt
    # enforces strictly-newer itself; orphan_status is read-only.
    _EPOCH_EXEMPT_VERBS = frozenset({"adopt", "orphan_status"})

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, frame: Dict[str, Any]) -> None:
        cid = frame.get("id")
        try:
            if frame.get("op") not in self._EPOCH_EXEMPT_VERBS:
                rpc.check_epoch(frame, self.epoch)
        except rpc.StaleEpochError as exc:
            # a gateway (or tool) addressing a previous incarnation of
            # this slot: reject typed, never touch the engine
            self._fenced_rejects += 1
            logger.warning(
                "fenced stale RPC",
                extra={
                    "extra_data": {
                        "op": frame.get("op"),
                        "got": exc.got,
                        "want": exc.want,
                    }
                },
            )
            if cid is not None:
                self._reply_err(
                    cid,
                    WorkerFencedError(
                        f"stale fencing epoch {exc.got} "
                        f"(worker incarnation is {exc.want})"
                    ),
                )
            return
        except rpc.FrameError as exc:
            # epoch MISSING (vs merely stale): a structural violation —
            # same treatment, typed fence, never touch the engine, and
            # never let it escape into the reader loop
            self._fenced_rejects += 1
            if cid is not None:
                self._reply_err(cid, WorkerFencedError(str(exc)))
            return
        op = frame.get("op")
        handler = self._VERBS.get(op)  # type: ignore[arg-type]
        if handler is None:
            if cid is not None:
                self._reply_err(cid, ValueError(f"unknown verb {op!r}"))
            return
        if op in self._SLOW_VERBS:
            threading.Thread(
                target=self._run_verb,
                args=(handler, frame, cid),
                daemon=True,
                name=f"vgt-worker-{op}",
            ).start()
        else:
            # fast verbs run inline on the reader thread — ping latency
            # IS the liveness signal, it must not queue behind warmup
            self._run_verb(handler, frame, cid)

    def _run_verb(self, handler, frame: Dict[str, Any], cid: Any) -> None:
        try:
            data = handler(self, frame)
        except BaseException as exc:  # noqa: BLE001 — must reach the wire
            if cid is not None:
                self._reply_err(cid, exc)
            else:
                logger.error(
                    "notification verb failed",
                    extra={"extra_data": {"op": frame.get("op")}},
                    exc_info=True,
                )
            return
        if cid is not None:
            self._reply(cid, data)

    # -------------------------------------------------------------- serve

    def serve(self, listener: socket.socket) -> None:
        """Accept the gateway connection and serve frames until EOF,
        protocol violation, or drain.  At ``pod.orphan_grace_s == 0``
        (the default) that is the end of the process — the gateway
        respawns a fresh incarnation; this process never serves two
        connections.  With a grace > 0, EOF enters orphan mode instead
        and the listener stays open so a successor gateway can
        re-accept and adopt this incarnation; the process exits only
        when the grace expires unclaimed (or on drain/stop)."""
        sender = threading.Thread(
            target=self._sender_loop, daemon=True, name="vgt-worker-send"
        )
        sender.start()
        self._write_registry("serving")
        threading.Thread(
            target=self._registry_beat_loop, daemon=True,
            name="vgt-worker-beat",
        ).start()
        listener.settimeout(1.0)
        try:
            while not self._stopping.is_set():
                conn: Optional[socket.socket] = None
                while conn is None and not self._stopping.is_set():
                    if (
                        self._orphaned
                        and self._orphan_deadline is not None
                        and time.monotonic() >= self._orphan_deadline
                    ):
                        logger.warning(
                            "orphan grace expired unclaimed; draining"
                        )
                        self.drain(reason="orphan_expired")
                        return
                    try:
                        conn, _ = listener.accept()
                    except socket.timeout:
                        continue
                if conn is None:
                    return
                if self.orphan_grace_s <= 0:
                    # pre-orphan contract, byte-identical: one
                    # connection for the process lifetime
                    listener.close()
                self._conn = conn
                reason = self._read_conn(conn)
                if self._stopping.is_set():
                    return
                if self.orphan_grace_s <= 0:
                    # grace-0 gateway EOF still routes through the
                    # drain fold so the registry keeps post-mortem
                    # evidence (final checkpoint summary + exit reason)
                    self.drain(reason="gateway_eof")
                    return
                self._enter_orphan_mode(reason)
        finally:
            self.shutdown()

    def _read_conn(self, conn: socket.socket) -> str:
        """Serve one gateway connection until EOF / violation / stop;
        returns why the read loop ended."""
        while not self._stopping.is_set():
            try:
                frame = rpc.recv_frame(conn, self.max_frame_bytes)
            except rpc.FrameError:
                logger.error(
                    "frame protocol violation from gateway; "
                    "tearing down",
                    exc_info=True,
                )
                return "frame_error"
            except OSError:
                return "socket_error"
            if frame is None:
                return "gateway_eof"  # gateway closed: orphaned/replaced
            self._dispatch(frame)
        return "stopping"

    def drain(self, reason: str = "sigterm") -> None:
        """The one checkpoint-fold exit path — SIGTERM, gateway EOF at
        grace 0, and orphan-grace expiry all route through it:
        checkpoint residents, ship them to the gateway (``evacuated``
        notification — buffered when there is no gateway left), write
        the final checkpoint summary + exit reason into the registry
        record (post-mortem evidence even when nobody is listening),
        then stop.  Worker-loss during a pod drain therefore degrades
        exactly like ``_redistribute`` — the gateway replays from its
        own request state either way."""
        try:
            out = self._verb_evacuate({"reason": reason, "timeout_s": 10.0})
        except Exception:
            logger.warning("drain evacuation failed", exc_info=True)
            out = {"evacuated": []}
        summary = [
            {
                "sid": ck.get("sid"),
                "request_id": ck.get("request_id"),
                "generated_tokens": ck.get("generated_tokens"),
            }
            for ck in out.get("evacuated") or []
        ]
        self._exit_reason = self._exit_reason or reason
        self._write_registry(
            "exited", exit_reason=self._exit_reason, checkpoints=summary
        )
        self._exit_recorded = True
        self._enqueue({"op": "evacuated", "reason": reason, **out})
        # let the sender flush before teardown
        deadline = time.monotonic() + 2.0
        while not self._send_q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)
        self._stopping.set()

    def shutdown(self) -> None:
        self._stopping.set()
        if not self._exit_recorded:
            self._exit_recorded = True
            self._write_registry(
                "exited",
                exit_reason=self._exit_reason or "shutdown",
                checkpoints=[],
            )
        self._teardown_conn()
        self._send_q.put(None)
        try:
            self.engine.stop()
        except Exception:
            pass
        # release any waiter threads whose sequences will never settle
        with self._seq_lock:
            for entry in self._seqs.values():
                entry.cancelled = True
            self._seqs.clear()


def _bind_listener(args: argparse.Namespace) -> socket.socket:
    if args.socket:
        try:
            os.unlink(args.socket)
        except FileNotFoundError:
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(args.socket)
    else:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", args.port))
    listener.listen(1)
    return listener


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="vgate-tpu engine worker process"
    )
    group = parser.add_mutually_exclusive_group(required=True)
    group.add_argument("--socket", help="unix-domain socket path to bind")
    group.add_argument("--port", type=int, help="localhost TCP port to bind")
    parser.add_argument(
        "--epoch", type=int, required=True,
        help="fencing epoch of this incarnation (gateway-assigned)",
    )
    parser.add_argument(
        "--config", required=True,
        help="resolved gateway config, JSON (pod.workers forced to 0)",
    )
    parser.add_argument("--index", type=int, default=0, help="worker slot")
    parser.add_argument(
        "--registry-dir", default=None,
        help="directory for the worker registry record (orphan "
        "adoption); defaults to the socket's directory for UDS",
    )
    args = parser.parse_args(argv)

    with open(args.config) as fh:
        config = VGTConfig(**json.load(fh))
    # belt and braces: a worker must never recurse into pod mode, and a
    # worker process hosts exactly one engine
    config.pod.workers = 0
    config.tpu.dp = 1
    set_config(config)
    faults.arm_from_env()

    if os.environ.get("VGT_MEMTRACE"):
        # drill/test span evidence: record this process's spans so the
        # ``spans`` verb can export them for parentage assertions
        global _MEMTRACE
        try:
            from vgate_tpu.observability.memtrace import MemorySpanRecorder

            _MEMTRACE = MemorySpanRecorder().install()
        except Exception:
            logger.warning(
                "VGT_MEMTRACE set but span recorder install failed",
                exc_info=True,
            )

    logging.basicConfig(
        level=logging.INFO,
        format=(
            f"%(asctime)s worker[{args.index}"
            f".e{args.epoch}] %(levelname)s %(name)s: %(message)s"
        ),
        stream=sys.stderr,
    )

    registry_dir = args.registry_dir
    if registry_dir is None and args.socket:
        registry_dir = os.path.dirname(os.path.abspath(args.socket))
    address = args.socket or f"127.0.0.1:{args.port}"

    listener = _bind_listener(args)
    server = WorkerServer(
        config, epoch=args.epoch, index=args.index,
        registry_dir=registry_dir, address=address,
    )

    def _on_sigterm(signum, _frame) -> None:
        threading.Thread(
            target=server.drain, daemon=True, name="vgt-worker-drain"
        ).start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        server.serve(listener)
    finally:
        server.shutdown()
        if args.socket:
            try:
                os.unlink(args.socket)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
