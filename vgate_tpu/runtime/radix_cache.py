"""Radix-tree KV prefix cache over the paged pool (cross-request reuse).

Replaces the flat per-page hash chain (``PageAllocator.register/lookup``
+ ``Scheduler._prefix_chain``) as the prefix index when
``tpu.prefix_cache.radix`` is on.  The million-user workloads the
roadmap targets (multi-turn chat, RAG with shared system+corpus
preambles, agent loops re-sending growing transcripts) are dominated by
shared prefixes, and the flat chain can only match whole-page exact
chains of *prompt* pages.  The tree adds what those shapes need:

* **Longest-shared-prefix matching** by walking token-keyed nodes that
  hold runs of full KV pages, splitting a node at a partial match point
  so the shared part becomes a common ancestor (SGLang's RadixAttention
  structure, first-party here).
* **Generated-token reuse**: a finished sequence's full transcript
  (prompt + generation, minus the final token whose KV was never
  written) is inserted, so turn N+1 of a chat — which re-sends turn N's
  answer inside its prompt — hits pages the flat chain never indexed.
* **Copy-on-write partial pages**: when a request diverges from a
  cached page mid-page, the shared head of that page is device-copied
  into a fresh page (engine_core ``_cow_copy_pages``) and prefill
  starts at the unaligned boundary — up to ``page_size - 1`` more hit
  tokens per request than page-granular matching.
* **Pressure-integrated eviction**: refcount-0 subtrees are reclaimable
  LRU-leaf-first, on demand when ``PageAllocator.allocate`` runs short
  (reason ``lru``) and *proactively* when the truly-free ratio sinks
  below ``tpu.prefix_cache.evict_watermark`` (reason ``pressure``) —
  trimming runs before the gateway's admission controller would start
  shedding on ``kv_pressure``, so a warm cache never turns into 503s.

Sharing/locking model: every page indexed by the tree carries one
allocator reference owned by the tree; each sequence whose prefix
matched also holds its own allocator reference on the shared pages (the
scheduler releases ``seq.pages`` uniformly).  ``lock_ref`` counts, per
node, the live sequences whose matched path passes through it —
matching locks the whole path, so ``lock_ref == 0`` implies the entire
subtree is unreferenced by running work and is therefore reclaimable in
one sweep.  Pure host-side policy, no JAX: unit-testable like the
scheduler (tests/test_radix_cache.py drives randomized interleavings
against the allocator invariants).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Tuple

from vgate_tpu import metrics
from vgate_tpu.analysis.annotations import engine_thread_only
from vgate_tpu.logging_config import get_logger

logger = get_logger(__name__)

# Threading contract (scripts/vgt_lint.py, thread-discipline): tree
# mutation is engine-thread-only; cross-thread readers get plain-int
# gauges, never tree walks (the PR-6 hardening).
VGT_COMPONENTS = {"swap": "KVSwapManager"}


class RadixNode:
    """One run of full KV pages keyed by its token content.

    ``tokens`` always has exactly ``len(pages) * page_size`` entries;
    children are keyed by the tuple of their first page's tokens (two
    children of one node must differ somewhere inside their first page,
    or insert would have factored the common page into a shared node).
    """

    __slots__ = (
        "tokens", "pages", "children", "parent", "lock_ref", "last_access",
        "swapped",
    )

    def __init__(
        self,
        tokens: Tuple[int, ...],
        pages: List[int],
        parent: Optional["RadixNode"],
    ) -> None:
        self.tokens = tokens
        self.pages = pages
        self.children: Dict[Tuple[int, ...], "RadixNode"] = {}
        self.parent = parent
        self.lock_ref = 0
        self.last_access = 0
        # host-swap victim cache (runtime/kv_swap.py): when eviction
        # demoted this leaf's pages to the host pool, `pages` is empty
        # and this holds the SwapTicket; a later match() promotes the
        # content back into fresh device pages.  Invariant: a node has
        # pages XOR a swapped ticket (or neither transiently never —
        # the root alone is permanently page-less).  Swapped nodes are
        # always leaves: eviction is leaf-first and neither insert nor
        # split ever descends below one.
        self.swapped = None


class RadixMatch:
    """A successful prefix match: shared full pages (+ optional COW tail).

    Holds the DEEPEST matched node; the lock walk goes deepest →
    parent → … → root, so a later :meth:`RadixCache._split` of any node
    on the path keeps the accounting exact (the split head sits on the
    parent chain and inherits the tail's count — storing the node list
    instead would orphan the head's share on unlock).  ``cow_node``
    stays locked only until the copy program is dispatched
    (``release_cow``) — after that the source page may be evicted
    freely, the copy is already in a sequence-owned page.
    """

    __slots__ = ("pages", "node", "cow_src", "cow_tokens", "cow_node")

    def __init__(
        self,
        pages: List[int],
        node: Optional[RadixNode],
        cow_src: Optional[int] = None,
        cow_tokens: int = 0,
        cow_node: Optional[RadixNode] = None,
    ) -> None:
        self.pages = pages
        self.node = node
        self.cow_src = cow_src
        self.cow_tokens = cow_tokens
        self.cow_node = cow_node

class RadixCache:
    """Page-granular radix tree over a :class:`PageAllocator`'s pool."""

    def __init__(
        self,
        allocator,
        page_size: int,
        min_share_pages: int = 1,
        cow: bool = True,
        cow_min_tokens: int = 8,
    ) -> None:
        self.allocator = allocator
        self.page_size = page_size
        self.min_share_pages = max(1, int(min_share_pages))
        self.cow = bool(cow)
        self.cow_min_tokens = max(1, int(cow_min_tokens))
        self.root = RadixNode((), [], None)
        # logical LRU clock: bumped per match/insert touch — wall time
        # adds nothing for recency ordering and a counter is testable
        self._clock = 0
        # reclaimable-page count, maintained INCREMENTALLY on the
        # lock_ref 0<->1 edges (_lock_chain), node creation (insert)
        # and node removal (evict) — a plain int, NOT a lazy tree walk:
        # allocator.num_free reads it on every decode page fault, and
        # the gateway event loop reads it cross-thread through
        # pressure_signals -> num_cached while the engine thread
        # mutates children dicts (a DFS there would die with
        # "dictionary changed size during iteration").  _split moves
        # pages between two nodes of the same lock state, so it never
        # touches the count.
        self._evictable = 0
        # brownout L4 (admission.py BROWNOUT_STEPS "bypass_cache_writes"):
        # stop inserting, keep serving hits — flipped cross-thread via
        # EngineCore.set_prefix_insert_suspended (bool stores are atomic
        # under the GIL)
        self.insert_suspended = False
        self.total_inserted_pages = 0
        self.total_evictions = {"lru": 0, "pressure": 0}
        # incremented by the ENGINE when it dispatches a COW page copy
        # (the copy program lives with the device code, the counter
        # lives with the rest of the cache stats)
        self.total_cow_copies = 0
        self.total_nodes = 1  # root
        # host-RAM swap tier (runtime/kv_swap.py), attached by the
        # engine via attach_swap(): eviction demotes lock-free leaves
        # into it (victim cache) and match() promotes them back.  None
        # keeps eviction = discard, byte-identical to the pre-swap tree.
        self.swap = None
        self._swapped_nodes = 0
        self.total_demoted_pages = 0
        self.total_promoted_pages = 0
        # inserts that stopped at a swapped node (we never index below
        # a host-resident prefix) — observability for victim-cache cost
        self.total_insert_blocked_on_swap = 0

    def attach_swap(self, swap) -> None:
        """Wire the host swap manager in (engine init): eviction gains
        the demote path and the manager's capacity drops unlink nodes
        through :meth:`drop_swapped`."""
        self.swap = swap
        swap.on_drop_node = self.drop_swapped

    # ------------------------------------------------------------- clock

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _key(self, tokens: Sequence[int], d: int) -> Tuple[int, ...]:
        return tuple(tokens[d : d + self.page_size])

    # ------------------------------------------------------------- match

    @engine_thread_only
    def match(self, tokens: Sequence[int]) -> Optional[RadixMatch]:
        """Walk to the longest shared prefix of ``tokens`` and lock it.

        Matches whole pages only up to ``len(tokens) - 1`` tokens (the
        suffix prefill must run at least one real token to sample
        from), splitting a node when the walk ends inside its page run.
        On success every matched page carries a NEW allocator reference
        owned by the caller (released via the sequence's normal page
        release) and every node on the path is locked (released via
        :meth:`unlock`).  A copy-on-write tail — ``cow_tokens`` shared
        tokens inside the first diverging page — is attached when
        enabled and worth a device copy.  Returns None when fewer than
        ``min_share_pages`` full pages match.
        """
        ps = self.page_size
        limit = len(tokens) - 1
        if limit < ps:
            return None  # min_share_pages >= 1: no full page can match
        node = self.root
        d = 0
        pages: List[int] = []
        path: List[RadixNode] = []
        diverged: Optional[RadixNode] = None  # node whose run we split off
        while d + ps <= limit:
            child = node.children.get(self._key(tokens, d))
            if child is None:
                break
            if child.swapped is not None:
                # host-swapped victim: promote its pages back into the
                # device pool so the walk (and the sharing) continues —
                # a failed promotion (no device pages / executor error)
                # simply ends the match at the resident prefix
                if not self._try_promote(child):
                    break
            # count matching full pages inside the child's run (first
            # page matched via the key)
            j = 1
            run = len(child.pages)
            while (
                j < run
                and d + (j + 1) * ps <= limit
                and child.tokens[j * ps : (j + 1) * ps]
                == tuple(tokens[d + j * ps : d + (j + 1) * ps])
            ):
                j += 1
            if j < run:
                # partial match point inside the run: split so the
                # shared head becomes its own (lockable) node; the tail
                # (holding the diverging page) is the COW candidate
                child = self._split(child, j)
                diverged = next(iter(child.children.values()))
            pages.extend(child.pages)
            d += j * ps
            path.append(child)
            node = child
            if j < run:
                break  # the tail child diverges — walk is over
        if len(pages) < self.min_share_pages:
            return None
        # copy-on-write tail: the first page of whichever child the walk
        # diverged from may still share a head of tokens
        cow_src = None
        cow_tokens = 0
        cow_node = None
        if self.cow:
            cand = diverged
            if cand is None:
                best = 0
                for child in node.children.values():
                    if child.swapped is not None:
                        # host-swapped: its first page is not device-
                        # resident, so there is nothing to COW-copy
                        # from (promoting a whole run for a sub-page
                        # tail would cost more than it saves)
                        continue
                    n = self._common_prefix(child.tokens, tokens, d, limit)
                    if n > best:
                        best, cand = n, child
                cow_tokens = best
            else:
                cow_tokens = self._common_prefix(
                    cand.tokens, tokens, d, limit
                )
            if cand is not None and self.cow_min_tokens <= cow_tokens < ps:
                cow_src = cand.pages[0]
                cow_node = cand
            else:
                cow_tokens = 0
        # lock the matched path by walking the parent chain from the
        # deepest node (+ the COW source node until dispatch).  The
        # chain walk — not a recorded node list — is what keeps later
        # splits of these nodes consistent: a split head joins the
        # chain and inherits the tail's count, so unlock finds it.
        now = self._tick()
        deepest = path[-1]
        self._lock_chain(deepest, +1, now)
        if cow_node is not None:
            # chain-walked like the path lock (a split of the source
            # node between match and dispatch must not orphan a share)
            self._lock_chain(cow_node, +1, now)
        self.allocator.retain(pages)
        self._touch_gauges()
        return RadixMatch(
            pages, deepest, cow_src=cow_src, cow_tokens=cow_tokens,
            cow_node=cow_node,
        )

    @engine_thread_only
    def _try_promote(self, child: RadixNode) -> bool:
        """Restore a host-swapped leaf's pages into the device pool
        (match-time promotion).  The node's chain is locked around the
        allocation so the eviction walk ``allocate`` may trigger can
        never touch the node being promoted; the unlock edge afterwards
        credits the restored pages back to the evictable count.
        Refcounts/locks then re-establish through the caller's normal
        parent-chain walk, exactly like a never-demoted match."""
        ticket = child.swapped
        if self.swap is None or ticket is None:
            return False
        self._lock_chain(child, +1, self._tick())
        pages = self.allocator.allocate(ticket.num_pages)
        if pages is None:
            self._lock_chain(child, -1, self._tick())
            return False
        try:
            self.swap.promote_node(ticket, pages)
        except Exception:  # executor failure: drop the dead node
            logger.warning(
                "prefix promotion failed; dropping swapped node",
                exc_info=True,
            )
            self.allocator.release(pages)
            self._lock_chain(child, -1, self._tick())
            self.drop_swapped(child, reason="stale")
            return False
        child.pages = pages
        child.swapped = None
        self._swapped_nodes -= 1
        self._lock_chain(child, -1, self._tick())
        self.total_promoted_pages += len(pages)
        self._touch_gauges()
        return True

    @engine_thread_only
    def _lock_chain(self, node: RadixNode, delta: int, now: int) -> None:
        while node is not None and node is not self.root:
            was_free = node.lock_ref == 0
            node.lock_ref += delta
            if was_free and delta > 0:
                self._evictable -= len(node.pages)
            elif node.lock_ref == 0 and delta < 0:
                self._evictable += len(node.pages)
            node.last_access = now
            node = node.parent

    @engine_thread_only
    def _common_prefix(
        self,
        child_tokens: Tuple[int, ...],
        tokens: Sequence[int],
        d: int,
        limit: int,
    ) -> int:
        n = 0
        cap = min(self.page_size, limit - d, len(child_tokens))
        while n < cap and child_tokens[n] == tokens[d + n]:
            n += 1
        return n

    @engine_thread_only
    def probe(self, tokens: Sequence[int]) -> Tuple[int, int]:
        """Lock-free admissibility probe: (matched full pages, how many
        of them are currently reclaimable).  A real ``match`` would
        revive reclaimable pages OUT of the free pool, so the
        scheduler's admissibility math subtracts them — mirroring the
        flat chain's ``is_evictable`` accounting.  Never splits."""
        ps = self.page_size
        limit = len(tokens) - 1
        node = self.root
        d = 0
        full = 0
        evictable = 0
        while d + ps <= limit:
            child = node.children.get(self._key(tokens, d))
            if child is None:
                break
            # token-run length works for resident AND host-swapped
            # nodes (tokens survive demotion; pages do not)
            run = len(child.tokens) // ps
            j = 1
            while (
                j < run
                and d + (j + 1) * ps <= limit
                and child.tokens[j * ps : (j + 1) * ps]
                == tuple(tokens[d + j * ps : d + (j + 1) * ps])
            ):
                j += 1
            full += j
            if child.swapped is not None:
                # promotable on a real match(), but promotion must
                # ALLOCATE the pages — counting them as evictable too
                # keeps the admissibility math honest (num_free -
                # evictable >= n_pages - full still requires the
                # device pages the swap-in will claim)
                evictable += j
                break
            if child.lock_ref == 0:
                evictable += j
            d += j * ps
            node = child
            if j < run:
                break
        return full, evictable

    # ------------------------------------------------------------ insert

    @engine_thread_only
    def insert(
        self, tokens: Sequence[int], pages: List[int]
    ) -> Optional[RadixNode]:
        """Index ``pages`` (full pages covering exactly ``tokens``) in
        the tree; returns the deepest node covering the stream (None
        when nothing was indexed or inserts are suspended).  Pages
        already covered by an existing prefix are NOT adopted — the
        caller's duplicates stay private and release normally (their
        content is identical by construction).  Each adopted page gains
        one allocator reference owned by the tree.

        Adopted pages are usually still referenced by the inserting
        sequence — callers indexing on behalf of RUNNING work
        (``Scheduler.commit_prefill``) must lock the returned node
        (:meth:`lock_node`) until the sequence releases, or the
        eviction accounting would count seq-referenced pages as
        reclaimable (``num_free`` overstating what allocate() can
        actually obtain).  Finish-time inserts release immediately
        after, so they skip the lock."""
        ps = self.page_size
        if self.insert_suspended or not pages:
            return None
        assert len(tokens) >= len(pages) * ps, "tokens must cover pages"
        node = self.root
        d = 0
        i = 0  # pages consumed
        created: Optional[RadixNode] = None
        now = self._tick()
        total = len(pages)
        while i < total:
            key = self._key(tokens, d)
            child = node.children.get(key)
            if child is None:
                run_tokens = tuple(tokens[d : d + (total - i) * ps])
                new = RadixNode(run_tokens, list(pages[i:]), node)
                new.last_access = now
                node.children[key] = new
                self.allocator.retain(new.pages)
                self.total_inserted_pages += len(new.pages)
                self.total_nodes += 1
                self._evictable += len(new.pages)
                created = new
                break
            if child.swapped is not None:
                # never index below a host-resident prefix: the walk
                # cannot split or extend a page-less run, and adopting
                # pages under it would claim device residency the
                # prefix doesn't have.  A later match() promotes the
                # node and re-opens the subtree for indexing.
                self.total_insert_blocked_on_swap += 1
                break
            # walk the child's run while it matches
            j = 0
            run = len(child.pages)
            while (
                j < run
                and i + j < total
                and child.tokens[j * ps : (j + 1) * ps]
                == tuple(tokens[d + j * ps : d + (j + 1) * ps])
            ):
                j += 1
            child.last_access = now
            if j == run:
                node = child
                d += j * ps
                i += j
                continue
            if i + j == total:
                # everything to insert already present inside this run
                break
            # diverged mid-run: split, then attach the new tail
            child = self._split(child, j)
            node = child
            d += j * ps
            i += j
        self._touch_gauges()
        return created

    @engine_thread_only
    def _split(self, child: RadixNode, j: int) -> RadixNode:
        """Split ``child``'s run at page ``j`` (0 < j < len): the head
        becomes a new node in child's place, the tail keeps ``child``'s
        identity (children, locks).  The head inherits the tail's
        lock_ref — every lock below passes through it — preserving the
        path-lock invariant."""
        ps = self.page_size
        parent = child.parent
        head = RadixNode(child.tokens[: j * ps], child.pages[:j], parent)
        head.lock_ref = child.lock_ref
        head.last_access = child.last_access
        parent.children[child.tokens[:ps]] = head
        child.tokens = child.tokens[j * ps :]
        child.pages = child.pages[j:]
        child.parent = head
        head.children[child.tokens[:ps]] = child
        self.total_nodes += 1
        return head

    # ---------------------------------------------------------- unlock

    @engine_thread_only
    def unlock(self, match: RadixMatch) -> None:
        """Release a sequence's path locks (its allocator page
        references are released separately, with the rest of
        ``seq.pages``)."""
        self.release_cow(match)
        if match.node is not None:
            self._lock_chain(match.node, -1, self._tick())
            match.node = None
        self._touch_gauges()

    @engine_thread_only
    def lock_node(self, node: RadixNode) -> None:
        """Pin ``node``'s parent chain on behalf of a RUNNING sequence
        whose private pages :meth:`insert` just adopted (commit-time
        indexing).  Until the matching :meth:`unlock_node` (the
        sequence's release path), those pages are still seq-referenced:
        an unpinned node would let ``evictable_pages`` count them as
        reclaimable and ``evict`` strip their tree references without
        freeing anything — ``num_free`` overstating what allocate()
        can actually obtain."""
        self._lock_chain(node, +1, self._tick())

    @engine_thread_only
    def unlock_node(self, node: RadixNode) -> None:
        """Drop a :meth:`lock_node` pin (chain-walked like every other
        lock, so later splits of the pinned path keep the accounting
        exact)."""
        self._lock_chain(node, -1, self._tick())
        self._touch_gauges()

    @engine_thread_only
    def release_cow(self, match: RadixMatch) -> None:
        """Drop the temporary lock on the COW source node — called once
        the copy program has been dispatched (device program order then
        guarantees the copy reads the page before any later reuse
        writes it)."""
        if match.cow_node is None:
            return
        self._lock_chain(match.cow_node, -1, self._tick())
        match.cow_node = None

    # --------------------------------------------------------- eviction

    def evictable_pages(self) -> int:
        """Pages reclaimable right now: every page in a ``lock_ref == 0``
        node (path-locking makes lock_ref==0 imply the whole subtree is
        unlocked, so leaf-first eviction can reach all of them in one
        ``reclaim`` call).  A maintained int — GIL-atomic for the
        gateway's cross-thread pressure reads (no tree walk here; the
        randomized invariant test checks it against an independent DFS
        every step)."""
        return self._evictable

    @engine_thread_only
    def reclaim(self, n: int) -> int:
        """PageAllocator's on-demand hook: free at least ``n`` pages if
        reclaimable (LRU leaves first)."""
        return self.evict(n, reason="lru")

    @engine_thread_only
    def evict(self, n: int, reason: str = "lru") -> int:
        """LRU walk over refcount-0 leaves: free up to ``n`` pages back
        to the allocator, cascading into parents as they become
        childless.  Returns pages actually freed."""
        if n <= 0:
            return 0

        def _evict_leaf(node: RadixNode) -> bool:
            # "leaf" for eviction purposes: nothing device-resident
            # BELOW it.  Host-swapped children are page-less, so a
            # node whose children are all swapped must still count —
            # otherwise a single swapped leaf would pin its whole
            # ancestor chain out of the walk while _evictable keeps
            # counting those pages (reclaim would under-deliver and
            # allocate() would refuse work the accounting promised).
            return (
                node.lock_ref == 0
                and bool(node.pages)
                and all(
                    g.swapped is not None
                    for g in node.children.values()
                )
            )

        heap: List[Tuple[int, int, RadixNode]] = []
        stack = [self.root]
        serial = 0
        while stack:
            node = stack.pop()
            for child in node.children.values():
                if _evict_leaf(child):
                    serial += 1
                    heapq.heappush(
                        heap, (child.last_access, serial, child)
                    )
                elif child.swapped is None:
                    stack.append(child)
                # swapped nodes: nothing device-resident in their
                # subtree (children of a swapped node are themselves
                # swapped); the host pool's LRU owns their lifetime
        freed = 0
        while heap and freed < n:
            _, _, leaf = heapq.heappop(heap)
            released = leaf.pages
            # count only pages whose tree reference was the LAST one
            # (the lock/ref pairing makes that all of them; defensive
            # against a caller unlocking without releasing)
            gone = sum(
                1 for p in released if self.allocator.refcount(p) == 1
            )
            # host swap tier: demote the content before the device
            # pages go — the node stays in the tree page-less (victim
            # cache) and a later match() promotes it back.  Demotion
            # declined (pool off/full, brownout L4) keeps the original
            # discard.
            ticket = (
                self.swap.demote_node(leaf, released)
                if self.swap is not None
                else None
            )
            self._evictable -= len(released)
            self.allocator.release(released)
            freed += gone
            parent = leaf.parent
            if ticket is not None:
                leaf.swapped = ticket
                leaf.pages = []
                self._swapped_nodes += 1
                self.total_demoted_pages += len(released)
                # the node stays its parent's child (victim cache)
            else:
                # truly discard — including any swapped descendants,
                # whose tickets would otherwise leak in the host pool
                # with their nodes unreachable
                self._drop_swapped_descendants(leaf)
                del parent.children[leaf.tokens[: self.page_size]]
                self.total_nodes -= 1
            if (
                parent is not self.root
                and _evict_leaf(parent)
            ):
                # cascade: the parent may have just become an eviction
                # leaf (childless, or all children now swapped)
                serial += 1
                heapq.heappush(
                    heap, (parent.last_access, serial, parent)
                )
            self.total_evictions[reason] = (
                self.total_evictions.get(reason, 0) + len(released)
            )
            metrics.PREFIX_EVICTIONS.labels(reason=reason).inc(
                len(released)
            )
        if freed:
            self._touch_gauges()
        return freed

    @engine_thread_only
    def _drop_swapped_descendants(self, node: RadixNode) -> None:
        """Discard the host tickets of every swapped node under
        ``node`` (exclusive) — they are about to become unreachable."""
        stack = list(node.children.values())
        while stack:
            child = stack.pop()
            stack.extend(child.children.values())
            if child.swapped is not None:
                ticket = child.swapped
                child.swapped = None
                self._swapped_nodes -= 1
                if self.swap is not None:
                    self.swap.drop_node_ticket(ticket, "capacity")
            self.total_nodes -= 1

    @engine_thread_only
    def drop_swapped(self, node: RadixNode, reason: str = "capacity") -> None:
        """Unlink a host-swapped (page-less) node: the manager dropped
        its ticket to make room for a preemption swap-out, or its
        promotion failed.  Swapped descendants (demotion chains) go
        with it — their tickets would otherwise leak unreachable.
        Idempotent against the manager's own ticket accounting
        (drop_node_ticket refunds only a still-registered ticket)."""
        ticket = node.swapped
        node.swapped = None
        if ticket is not None:
            self._swapped_nodes -= 1
            if self.swap is not None:
                self.swap.drop_node_ticket(ticket, reason)
        self._drop_swapped_descendants(node)
        parent = node.parent
        if parent is not None:
            key = node.tokens[: self.page_size]
            if parent.children.get(key) is node:
                del parent.children[key]
                self.total_nodes -= 1
        node.parent = None

    @engine_thread_only
    def trim_to_watermark(self, target_free: int) -> int:
        """Proactive pressure trim: top the allocator's *truly free*
        list back up to ``target_free`` pages by evicting cold cache
        (reason ``pressure``).  Called from the engine tick so the
        eviction walk is paid off the allocation hot path, BEFORE
        admission's kv_pressure watermark could start shedding."""
        short = target_free - self.allocator.num_truly_free
        if short <= 0 or self.evictable_pages() == 0:
            return 0
        return self.evict(short, reason="pressure")

    # ----------------------------------------------------- introspection

    @engine_thread_only
    def _touch_gauges(self) -> None:
        metrics.PREFIX_CACHED_PAGES.set(self.allocator.num_cached)

    def pages_in_tree(self) -> Dict[int, RadixNode]:
        """page id -> owning node, for invariant checks (a physical page
        must never be indexed twice)."""
        out: Dict[int, RadixNode] = {}
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                for p in child.pages:
                    assert p not in out, f"page {p} doubly indexed"
                    out[p] = child
                stack.append(child)
        return out

    def get_stats(self) -> dict:
        return {
            "nodes": self.total_nodes,
            "cached_pages": self.evictable_pages(),
            "inserted_pages": self.total_inserted_pages,
            "evictions": dict(self.total_evictions),
            "insert_suspended": self.insert_suspended,
            **(
                {
                    "swapped_nodes": self._swapped_nodes,
                    "demoted_pages": self.total_demoted_pages,
                    "promoted_pages": self.total_promoted_pages,
                    "insert_blocked_on_swap": (
                        self.total_insert_blocked_on_swap
                    ),
                }
                if self.swap is not None
                else {}
            ),
        }
