"""Disaggregated prefill→decode KV handoff plane (ISSUE 17).

With ``pod.roles`` set, new requests prefill on the prefill pool; once
the first token exists the prefill worker folds the sequence
(``prepare_migrate`` shape) and stages its KV pages through the PR-11
host swap pool.  The gateway then ships the staged pages to a
least-loaded decode worker as a chunked, checksummed (PR-9 digest),
fencing-epoch-stamped RPC transfer and the decode worker restores them
and continues the stream token-identically.

This module holds the pure, process-agnostic pieces all three parties
share:

* the **handoff state machine** — an explicit allowed-transition map
  (PREFILLING → STAGED → TRANSFERRING → ACCEPTED → DECODING, plus the
  terminal FALLBACK / CANCELLED / FAILED exits every failure branch
  lands on).  Transitions are idempotent (re-entering the current state
  is a no-op) so a duplicated ACCEPT cannot double-apply, and illegal
  jumps raise instead of silently corrupting the record.
* the **payload codec** — ``pack_payload``/``unpack_payload`` serialize
  the swap ticket's KV pytree (nested tuples/lists/dicts of numpy
  arrays, including the int8 ``QuantPages`` NamedTuples) to one
  self-describing byte buffer.  NOT pickle: a length-prefixed JSON
  manifest + raw array bytes, so a garbled wire produces a typed
  :class:`~vgate_tpu.errors.HandoffTransferError`, never arbitrary
  object construction.
* the **chunk assembler** — reassembles out-of-order, possibly
  duplicated transfer chunks on the decode worker; exact re-delivery is
  idempotent, conflicting overlap / overflow / coverage gaps are typed
  errors, never hangs.

Gateway orchestration (records, retries, fallback) lives in
``runtime/pod_engine.py``; the worker-side verbs in ``runtime/worker.py``.
"""

from __future__ import annotations

import importlib
import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from vgate_tpu.errors import HandoffTransferError

# --------------------------------------------------------------- states

PREFILLING = "PREFILLING"
STAGED = "STAGED"
TRANSFERRING = "TRANSFERRING"
ACCEPTED = "ACCEPTED"
DECODING = "DECODING"
# terminal exits — every failure branch of the tentpole lands on one
FALLBACK = "FALLBACK"  # monolithic decode on the prefill worker
CANCELLED = "CANCELLED"  # raced a loss/abort/finish; replay path owns it
FAILED = "FAILED"  # transfer exhausted and no fallback possible

STATES = (
    PREFILLING, STAGED, TRANSFERRING, ACCEPTED, DECODING,
    FALLBACK, CANCELLED, FAILED,
)

# the explicit transition map: state -> states reachable from it
TRANSITIONS: Dict[str, Tuple[str, ...]] = {
    PREFILLING: (STAGED, FALLBACK, CANCELLED, FAILED),
    STAGED: (TRANSFERRING, FALLBACK, CANCELLED, FAILED),
    TRANSFERRING: (ACCEPTED, FALLBACK, CANCELLED, FAILED),
    ACCEPTED: (DECODING, CANCELLED, FAILED),
    DECODING: (),
    FALLBACK: (),
    CANCELLED: (),
    FAILED: (),
}

TERMINAL = frozenset(s for s, nxt in TRANSITIONS.items() if not nxt)


class HandoffStateError(RuntimeError):
    """An illegal handoff state transition was attempted — a logic bug
    or a raced duplicate control frame; the record is left unchanged."""


def advance(current: str, to: str) -> bool:
    """Validate one state transition.  Returns True when the move is
    legal and real, False when ``to == current`` (idempotent re-entry —
    how a duplicated ACCEPT frame becomes a no-op), and raises
    :class:`HandoffStateError` on an illegal jump."""
    if current not in TRANSITIONS:
        raise HandoffStateError(f"unknown handoff state {current!r}")
    if to == current:
        return False
    if to not in TRANSITIONS:
        raise HandoffStateError(f"unknown handoff state {to!r}")
    if to not in TRANSITIONS[current]:
        raise HandoffStateError(
            f"illegal handoff transition {current} -> {to}"
        )
    return True


# -------------------------------------------------------- payload codec
#
# wire layout: MAGIC(4) | manifest_len(4, big-endian) | manifest | blob
# manifest: JSON spec tree; array leaves carry (dtype, shape, off, len)
# into the blob.  Self-describing and boring on purpose — every decode
# failure is a typed HandoffTransferError.

_MAGIC = b"VGKV"
_HEADER = struct.Struct(">I")
_MAX_MANIFEST = 4 * 1024 * 1024

# NamedTuple payload leaves (int8 KV ships QuantPages) reconstruct by
# import path; anything that is not a tuple subclass is refused.
_ALLOWED_NT_MODULES = ("vgate_tpu.",)


def _spec(node: Any, chunks: List[bytes], off: int) -> Tuple[Any, int]:
    if node is None:
        return {"t": "none"}, off
    if isinstance(node, np.ndarray):
        raw = np.ascontiguousarray(node).tobytes()
        chunks.append(raw)
        spec = {
            "t": "nd",
            "dtype": str(node.dtype),
            "shape": list(node.shape),
            "off": off,
            "len": len(raw),
        }
        return spec, off + len(raw)
    if isinstance(node, tuple) and hasattr(node, "_fields"):
        cls = type(node)
        items = []
        for child in node:
            child_spec, off = _spec(child, chunks, off)
            items.append(child_spec)
        return {
            "t": "namedtuple",
            "cls": f"{cls.__module__}:{cls.__qualname__}",
            "items": items,
        }, off
    if isinstance(node, (list, tuple)):
        items = []
        for child in node:
            child_spec, off = _spec(child, chunks, off)
            items.append(child_spec)
        return {
            "t": "tuple" if isinstance(node, tuple) else "list",
            "items": items,
        }, off
    if isinstance(node, dict):
        keys, items = [], []
        for key, child in node.items():
            if not isinstance(key, str):
                raise HandoffTransferError(
                    f"unpackable payload dict key {key!r} (want str)"
                )
            child_spec, off = _spec(child, chunks, off)
            keys.append(key)
            items.append(child_spec)
        return {"t": "dict", "keys": keys, "items": items}, off
    if isinstance(node, (bool, int, float, str)):
        return {"t": "py", "v": node}, off
    raise HandoffTransferError(
        f"unpackable payload leaf of type {type(node).__name__}"
    )


def pack_payload(payload: Any) -> bytes:
    """Serialize a KV payload pytree to one self-describing byte buffer
    (manifest + raw array bytes).  Deterministic for a given payload, so
    the PR-9 digest of the buffer is a transfer checksum."""
    chunks: List[bytes] = []
    spec, _ = _spec(payload, chunks, 0)
    manifest = json.dumps(spec, separators=(",", ":")).encode()
    if len(manifest) > _MAX_MANIFEST:
        raise HandoffTransferError(
            f"payload manifest too large ({len(manifest)} bytes)"
        )
    return b"".join([_MAGIC, _HEADER.pack(len(manifest)), manifest] + chunks)


def _build(spec: Any, blob: memoryview) -> Any:
    if not isinstance(spec, dict) or "t" not in spec:
        raise HandoffTransferError("malformed payload manifest node")
    kind = spec["t"]
    if kind == "none":
        return None
    if kind == "py":
        val = spec.get("v")
        if not isinstance(val, (bool, int, float, str)):
            raise HandoffTransferError("malformed scalar leaf")
        return val
    if kind == "nd":
        try:
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            off, length = int(spec["off"]), int(spec["len"])
        except (KeyError, TypeError, ValueError) as exc:
            raise HandoffTransferError(
                f"malformed array leaf: {exc}"
            ) from None
        if off < 0 or length < 0 or off + length > len(blob):
            raise HandoffTransferError(
                f"array leaf out of bounds (off={off} len={length} "
                f"blob={len(blob)})"
            )
        want = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if want != length:
            raise HandoffTransferError(
                f"array leaf size mismatch (shape wants {want}, "
                f"manifest says {length})"
            )
        arr = np.frombuffer(blob[off:off + length], dtype=dtype)
        return arr.reshape(shape).copy()
    if kind in ("list", "tuple"):
        items = spec.get("items")
        if not isinstance(items, list):
            raise HandoffTransferError("malformed container node")
        built = [_build(child, blob) for child in items]
        return tuple(built) if kind == "tuple" else built
    if kind == "dict":
        keys = spec.get("keys")
        items = spec.get("items")
        if (
            not isinstance(keys, list)
            or not isinstance(items, list)
            or len(keys) != len(items)
            or not all(isinstance(k, str) for k in keys)
        ):
            raise HandoffTransferError("malformed dict node")
        return {
            key: _build(child, blob) for key, child in zip(keys, items)
        }
    if kind == "namedtuple":
        path = spec.get("cls", "")
        items = spec.get("items")
        if not isinstance(path, str) or not isinstance(items, list):
            raise HandoffTransferError("malformed namedtuple node")
        if not path.startswith(_ALLOWED_NT_MODULES):
            raise HandoffTransferError(
                f"refusing namedtuple outside vgate_tpu: {path!r}"
            )
        try:
            mod_name, _, qualname = path.partition(":")
            obj: Any = importlib.import_module(mod_name)
            for part in qualname.split("."):
                obj = getattr(obj, part)
        except (ImportError, AttributeError, ValueError) as exc:
            raise HandoffTransferError(
                f"cannot resolve payload class {path!r}: {exc}"
            ) from None
        if not (isinstance(obj, type) and issubclass(obj, tuple)
                and hasattr(obj, "_fields")):
            raise HandoffTransferError(
                f"payload class {path!r} is not a NamedTuple"
            )
        if len(items) != len(obj._fields):
            raise HandoffTransferError(
                f"payload class {path!r} arity mismatch"
            )
        return obj(*[_build(child, blob) for child in items])
    raise HandoffTransferError(f"unknown manifest node type {kind!r}")


def unpack_payload(buf: bytes) -> Any:
    """Inverse of :func:`pack_payload`.  Every malformation — bad magic,
    truncation, undecodable manifest, out-of-bounds leaves — raises
    :class:`~vgate_tpu.errors.HandoffTransferError`."""
    view = memoryview(buf)
    head = len(_MAGIC) + _HEADER.size
    if len(view) < head:
        raise HandoffTransferError(
            f"payload truncated ({len(view)} bytes, header needs {head})"
        )
    if bytes(view[:len(_MAGIC)]) != _MAGIC:
        raise HandoffTransferError("bad payload magic")
    (mlen,) = _HEADER.unpack(view[len(_MAGIC):head])
    if mlen > _MAX_MANIFEST or head + mlen > len(view):
        raise HandoffTransferError(
            f"payload manifest length {mlen} out of bounds"
        )
    try:
        spec = json.loads(bytes(view[head:head + mlen]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise HandoffTransferError(
            f"undecodable payload manifest: {exc}"
        ) from None
    return _build(spec, view[head + mlen:])


def payload_digest(buf: bytes) -> int:
    """PR-9 positional digest of a packed payload buffer — the transfer
    checksum the decode worker verifies before restoring pages."""
    # imported lazily: vgate_tpu.integrity pulls jax, and this module
    # must stay cheap to import for the wire-helper unit tests
    from vgate_tpu.integrity import host_leaf_digest

    return host_leaf_digest(np.frombuffer(buf, dtype=np.uint8))


# ------------------------------------------------------ chunk assembler


class ChunkAssembler:
    """Reassembles one transfer's chunks on the decode worker.

    Byte-identical redelivery of a chunk (the ``duplicate`` fault mode,
    or a gateway retry racing its own first attempt) is an idempotent
    no-op; conflicting overlap, overflow past ``total`` and commit with
    coverage gaps are typed errors.  Single-threaded per transfer (the
    worker's verb dispatch serializes puts for one connection)."""

    def __init__(self, total: int, max_bytes: int) -> None:
        if total <= 0 or total > max_bytes:
            raise HandoffTransferError(
                f"transfer size {total} out of bounds (cap {max_bytes})"
            )
        self.total = total
        self._buf = bytearray(total)
        # merged sorted coverage intervals [(start, end), ...)
        self._spans: List[Tuple[int, int]] = []

    @property
    def received(self) -> int:
        return sum(end - start for start, end in self._spans)

    def put(self, off: int, data: bytes) -> int:
        """Apply one chunk; returns total bytes covered so far."""
        if not data:
            raise HandoffTransferError("empty transfer chunk")
        end = off + len(data)
        if off < 0 or end > self.total:
            raise HandoffTransferError(
                f"chunk [{off}:{end}) outside transfer of {self.total}"
            )
        for start, stop in self._spans:
            lo, hi = max(off, start), min(end, stop)
            if lo < hi and (
                self._buf[lo:hi] != data[lo - off:hi - off]
            ):
                raise HandoffTransferError(
                    f"conflicting chunk overlap at [{lo}:{hi})"
                )
        self._buf[off:end] = data
        self._spans = _merge_spans(self._spans + [(off, end)])
        return self.received

    def complete(self) -> bytes:
        """Return the assembled buffer; raises (with the missing ranges
        named) when coverage has gaps — the gateway's retry signal."""
        if self._spans != [(0, self.total)]:
            missing = _gaps(self._spans, self.total)
            raise HandoffTransferError(
                f"transfer incomplete: missing byte ranges {missing}"
            )
        return bytes(self._buf)


def _merge_spans(spans: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    spans = sorted(spans)
    merged = [spans[0]]
    for start, end in spans[1:]:
        last_start, last_end = merged[-1]
        if start <= last_end:
            merged[-1] = (last_start, max(last_end, end))
        else:
            merged.append((start, end))
    return merged


def _gaps(spans: List[Tuple[int, int]], total: int) -> List[Tuple[int, int]]:
    gaps, cursor = [], 0
    for start, end in spans:
        if start > cursor:
            gaps.append((cursor, start))
        cursor = max(cursor, end)
    if cursor < total:
        gaps.append((cursor, total))
    return gaps


def chunk_offsets(total: int, chunk_bytes: int) -> List[Tuple[int, int]]:
    """Split ``total`` transfer bytes into (offset, length) chunks."""
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be > 0")
    return [
        (off, min(chunk_bytes, total - off))
        for off in range(0, total, chunk_bytes)
    ]
