"""Continuous-batching scheduler.

Replaces the stop-the-world batch lock at the heart of the reference
(vgate/batcher.py:79,195 serializes every batch behind one asyncio.Lock,
SURVEY.md section 7 step 4) with per-step admission: the decode loop owns
the device, and between decode steps the scheduler admits waiting prompts
into free slots, allocates KV pages on demand, and preempts under memory
pressure.

Pure host-side policy, no JAX: fully unit-testable (SURVEY.md section 4's
CPU-only strategy).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Union

import numpy as np

from vgate_tpu import metrics
from vgate_tpu.analysis.annotations import engine_thread_only
from vgate_tpu.errors import DeadlineExceededError, KVCapacityError
from vgate_tpu.logging_config import get_logger
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.kv_swap import KVSwapManager, SwapTicket
from vgate_tpu.runtime.radix_cache import RadixCache, RadixMatch
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.utils.math import bucket_for, cdiv, round_up

logger = get_logger(__name__)

# Threading contract (scripts/vgt_lint.py, thread-discipline): the
# scheduler is engine-thread-owned state; cross-module resolution for
# self.radix.* / self.swap.* calls.
VGT_COMPONENTS = {"radix": "RadixCache", "swap": "KVSwapManager"}


def _rank(seq: "Sequence") -> int:
    """Priority-tier rank from the request's SamplingParams
    (vgate_tpu/admission.py: 0 interactive, 1 standard, 2 batch);
    direct engine callers without the field schedule as standard.
    Integrity canary self-probes rank ahead of every tier: a replica's
    fitness check must not queue behind the very traffic it gates
    (vgate_tpu/integrity.py CanaryKeeper)."""
    if seq.canary:
        return -1
    return getattr(seq.params, "priority", 1)


class EngineBusyError(RuntimeError):
    """Raised at admission when the waiting queue is full (load shedding,
    SURVEY.md section 5.3: 'add deadlines/load-shedding at admission')."""

    # the 503 body's machine-readable flavor (vgate_tpu/errors.py)
    reason = "overloaded"


class AdmissionDeadlineExceeded(EngineBusyError):
    """A queued request waited past ``scheduler.admission_deadline_ms`` and
    was shed instead of admitted (the completion would arrive too late to
    be useful; SURVEY.md section 5.3)."""


@dataclass
class PrefillPlan:
    seq: Sequence
    slot: int
    bucket: int  # padded sequence length for this prefill program
    # prefix-cache reuse: the first cached_len prompt tokens' KV is already
    # resident in shared pages; only the suffix needs the prompt pass.
    # `bucket` then buckets the SUFFIX length, and register_hashes lists
    # (page, chain_hash) pairs to index once this prefill is dispatched.
    # With the radix tree, cached_len may be UNALIGNED (full shared pages
    # plus a copy-on-write partial page) and register_hashes stays None —
    # radix_insert/cow carry the tree bookkeeping instead.
    cached_len: int = 0
    register_hashes: list = None  # type: ignore[assignment]
    # chunked prefill: the (suffix) prompt exceeds the bucket cap and
    # runs as SERIAL suffix passes of `bucket` tokens each
    # (engine_core._dispatch_chunked_prefill)
    chunked: bool = False
    # copy-on-write partial page: (src_page, dst_page, shared_tokens) —
    # the engine device-copies the first shared_tokens of src into dst
    # (the sequence's own page) BEFORE dispatching the suffix prefill,
    # then prefill starts mid-page at cached_len
    cow: tuple = None  # type: ignore[assignment]
    # radix commit data snapshotted at admission: (tokens, pages) of the
    # full prompt pages this prefill makes indexable, plus the match
    # handle whose COW lock commit_prefill releases.  Snapshotted so a
    # containment fold between dispatch and commit cannot skew it.
    radix_insert: tuple = None  # type: ignore[assignment]
    radix_match: RadixMatch = None  # type: ignore[assignment]


@dataclass
class SwapInPlan:
    """Re-admission of a host-swapped preemption victim
    (runtime/kv_swap.py): the engine scatters the parked KV into the
    freshly-allocated ``seq.pages`` and the sequence rejoins decode at
    the exact position it stopped — no prefill program, no first-token
    sampling (its last sampled token is the decode feed)."""

    seq: Sequence
    slot: int
    ticket: SwapTicket


@dataclass
class DecodePlan:
    seqs: List[Sequence]  # active sequences, indexed by slot in .slot


Plan = Union[PrefillPlan, SwapInPlan, DecodePlan]


class Scheduler:
    def __init__(
        self,
        allocator: PageAllocator,
        max_slots: int,
        page_size: int,
        prefill_buckets: List[int],
        max_model_len: int,
        max_queue_size: int = 512,
        preempt_on_oom: bool = True,
        admission_deadline_ms: float = 0.0,
        prefix_cache: bool = False,
        prefill_chunk: int = 0,
        text_fn=None,
        recorder=None,
        radix: Optional[RadixCache] = None,
        cache_aware_sched: bool = True,
        insert_generated: bool = True,
        evict_watermark: float = 0.0,
        swap: Optional[KVSwapManager] = None,
    ) -> None:
        # optional flight recorder (observability/flight.py): residency
        # events (preempt/shed/abort) become post-mortem ring entries
        self.recorder = recorder
        # renders a sequence's partial generation for deadline-shed
        # metadata (the engine injects tokenizer.decode-backed
        # final_text); None keeps queued sheds text-less.  A preempted
        # sequence shed from the WAITING queue can hold generated
        # tokens, and its 504 must carry them like a running shed's.
        self.text_fn = text_fn
        self.allocator = allocator
        self.page_size = page_size
        # buckets: page-aligned, capped at max_model_len, and always
        # including a top bucket that can hold any admissible prompt
        # (preempted sequences re-prefill with their grown context).
        # With chunked prefill (prefill_chunk > 0) the ladder caps at the
        # chunk size instead, and longer prompts run serial suffix passes
        # of top-bucket tokens each.
        top = round_up(max_model_len, page_size)
        if prefill_chunk > 0:
            top = min(top, round_up(prefill_chunk, page_size))
        self.prefill_chunk = prefill_chunk
        aligned = {
            min(round_up(b, page_size), top)
            for b in prefill_buckets
            if b > 0
        }
        aligned.add(top)
        self.prefill_buckets = sorted(aligned)
        self.max_model_len = max_model_len
        self.max_queue_size = max_queue_size
        self.preempt_on_oom = preempt_on_oom
        self.admission_deadline_ms = admission_deadline_ms
        self.total_deadline_shed = 0
        self.prefix_cache = prefix_cache
        # radix-tree prefix index (runtime/radix_cache.py): replaces the
        # flat hash chain when provided; None keeps the r2-era flat
        # whole-page chain (still constructible for comparison)
        self.radix = radix if prefix_cache else None
        self.cache_aware_sched = bool(cache_aware_sched)
        self.insert_generated = bool(insert_generated)
        # proactive trim target in PAGES (0 disables): the engine tick
        # calls maybe_trim() so eviction walks run off the allocation
        # hot path, before admission's kv_pressure watermark engages
        self._trim_target = 0
        if self.radix is not None and evict_watermark > 0:
            self._trim_target = int(
                evict_watermark * allocator.num_allocatable
            )
        self.total_prefix_hit_tokens = 0
        self.waiting: Deque[Sequence] = deque()
        # sticky: set once any deadline-bearing sequence is ever queued,
        # so deployments without client deadlines skip _shed_expired's
        # per-tick queue scan entirely (try_admit runs in a tight loop
        # on the engine thread)
        self._deadline_seen = False
        # sticky twin for priority tiers: until a non-standard-priority
        # sequence is queued, admission selection stays head-of-queue
        self._priority_seen = False
        self.slots: List[Optional[Sequence]] = [None] * max_slots
        # host-RAM KV swap tier (runtime/kv_swap.py): preemption parks
        # the victim's pages instead of recomputing, re-admission
        # swaps them back in; None keeps the pre-swap engine
        # byte-identical (kv_cache.host_swap_bytes = 0)
        self.swap = swap
        self.total_preemptions = 0
        self.total_swap_preempts = 0
        self.total_preempt_recompute_tokens = 0
        self.total_admitted = 0
        self.total_finished = 0
        self.total_aborted = 0
        # sequences folded + staged for a prefill→decode handoff
        self.total_handoff_holds = 0

    # -- admission --

    @engine_thread_only
    def add(self, seq: Sequence) -> None:
        if (
            len(self.waiting) >= self.max_queue_size
            and seq.resume_count == 0
            and seq.migrate_count == 0
            # a handoff-adopted sequence (disaggregated prefill→decode)
            # was likewise already admitted on its prefill worker
            and seq.handoff_count == 0
            # integrity canaries bypass too: a self-probe rejected by an
            # overload gate would read as a corruption verdict and tear
            # down a merely-busy replica (one tiny greedy probe cannot
            # meaningfully deepen a 512-entry queue)
            and not seq.canary
        ):
            # replayed sequences (resume_count > 0: checkpointed across
            # an engine restart / dp failover; migrate_count > 0:
            # planned drain/rebalance movement) bypass the queue-full
            # gate — they were ALREADY admitted once and their clients
            # are still owed an answer; shedding them here would turn a
            # survivable restart (or a routine rolling deploy) into a
            # 503 exactly when the surviving queue is busiest.  Bounded:
            # at most slots+queue sequences existed on the source, so
            # the overshoot is one queue's worth.
            raise EngineBusyError(
                f"engine queue full ({self.max_queue_size} waiting)"
            )
        if seq.num_prompt_tokens >= self.max_model_len:
            raise ValueError(
                f"prompt of {seq.num_prompt_tokens} tokens exceeds "
                f"max_model_len={self.max_model_len}"
            )
        if seq.deadline_t is not None:
            self._deadline_seen = True
        if _rank(seq) != 1 and not seq.canary:
            # sticky, like _deadline_seen: deployments without priority
            # tiers keep the O(1) head-of-queue admission path.  The
            # engine's own canary probes (rank -1) don't flip it — one
            # boot probe must not tax every client admission for the
            # process lifetime; canaries only run on idle engines, so
            # queue position is moot for them.
            self._priority_seen = True
        self.waiting.append(seq)
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    # -- queries --

    @property
    def running(self) -> List[Sequence]:
        return [s for s in self.slots if s is not None]

    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            s is not None for s in self.slots
        )

    @engine_thread_only
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    @engine_thread_only
    def has_admissible_waiting(self) -> bool:
        """True when the head-of-queue prompt could actually be admitted
        right now: a free slot exists AND its pages are allocatable.
        The engine's admission-pressure signals (re-tick without napping,
        decode-chunk cap) key off this — page-exhausted queues must NOT
        shrink chunks or spin, since admission is blocked on a sequence
        finishing, not on loop latency."""
        head = self._select_next()
        if head is None or self._free_slot() is None:
            return False
        if self.swap is not None:
            # a swapped-out head re-admits via swap-in: exactly the
            # parked page count, no prefix sharing (probe only — a
            # stale ticket falls through to the prefill math below,
            # which is consistent because staleness implies the fold
            # already moved the generation into the prompt)
            ticket = getattr(head, "_swap_ticket", None)
            if (
                ticket is not None
                and head.preempt_count == ticket.epoch
            ):
                return self.allocator.num_free >= ticket.num_pages
        n_pages = cdiv(max(1, head.num_prompt_tokens), self.page_size)
        if self.radix is not None:
            # mirror try_admit's radix accounting: matched pages are
            # shared, not allocated, but matched pages of UNLOCKED
            # nodes currently count toward num_free and a real match
            # would revive them out of that pool — subtract those or
            # this predicate would say "admissible" where allocate()
            # then fails (busy-spin + needless decode-chunk shrink)
            full, evictable = self._radix_probe(head)
            return (
                self.allocator.num_free - evictable >= n_pages - full
            )
        if self.prefix_cache:
            # mirror try_admit's accounting: resident prefix pages are
            # shared, not allocated (peek — no refcount mutation).  A
            # matched page that is currently EVICTABLE counts toward
            # num_free, but try_admit's lookup() would revive it out of
            # that pool — subtract those or this predicate would say
            # "admissible" where allocate() then fails (busy-spin +
            # needless decode-chunk shrink).
            matched_evictable = 0
            for h in self._prefix_chain(head):
                page = self.allocator.peek(h)
                if page is None:
                    break
                n_pages -= 1
                if self.allocator.is_evictable(page):
                    matched_evictable += 1
            return (
                self.allocator.num_free - matched_evictable >= n_pages
            )
        return self.allocator.num_free >= n_pages

    # -- planning --

    @engine_thread_only
    def schedule(self) -> Optional[Plan]:
        """Pick the next device program: prefill-priority admission, else a
        decode step over the active slots.

        Convenience wrapper composing the two primitives the engine loop
        calls directly (``try_admit`` for async prefill dispatch and
        ``prepare_decode`` with a chunk horizon — engine_core.py:_tick);
        kept for simple single-step drivers and tests."""
        plan = self.try_admit()
        if plan is not None:
            return plan
        active = self.running
        if not active:
            return None
        if self.prepare_decode(active):
            # preemption may have emptied the slots
            active = self.running
            if active:
                return DecodePlan(seqs=active)
        return self.try_admit()  # everything preempted; try re-admission

    @engine_thread_only
    def _shed_expired(self) -> None:
        """Fail queued sequences whose deadline has passed (their
        completion would arrive too late to be useful).  Two deadlines
        apply: the global admission deadline (preempted sequences are
        exempt — they were already admitted once and hold generated
        tokens the client is owed) and each request's own end-to-end
        deadline (``seq.deadline_t``; applies unconditionally — the
        client's budget is blown either way)."""
        if not self.admission_deadline_ms and not self._deadline_seen:
            return
        admission_s = self.admission_deadline_ms / 1000.0
        now = time.perf_counter()
        kept: Deque[Sequence] = deque()
        shed = 0
        for seq in self.waiting:
            if seq.past_deadline(now):
                waited = (now - seq.arrival_t) * 1000
                partial_text = ""
                if seq.num_generated and self.text_fn is not None:
                    # preempted sequences re-enter the queue carrying
                    # generated tokens — their shed metadata must be as
                    # complete as a running shed's
                    try:
                        partial_text = self.text_fn(seq)
                    except Exception:  # pragma: no cover - defensive
                        pass
                self._event(
                    "shed", seq, where="queued",
                    partial_tokens=seq.num_generated,
                )
                # phase attribution from the recorder when attached: a
                # PREEMPTED sequence re-queued here spent most of its
                # budget computing, and reporting the whole lifetime as
                # queue_s would misattribute it
                if self.recorder is not None:
                    phases = self.recorder.phases_of(seq)
                else:
                    phases = {"queue_s": round(waited / 1000.0, 6)}
                self._discard_swap(seq, "settled")
                seq.fail(
                    DeadlineExceededError(
                        f"request deadline "
                        f"({seq.params.timeout_s:.3f}s) passed after "
                        f"{waited:.0f}ms in queue, before generation "
                        "could finish",
                        partial_text=partial_text,
                        partial_tokens=seq.num_generated,
                        deadline_s=seq.params.timeout_s or 0.0,
                        phases=phases,
                    )
                )
                metrics.CANCELLED_REQUESTS.labels(reason="deadline").inc()
                metrics.DEADLINE_PARTIAL_TOKENS.observe(seq.num_generated)
                shed += 1
            elif (
                self.admission_deadline_ms
                and seq.preempt_count == 0
                and now - seq.arrival_t > admission_s
            ):
                self._event("shed", seq, where="admission")
                seq.fail(
                    AdmissionDeadlineExceeded(
                        f"request waited {(now - seq.arrival_t) * 1000:.0f}ms "
                        f"in queue (> {self.admission_deadline_ms:.0f}ms "
                        "admission deadline)"
                    )
                )
                shed += 1
            else:
                kept.append(seq)
        if shed:
            self.waiting = kept
            self.total_deadline_shed += shed
            metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))
            logger.warning(
                "shed requests past deadline",
                extra={"extra_data": {"shed": shed}},
            )

    @engine_thread_only
    def _prefix_chain(self, seq: Sequence) -> List[bytes]:
        """Chain digests, one per full prompt page, cached on the sequence
        (re-admission attempts under memory pressure must not rehash the
        prompt every tick).  sha256 over the token bytes — a collision
        would silently share another request's KV (the weakness behind
        vLLM's prefix-cache CVE-2025-25183), so the builtin hash() is not
        acceptable here."""
        import hashlib

        key = (len(seq.prompt_ids), seq.preempt_count)
        cached = getattr(seq, "_prefix_chain_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        n_full = seq.num_prompt_tokens // self.page_size
        # never match the ENTIRE prompt: the prefill program must run at
        # least one real token to produce the first sampled token
        if n_full * self.page_size == seq.num_prompt_tokens:
            n_full -= 1
        chain: List[bytes] = []
        h = b""
        for i in range(n_full):
            block = np.asarray(
                seq.prompt_ids[
                    i * self.page_size : (i + 1) * self.page_size
                ],
                np.int64,
            ).tobytes()
            h = hashlib.sha256(h + block).digest()
            chain.append(h)
        seq._prefix_chain_cache = (key, chain)  # type: ignore[attr-defined]
        return chain

    @engine_thread_only
    def _radix_probe(self, seq: Sequence) -> tuple:
        """(matched full pages, matched-but-reclaimable pages) for a
        waiting sequence, memoized per (prompt epoch, tree clock) —
        cache-aware selection probes several candidates per admission
        and must not re-walk an unchanged tree."""
        key = (
            len(seq.prompt_ids), seq.preempt_count, self.radix._clock
        )
        cached = getattr(seq, "_radix_probe_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        result = self.radix.probe(seq.prompt_ids)
        if result[0] < self.radix.min_share_pages:
            # match() refuses sub-threshold shares, so crediting them
            # here would claim admissibility where try_admit must then
            # allocate every page (busy-spin when it can't), and would
            # prefer a "warm" candidate that actually admits cold
            result = (0, 0)
        seq._radix_probe_cache = (key, result)  # type: ignore[attr-defined]
        return result

    # bounded FIFO bypass for cache-aware selection: a cold head is
    # passed over at most this many times before it is admitted
    # regardless, so warm traffic cannot starve it
    CACHE_AWARE_MAX_BYPASS = 4
    # candidates probed per admission (first K of the best tier, queue
    # order) — bounds the per-tick probe cost under deep queues
    CACHE_AWARE_LOOKAHEAD = 8

    @engine_thread_only
    def _select_next(self, count_bypass: bool = False) -> Optional[Sequence]:
        """Admission candidate: the oldest sequence of the most
        important waiting tier (rank, then seq_id — FIFO within a
        tier; a preempted sequence's old seq_id keeps it ahead of
        younger tier-mates on re-admission).  Aborted sequences are
        skipped here and reaped by ``_reap_aborted``.  Without priority
        tiers in play this is the head of the queue (O(1)).

        With the radix tree and ``cache_aware_sched``, same-tier
        candidates that share MORE resident tree pages are preferred
        (bounded lookahead, bounded bypass): admitting warm work while
        its prefix is locked-resident keeps hot prefixes co-batched and
        un-evictable, and costs the cold head at most
        ``CACHE_AWARE_MAX_BYPASS`` admissions of delay.
        ``count_bypass`` is set only by ``try_admit`` — probe callers
        (``has_admissible_waiting``) must not age the head."""
        if not self._priority_seen:
            best = None
            for seq in self.waiting:  # head modulo an aborted/held prefix
                if not seq.abort_requested and not getattr(
                    seq, "_handoff_hold", False
                ):
                    best = seq
                    break
        else:
            best = None
            for seq in self.waiting:
                if seq.abort_requested or getattr(
                    seq, "_handoff_hold", False
                ):
                    continue
                if best is None or (_rank(seq), seq.seq_id) < (
                    _rank(best), best.seq_id
                ):
                    best = seq
        if (
            best is None
            or self.radix is None
            or not self.cache_aware_sched
        ):
            return best
        if (
            getattr(best, "_cache_bypassed", 0)
            >= self.CACHE_AWARE_MAX_BYPASS
        ):
            return best
        best_rank = _rank(best)
        best_pages = self._radix_probe(best)[0]
        warm, warm_pages = best, best_pages
        seen = 0
        for seq in self.waiting:
            if (
                seq.abort_requested
                or getattr(seq, "_handoff_hold", False)
                or _rank(seq) != best_rank
            ):
                continue
            seen += 1
            if seen > self.CACHE_AWARE_LOOKAHEAD:
                break
            pages = self._radix_probe(seq)[0]
            if pages > warm_pages or (
                pages == warm_pages and seq.seq_id < warm.seq_id
            ):
                warm, warm_pages = seq, pages
        if warm is not best and warm_pages > best_pages:
            if count_bypass:
                best._cache_bypassed = (  # type: ignore[attr-defined]
                    getattr(best, "_cache_bypassed", 0) + 1
                )
            return warm
        return best

    @engine_thread_only
    def _dequeue(self, seq: Sequence) -> None:
        """Remove a selected sequence from the waiting queue — O(1) for
        the head (the only case without priority tiers in play)."""
        if self.waiting and self.waiting[0] is seq:
            self.waiting.popleft()
        else:
            self.waiting.remove(seq)

    @engine_thread_only
    def _reap_aborted(self) -> None:
        """Settle client-cancelled waiting sequences WHEREVER they sit.
        Head-only reaping is not enough once priority selection admits
        around the head: an aborted sequence parked behind a bypassed
        lower-tier head would otherwise never settle — its future (and
        the gateway's admission backlog charge) would leak forever."""
        if not any(s.abort_requested for s in self.waiting):
            return
        kept: Deque[Sequence] = deque()
        for seq in self.waiting:
            if seq.abort_requested:
                self.abort(seq)
            else:
                kept.append(seq)
        self.waiting = kept
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    @engine_thread_only
    def try_admit(self) -> Optional[PrefillPlan]:
        self._shed_expired()
        self._reap_aborted()
        if not self.waiting:
            return None
        slot = self._free_slot()
        if slot is None:
            return None
        seq = self._select_next(count_bypass=True)
        if seq is None:
            return None
        if self.swap is not None:
            # swapped-out preemption victim: re-admit via host->device
            # swap-in instead of re-prefill (ticket_for discards a
            # stale ticket internally, falling through to recompute)
            ticket = self.swap.ticket_for(seq)
            if ticket is not None:
                return self._admit_swap_in(seq, slot, ticket)
        n_pages = cdiv(max(1, seq.num_prompt_tokens), self.page_size)

        # prefix cache: match the longest shared prefix already resident;
        # only the remainder allocates + prefills.  Radix mode walks the
        # tree (full pages + optional COW partial page); flat mode
        # matches the whole-page hash chain.
        matched: List[int] = []
        chain: List[bytes] = []
        radix_match: Optional[RadixMatch] = None
        cow_tokens = 0
        if self.radix is not None:
            radix_match = self.radix.match(seq.prompt_ids)
            if radix_match is not None:
                matched = radix_match.pages
                cow_tokens = radix_match.cow_tokens
        elif self.prefix_cache:
            chain = self._prefix_chain(seq)
            for h in chain:
                page = self.allocator.lookup(h)
                if page is None:
                    break
                matched.append(page)

        if cow_tokens and (
            seq.num_prompt_tokens
            - len(matched) * self.page_size
            - cow_tokens
            > self.prefill_buckets[-1]
        ):
            # the suffix exceeds the bucket cap, so this prefill runs
            # CHUNKED — serial page-aligned passes that cannot start
            # mid-page.  Drop the COW tail and recompute those tokens
            # with the first chunk instead.
            self.radix.release_cow(radix_match)
            radix_match.cow_tokens = 0
            cow_tokens = 0

        pages = self.allocator.allocate(n_pages - len(matched))
        if pages is None:
            self.allocator.release(matched)
            if radix_match is not None:
                self.radix.unlock(radix_match)
            if self.preempt_on_oom and not self.running:
                # nothing to preempt and still no memory: the prompt can
                # never fit — fail it rather than deadlock
                self._dequeue(seq)
                seq.fail(
                    RuntimeError(
                        "KV cache too small for prompt "
                        f"({seq.num_prompt_tokens} tokens)"
                    )
                )
            return None
        self._dequeue(seq)
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))
        seq.pages = matched + pages
        seq.slot = slot
        seq.status = SeqStatus.RUNNING
        self.slots[slot] = seq
        self.total_admitted += 1
        metrics.ACTIVE_SEQUENCES.set(len(self.running))
        cached_len = len(matched) * self.page_size + cow_tokens
        if getattr(seq, "_preempt_recompute", False):
            # the waste the host swap tier exists to eliminate: suffix
            # tokens this re-prefill recomputes because a preemption
            # destroyed (rather than parked) the sequence's KV
            seq._preempt_recompute = False  # type: ignore[attr-defined]
            waste = max(0, seq.num_prompt_tokens - cached_len)
            self.total_preempt_recompute_tokens += waste
            metrics.PREEMPT_RECOMPUTE_TOKENS.inc(waste)
        self.total_prefix_hit_tokens += cached_len
        # hits count only on successful admission (a failed allocate above
        # rolls the references back and must not inflate the stat)
        self.allocator.prefix_hits += len(matched)
        if cached_len:
            metrics.PREFIX_HIT_TOKENS.inc(cached_len)
            metrics.PREFIX_HIT_PAGES.inc(len(matched))
        cow = None
        if cow_tokens:
            # dst = the sequence's first OWN page: the engine copies the
            # shared head of the diverging source page into it, then the
            # suffix prefill starts mid-page at cached_len
            cow = (radix_match.cow_src, pages[0], cow_tokens)
        if radix_match is not None:
            # the sequence's release path must drop the tree path locks
            seq._radix_match = radix_match  # type: ignore[attr-defined]
        radix_insert = None
        if self.radix is not None:
            # snapshot what this prefill makes indexable (all full
            # prompt pages): commit_prefill inserts it after dispatch.
            # Snapshotted NOW so a watchdog containment folding the
            # sequence mid-dispatch cannot skew the commit data.
            n_full = seq.num_prompt_tokens // self.page_size
            if n_full > len(matched):
                radix_insert = (
                    list(seq.prompt_ids[: n_full * self.page_size]),
                    list(seq.pages[:n_full]),
                )
        # flat mode: pages this prefill will fill (full prompt pages
        # beyond the matched prefix), for the ENGINE to index AFTER it
        # dispatched the program — registering here would let a
        # same-tick reader's program be grouped ahead of this writer's
        # and gather unwritten pages (same-wave identical prompts are
        # the batcher dedup's job)
        register_hashes = [
            (seq.pages[i], chain[i]) for i in range(len(matched), len(chain))
        ]
        suffix_len = seq.num_prompt_tokens - cached_len
        top = self.prefill_buckets[-1]
        if suffix_len > top:
            # chunked prefill: serial suffix passes of `top` tokens
            return PrefillPlan(
                seq=seq, slot=slot, bucket=top, cached_len=cached_len,
                register_hashes=register_hashes, chunked=True,
                cow=cow, radix_insert=radix_insert,
                radix_match=radix_match,
            )
        bucket = bucket_for(suffix_len, self.prefill_buckets)
        return PrefillPlan(
            seq=seq, slot=slot, bucket=bucket, cached_len=cached_len,
            register_hashes=register_hashes,
            cow=cow, radix_insert=radix_insert, radix_match=radix_match,
        )

    @engine_thread_only
    def _admit_swap_in(
        self, seq: Sequence, slot: int, ticket: SwapTicket
    ) -> Optional[SwapInPlan]:
        """Re-admit a host-swapped sequence: allocate exactly the
        parked page count (its KV is complete — no radix match, no
        prefill) and hand the engine a :class:`SwapInPlan` to scatter
        the content back.  On allocation failure the sequence simply
        waits, unless nothing is running and nothing can be preempted —
        then the ticket is dropped and the sequence folds to the
        recompute path, whose radix sharing may still fit it (and
        whose own fail-fast gives the definitive answer if not)."""
        pages = self.allocator.allocate(ticket.num_pages)
        if pages is None:
            if self.preempt_on_oom and not self.running:
                self.swap.discard_for(seq, reason="no_fit")
                seq.reset_for_recompute()
                seq._preempt_recompute = True  # type: ignore[attr-defined]
            return None
        self._dequeue(seq)
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))
        seq.pages = pages
        seq.slot = slot
        seq.status = SeqStatus.RUNNING
        self.slots[slot] = seq
        self.total_admitted += 1
        metrics.ACTIVE_SEQUENCES.set(len(self.running))
        return SwapInPlan(seq=seq, slot=slot, ticket=ticket)

    @engine_thread_only
    def commit_prefill(self, plan: PrefillPlan, stale: bool = False) -> None:
        """Index the pages a dispatched prefill has made reusable —
        called by the engine AFTER every writer program of the admission
        wave is enqueued, so a reader admitted in a later tick provably
        dispatches after the writer.  Flat mode registers the chain
        hashes; radix mode inserts the admission-time snapshot and
        releases the COW source lock.  ``stale`` (the sequence was
        checkpointed by a watchdog containment mid-dispatch) skips the
        insert — its snapshot pages were already released — but still
        drops the COW lock."""
        if self.radix is not None:
            if plan.radix_match is not None:
                self.radix.release_cow(plan.radix_match)
            if plan.radix_insert is not None and not stale:
                tokens, pages = plan.radix_insert
                node = self.radix.insert(tokens, pages)
                if node is not None:
                    # the adopted pages are still referenced by the
                    # RUNNING sequence: pin the path until its release
                    # (_radix_unlock), or eviction would count/strip
                    # seq-referenced pages as reclaimable
                    self.radix.lock_node(node)
                    plan.seq._radix_insert_node = (  # type: ignore[attr-defined]
                        node
                    )
            return
        if stale:
            return
        for page, h in plan.register_hashes or ():
            self.allocator.register(page, h)

    @engine_thread_only
    def maybe_trim(self) -> None:
        """Proactive cache trim (engine tick): keep the truly-free list
        above the evict watermark by evicting cold tree pages, so
        allocation bursts never pay the eviction walk synchronously and
        admission's kv_pressure shedding only engages when the pool is
        genuinely exhausted."""
        if (
            self._trim_target
            and self.allocator.num_truly_free < self._trim_target
        ):
            self.radix.trim_to_watermark(self._trim_target)

    @engine_thread_only
    def prepare_decode(
        self, active: List[Sequence], horizon: int = 1
    ) -> bool:
        """Allocate pages so every sequence can decode ``horizon`` steps
        (KV writes land at positions ``pos .. pos+horizon-1``) without
        crossing into unowned memory; preempt the youngest sequences on
        exhaustion.  Returns True when a decode step can proceed."""
        max_pages = cdiv(self.max_model_len, self.page_size)
        # higher tiers claim pages first, so when the pool runs dry
        # mid-loop it is the lower tiers that trigger preemption
        for seq in sorted(active, key=lambda s: (_rank(s), s.seq_id)):
            if seq.status is not SeqStatus.RUNNING:
                continue  # preempted by an earlier iteration
            # pages only need to cover the steps this sequence will KEEP
            # (overshoot past its budget is discarded at readback; those
            # writes fall through to the trash page once the page-table row
            # runs out of real pages)
            rem = max(1, seq.params.max_tokens) - seq.num_generated
            steps = max(1, min(horizon, rem))
            while True:
                # last position written within the horizon (clamped: steps
                # past max_model_len clip into the final page harmlessly)
                pos = seq.total_len - 1
                needed = min((pos + steps - 1) // self.page_size + 1,
                             max_pages)
                if len(seq.pages) >= needed:
                    break
                pages = self.allocator.allocate(1)
                if pages is not None:
                    seq.pages.extend(pages)
                    continue  # horizon may need several pages
                if not self.preempt_on_oom:
                    seq.fail(
                        KVCapacityError(
                            "KV pages exhausted mid-decode "
                            "(scheduler.preempt_on_oom is off); retry "
                            "when resident work completes"
                        )
                    )
                    self.remove(seq)
                    break
                victim = self._pick_victim()
                if victim is None or (
                    victim is seq and len(self.running) == 1
                ):
                    # alone and still no memory: the context can never fit
                    seq.fail(
                        KVCapacityError(
                            "KV pages exhausted: the sequence's grown "
                            f"context ({seq.total_len} tokens) cannot "
                            "fit the pool even alone; retry against a "
                            "less-loaded replica",
                            retry_after=5.0,
                        )
                    )
                    self.remove(seq)
                    break
                self._preempt(victim)
                if victim is seq:
                    break  # requester preempted itself; skip its decode
        return any(s is not None for s in self.slots)

    @engine_thread_only
    def _pick_victim(self) -> Optional[Sequence]:
        """Lowest-tier running sequence, youngest within the tier —
        under KV pressure batch work yields to interactive before any
        same-tier sequence is touched.  Possibly the requester itself."""
        running = self.running
        if not running:
            return None
        return max(running, key=lambda s: (_rank(s), s.seq_id))

    @engine_thread_only
    def _event(self, kind: str, seq: Sequence, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record_tick(
                kind,
                seq_id=seq.seq_id,
                request_id=seq.request_id,
                queue_depth=len(self.waiting),
                **fields,
            )

    @engine_thread_only
    def _preempt(self, seq: Sequence) -> None:
        # host swap tier first, BEFORE anything releases the pages:
        # park the valid KV (positions 0 .. total_len-2 — the final
        # sampled token's KV was never written) so re-admission resumes
        # decode with ZERO recompute.  Page content survives release()
        # untouched until reallocated, but the read must complete
        # before any later program could write these pages — both
        # happen on this engine thread, so reading first is sufficient.
        swapped = False
        if self.swap is not None:
            n_valid = cdiv(max(1, seq.total_len - 1), self.page_size)
            swapped = self.swap.swap_out_seq(seq, seq.pages[:n_valid])
        logger.warning(
            "preempting sequence for KV pressure",
            extra={
                "extra_data": {
                    "seq_id": seq.seq_id,
                    "request_id": seq.request_id,
                    "trace_id": getattr(seq.trace, "trace_id", None),
                    "resident_tokens": seq.total_len,
                    "swapped": swapped,
                }
            },
        )
        self._event(
            "preempt", seq, resident_tokens=seq.total_len,
            swapped=swapped,
        )
        if self.recorder is not None:
            # phase accounting: accrue the interrupted compute phase,
            # re-enter queue time (re-admission resumes at on_admit)
            self.recorder.on_preempt(seq)
        if seq.trace is not None:
            seq.trace.preempted()
        slot = seq.slot
        self._radix_unlock(seq)
        self.allocator.release(seq.pages)
        if slot is not None:
            self.slots[slot] = None
        if swapped:
            seq.reset_for_swap()
            self.total_swap_preempts += 1
        else:
            seq.reset_for_recompute()
            # marks the re-admission prefill as preemption-caused waste
            # (vgt_preempt_recompute_tokens — the cost the swap tier
            # exists to eliminate); counted when the re-prefill is
            # actually planned, cleared there
            seq._preempt_recompute = True  # type: ignore[attr-defined]
        self.waiting.appendleft(seq)
        self.total_preemptions += 1
        metrics.PREEMPTED_SEQUENCES.inc()
        metrics.ACTIVE_SEQUENCES.set(len(self.running))
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    # -- disaggregated prefill→decode handoff (runtime/handoff.py) --

    @engine_thread_only
    def hold_for_handoff(self, seq: Sequence) -> bool:
        """Fold a RUNNING sequence off the device and park its valid KV
        in the host pool for a prefill→decode handoff — mechanically a
        swap-preemption (same valid-KV bound, same ticket), but the
        sequence then sits in ``waiting`` marked HELD: ``_select_next``
        skips it, so it neither re-admits locally nor blocks admission,
        while every existing settle path (abort reap, deadline shed,
        containment fold) still finds it.  The exit paths:

        * transfer accepted → :meth:`evacuate` (dequeue + discard the
          local ticket; the decode worker owns the sequence now),
        * transfer failed / cancelled → :meth:`release_hold` (clear the
          mark; ``try_admit`` swap-ins the local ticket and decode
          continues monolithically with zero recompute).

        False = could not stage (no swap tier / pool full / readback
        raced a fold): the sequence keeps running untouched and the
        caller reports the monolithic fallback."""
        if self.swap is None or seq.status is not SeqStatus.RUNNING:
            return False
        n_valid = cdiv(max(1, seq.total_len - 1), self.page_size)
        if not self.swap.swap_out_seq(seq, seq.pages[:n_valid]):
            return False
        self._event(
            "handoff_hold", seq, resident_tokens=seq.total_len,
        )
        if self.recorder is not None:
            # phase accounting: accrue the interrupted compute phase;
            # re-enters queue time until the decode worker resumes it
            # (or release_hold re-admits it here)
            self.recorder.on_preempt(seq)
        slot = seq.slot
        self._radix_unlock(seq)
        self.allocator.release(seq.pages)
        if slot is not None:
            self.slots[slot] = None
        seq.reset_for_swap()
        seq._handoff_hold = True  # type: ignore[attr-defined]
        self.waiting.appendleft(seq)
        self.total_handoff_holds += 1
        metrics.ACTIVE_SEQUENCES.set(len(self.running))
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))
        return True

    @engine_thread_only
    def release_hold(self, seq: Sequence) -> None:
        """Lift a handoff hold: the transfer fell through (retries
        exhausted, decode pool drained, raced a cancel), so the
        sequence becomes an ordinary swapped-out waiting sequence —
        the next ``try_admit`` finds its live ticket and swap-ins for
        a monolithic local decode with zero recompute.  Idempotent;
        a no-op for settled or never-held sequences."""
        if getattr(seq, "_handoff_hold", False):
            seq._handoff_hold = False  # type: ignore[attr-defined]
            self._event("handoff_release", seq)

    # -- completion --

    @engine_thread_only
    def _radix_unlock(self, seq: Sequence) -> None:
        """Drop the sequence's tree path locks (idempotent; its page
        references are released with the rest of ``seq.pages``) — both
        the match-time path lock and the commit-time pin on the node
        holding its own adopted prompt pages."""
        if self.radix is None:
            return
        match = getattr(seq, "_radix_match", None)
        if match is not None:
            self.radix.unlock(match)
            seq._radix_match = None  # type: ignore[attr-defined]
        node = getattr(seq, "_radix_insert_node", None)
        if node is not None:
            self.radix.unlock_node(node)
            seq._radix_insert_node = None  # type: ignore[attr-defined]

    @engine_thread_only
    def _radix_insert_final(self, seq: Sequence) -> None:
        """Index a finishing sequence's GENERATED tokens too: turn N+1
        of a chat re-sends turn N's answer inside its prompt, so the
        transcript's full pages are exactly what the next request
        matches.  Valid KV covers positions ``0 .. total_len - 2`` (the
        final sampled token was never fed back, so its KV was never
        written) — only full pages at or below that bound insert."""
        if (
            self.radix is None
            or not self.insert_generated
            or seq.status is not SeqStatus.RUNNING
            or not seq.pages
        ):
            return
        n_full = (seq.total_len - 1) // self.page_size
        if n_full <= 0:
            return
        stream = seq.prompt_ids + seq.output_ids
        self.radix.insert(
            stream[: n_full * self.page_size], seq.pages[:n_full]
        )

    @engine_thread_only
    def _discard_swap(self, seq: Sequence, reason: str) -> None:
        """Drop a waiting sequence's parked host-pool KV (idempotent
        no-op for sequences without a live ticket) — called on every
        path that settles or re-folds a sequence out from under its
        ticket.  The manager's stale sweep is the backstop for any
        path that slips through (e.g. fatal containment, whose pool
        dies with the core anyway)."""
        if self.swap is not None:
            self.swap.discard_for(seq, reason=reason)

    @engine_thread_only
    def _release_residency(self, seq: Sequence) -> None:
        self._radix_unlock(seq)
        if seq.pages:
            self.allocator.release(seq.pages)
            seq.pages = []
        if seq.slot is not None and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        seq.slot = None
        metrics.ACTIVE_SEQUENCES.set(len(self.running))

    @engine_thread_only
    def remove(self, seq: Sequence) -> None:
        """Release residency after finish/failure.  A sequence finishing
        cleanly (the engine calls remove just before ``seq.finish``, so
        its status is still RUNNING — failures arrive already FAILED)
        donates its transcript's full pages to the radix tree first."""
        self._radix_insert_final(seq)
        self._release_residency(seq)
        self.total_finished += 1

    @engine_thread_only
    def evacuate(self, seq: Sequence) -> None:
        """Planned migration (engine thread only): release this
        sequence's residency or queue position WITHOUT settling it —
        unlike :meth:`abort`/:meth:`shed`, the future stays open; the
        caller folds the sequence (``Sequence.prepare_migrate``) and
        replays it into another replica.  Accounted as neither finished
        nor aborted: the sequence's terminal outcome happens wherever
        it lands."""
        if seq.status is SeqStatus.RUNNING:
            self._release_residency(seq)
        else:
            try:
                self.waiting.remove(seq)
            except ValueError:
                pass  # already dequeued (racing admission this tick)
            # a swapped-out waiting sequence folds to the recompute
            # path on the migration target (the parked KV is local to
            # this core's pool and cannot travel)
            self._discard_swap(seq, "stale")
            metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    @engine_thread_only
    def abort(self, seq: Sequence) -> None:
        """Client cancellation: release any residency, account it as
        aborted (NOT finished — the two are disjoint outcomes), and
        finish the sequence with reason "abort".  The single owner of
        abort bookkeeping for both the running and queued paths."""
        self._release_residency(seq)
        self._discard_swap(seq, "settled")
        self.total_aborted += 1
        metrics.CANCELLED_REQUESTS.labels(reason=seq.abort_reason).inc()
        self._event("abort", seq, reason=seq.abort_reason)
        seq.finish("abort")

    @engine_thread_only
    def fail_sequence(self, seq: Sequence, exc: BaseException) -> None:
        """Fail ONE sequence with a typed error, freeing its residency
        this tick (slot + KV pages) — the integrity soft-sentinel path:
        the sequence's own output is suspect (entropy collapse) but the
        engine and its weights are not, so the replica keeps serving
        everyone else."""
        if seq in self.waiting:
            self.waiting.remove(seq)
        self._release_residency(seq)
        self._discard_swap(seq, "settled")
        self._event("integrity_fail", seq, error=type(exc).__name__)
        seq.fail(exc)

    @engine_thread_only
    def shed(self, seq: Sequence, exc: DeadlineExceededError) -> None:
        """Deadline shed of a RUNNING sequence (the engine detected
        ``past_deadline`` between decode ticks and built the exception,
        which carries the partial text): release residency immediately —
        slot and KV pages free this tick, not at natural completion —
        and fail the owed future.  Counted with the queued sheds in
        ``total_deadline_shed``."""
        self._release_residency(seq)
        self.total_deadline_shed += 1
        metrics.CANCELLED_REQUESTS.labels(reason="deadline").inc()
        metrics.DEADLINE_PARTIAL_TOKENS.observe(seq.num_generated)
        self._event(
            "shed", seq, where="running",
            partial_tokens=seq.num_generated,
        )
        seq.fail(exc)

    def get_stats(self) -> dict:
        return {
            "waiting": len(self.waiting),
            "running": len(self.running),
            "slots": len(self.slots),
            "free_pages": self.allocator.num_free,
            "used_pages": self.allocator.num_used,
            "admitted": self.total_admitted,
            "finished": self.total_finished,
            "preemptions": self.total_preemptions,
            "swap_preempts": self.total_swap_preempts,
            "preempt_recompute_tokens": (
                self.total_preempt_recompute_tokens
            ),
            "deadline_shed": self.total_deadline_shed,
            "aborted": self.total_aborted,
            "prefix_cache": {
                "enabled": self.prefix_cache,
                "mode": "radix" if self.radix is not None else "flat",
                "hit_tokens": self.total_prefix_hit_tokens,
                "hit_pages": self.allocator.prefix_hits,
                "cached_pages": self.allocator.num_cached,
                "evictions": (
                    sum(self.radix.total_evictions.values())
                    if self.radix is not None
                    else self.allocator.prefix_evictions
                ),
                **(
                    {
                        "nodes": self.radix.total_nodes,
                        "inserted_pages": self.radix.total_inserted_pages,
                        "evictions_lru": self.radix.total_evictions.get(
                            "lru", 0
                        ),
                        "evictions_pressure": (
                            self.radix.total_evictions.get("pressure", 0)
                        ),
                        "cow_copies": self.radix.total_cow_copies,
                        "insert_suspended": self.radix.insert_suspended,
                        **(
                            {
                                "swapped_nodes": (
                                    self.radix._swapped_nodes
                                ),
                                "demoted_pages": (
                                    self.radix.total_demoted_pages
                                ),
                                "promoted_pages": (
                                    self.radix.total_promoted_pages
                                ),
                            }
                            if self.radix.swap is not None
                            else {}
                        ),
                    }
                    if self.radix is not None
                    else {}
                ),
            },
        }
