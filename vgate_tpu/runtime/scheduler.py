"""Continuous-batching scheduler.

Replaces the stop-the-world batch lock at the heart of the reference
(vgate/batcher.py:79,195 serializes every batch behind one asyncio.Lock,
SURVEY.md section 7 step 4) with per-step admission: the decode loop owns
the device, and between decode steps the scheduler admits waiting prompts
into free slots, allocates KV pages on demand, and preempts under memory
pressure.

Pure host-side policy, no JAX: fully unit-testable (SURVEY.md section 4's
CPU-only strategy).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Union

import numpy as np

from vgate_tpu import metrics
from vgate_tpu.errors import DeadlineExceededError
from vgate_tpu.logging_config import get_logger
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.utils.math import bucket_for, cdiv, round_up

logger = get_logger(__name__)


def _rank(seq: "Sequence") -> int:
    """Priority-tier rank from the request's SamplingParams
    (vgate_tpu/admission.py: 0 interactive, 1 standard, 2 batch);
    direct engine callers without the field schedule as standard."""
    return getattr(seq.params, "priority", 1)


class EngineBusyError(RuntimeError):
    """Raised at admission when the waiting queue is full (load shedding,
    SURVEY.md section 5.3: 'add deadlines/load-shedding at admission')."""

    # the 503 body's machine-readable flavor (vgate_tpu/errors.py)
    reason = "overloaded"


class AdmissionDeadlineExceeded(EngineBusyError):
    """A queued request waited past ``scheduler.admission_deadline_ms`` and
    was shed instead of admitted (the completion would arrive too late to
    be useful; SURVEY.md section 5.3)."""


@dataclass
class PrefillPlan:
    seq: Sequence
    slot: int
    bucket: int  # padded sequence length for this prefill program
    # prefix-cache reuse: the first cached_len prompt tokens' KV is already
    # resident in shared pages; only the suffix needs the prompt pass.
    # `bucket` then buckets the SUFFIX length, and register_hashes lists
    # (page, chain_hash) pairs to index once this prefill is dispatched.
    cached_len: int = 0
    register_hashes: list = None  # type: ignore[assignment]
    # chunked prefill: the (suffix) prompt exceeds the bucket cap and
    # runs as SERIAL suffix passes of `bucket` tokens each
    # (engine_core._dispatch_chunked_prefill)
    chunked: bool = False


@dataclass
class DecodePlan:
    seqs: List[Sequence]  # active sequences, indexed by slot in .slot


Plan = Union[PrefillPlan, DecodePlan]


class Scheduler:
    def __init__(
        self,
        allocator: PageAllocator,
        max_slots: int,
        page_size: int,
        prefill_buckets: List[int],
        max_model_len: int,
        max_queue_size: int = 512,
        preempt_on_oom: bool = True,
        admission_deadline_ms: float = 0.0,
        prefix_cache: bool = False,
        prefill_chunk: int = 0,
        text_fn=None,
        recorder=None,
    ) -> None:
        # optional flight recorder (observability/flight.py): residency
        # events (preempt/shed/abort) become post-mortem ring entries
        self.recorder = recorder
        # renders a sequence's partial generation for deadline-shed
        # metadata (the engine injects tokenizer.decode-backed
        # final_text); None keeps queued sheds text-less.  A preempted
        # sequence shed from the WAITING queue can hold generated
        # tokens, and its 504 must carry them like a running shed's.
        self.text_fn = text_fn
        self.allocator = allocator
        self.page_size = page_size
        # buckets: page-aligned, capped at max_model_len, and always
        # including a top bucket that can hold any admissible prompt
        # (preempted sequences re-prefill with their grown context).
        # With chunked prefill (prefill_chunk > 0) the ladder caps at the
        # chunk size instead, and longer prompts run serial suffix passes
        # of top-bucket tokens each.
        top = round_up(max_model_len, page_size)
        if prefill_chunk > 0:
            top = min(top, round_up(prefill_chunk, page_size))
        self.prefill_chunk = prefill_chunk
        aligned = {
            min(round_up(b, page_size), top)
            for b in prefill_buckets
            if b > 0
        }
        aligned.add(top)
        self.prefill_buckets = sorted(aligned)
        self.max_model_len = max_model_len
        self.max_queue_size = max_queue_size
        self.preempt_on_oom = preempt_on_oom
        self.admission_deadline_ms = admission_deadline_ms
        self.total_deadline_shed = 0
        self.prefix_cache = prefix_cache
        self.total_prefix_hit_tokens = 0
        self.waiting: Deque[Sequence] = deque()
        # sticky: set once any deadline-bearing sequence is ever queued,
        # so deployments without client deadlines skip _shed_expired's
        # per-tick queue scan entirely (try_admit runs in a tight loop
        # on the engine thread)
        self._deadline_seen = False
        # sticky twin for priority tiers: until a non-standard-priority
        # sequence is queued, admission selection stays head-of-queue
        self._priority_seen = False
        self.slots: List[Optional[Sequence]] = [None] * max_slots
        self.total_preemptions = 0
        self.total_admitted = 0
        self.total_finished = 0
        self.total_aborted = 0

    # -- admission --

    def add(self, seq: Sequence) -> None:
        if (
            len(self.waiting) >= self.max_queue_size
            and seq.resume_count == 0
        ):
            # replayed sequences (resume_count > 0: checkpointed across
            # an engine restart / dp failover) bypass the queue-full
            # gate — they were ALREADY admitted once and their clients
            # are still owed an answer; shedding them here would turn a
            # survivable restart into a 503 exactly when the rebuilt
            # queue is busiest.  Bounded: at most slots+queue sequences
            # existed pre-crash, so the overshoot is one queue's worth.
            raise EngineBusyError(
                f"engine queue full ({self.max_queue_size} waiting)"
            )
        if seq.num_prompt_tokens >= self.max_model_len:
            raise ValueError(
                f"prompt of {seq.num_prompt_tokens} tokens exceeds "
                f"max_model_len={self.max_model_len}"
            )
        if seq.deadline_t is not None:
            self._deadline_seen = True
        if _rank(seq) != 1:
            # sticky, like _deadline_seen: deployments without priority
            # tiers keep the O(1) head-of-queue admission path
            self._priority_seen = True
        self.waiting.append(seq)
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    # -- queries --

    @property
    def running(self) -> List[Sequence]:
        return [s for s in self.slots if s is not None]

    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            s is not None for s in self.slots
        )

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def has_admissible_waiting(self) -> bool:
        """True when the head-of-queue prompt could actually be admitted
        right now: a free slot exists AND its pages are allocatable.
        The engine's admission-pressure signals (re-tick without napping,
        decode-chunk cap) key off this — page-exhausted queues must NOT
        shrink chunks or spin, since admission is blocked on a sequence
        finishing, not on loop latency."""
        head = self._select_next()
        if head is None or self._free_slot() is None:
            return False
        n_pages = cdiv(max(1, head.num_prompt_tokens), self.page_size)
        if self.prefix_cache:
            # mirror try_admit's accounting: resident prefix pages are
            # shared, not allocated (peek — no refcount mutation).  A
            # matched page that is currently EVICTABLE counts toward
            # num_free, but try_admit's lookup() would revive it out of
            # that pool — subtract those or this predicate would say
            # "admissible" where allocate() then fails (busy-spin +
            # needless decode-chunk shrink).
            matched_evictable = 0
            for h in self._prefix_chain(head):
                page = self.allocator.peek(h)
                if page is None:
                    break
                n_pages -= 1
                if self.allocator.is_evictable(page):
                    matched_evictable += 1
            return (
                self.allocator.num_free - matched_evictable >= n_pages
            )
        return self.allocator.num_free >= n_pages

    # -- planning --

    def schedule(self) -> Optional[Plan]:
        """Pick the next device program: prefill-priority admission, else a
        decode step over the active slots.

        Convenience wrapper composing the two primitives the engine loop
        calls directly (``try_admit`` for async prefill dispatch and
        ``prepare_decode`` with a chunk horizon — engine_core.py:_tick);
        kept for simple single-step drivers and tests."""
        plan = self.try_admit()
        if plan is not None:
            return plan
        active = self.running
        if not active:
            return None
        if self.prepare_decode(active):
            # preemption may have emptied the slots
            active = self.running
            if active:
                return DecodePlan(seqs=active)
        return self.try_admit()  # everything preempted; try re-admission

    def _shed_expired(self) -> None:
        """Fail queued sequences whose deadline has passed (their
        completion would arrive too late to be useful).  Two deadlines
        apply: the global admission deadline (preempted sequences are
        exempt — they were already admitted once and hold generated
        tokens the client is owed) and each request's own end-to-end
        deadline (``seq.deadline_t``; applies unconditionally — the
        client's budget is blown either way)."""
        if not self.admission_deadline_ms and not self._deadline_seen:
            return
        admission_s = self.admission_deadline_ms / 1000.0
        now = time.perf_counter()
        kept: Deque[Sequence] = deque()
        shed = 0
        for seq in self.waiting:
            if seq.past_deadline(now):
                waited = (now - seq.arrival_t) * 1000
                partial_text = ""
                if seq.num_generated and self.text_fn is not None:
                    # preempted sequences re-enter the queue carrying
                    # generated tokens — their shed metadata must be as
                    # complete as a running shed's
                    try:
                        partial_text = self.text_fn(seq)
                    except Exception:  # pragma: no cover - defensive
                        pass
                self._event(
                    "shed", seq, where="queued",
                    partial_tokens=seq.num_generated,
                )
                # phase attribution from the recorder when attached: a
                # PREEMPTED sequence re-queued here spent most of its
                # budget computing, and reporting the whole lifetime as
                # queue_s would misattribute it
                if self.recorder is not None:
                    phases = self.recorder.phases_of(seq)
                else:
                    phases = {"queue_s": round(waited / 1000.0, 6)}
                seq.fail(
                    DeadlineExceededError(
                        f"request deadline "
                        f"({seq.params.timeout_s:.3f}s) passed after "
                        f"{waited:.0f}ms in queue, before generation "
                        "could finish",
                        partial_text=partial_text,
                        partial_tokens=seq.num_generated,
                        deadline_s=seq.params.timeout_s or 0.0,
                        phases=phases,
                    )
                )
                metrics.CANCELLED_REQUESTS.labels(reason="deadline").inc()
                metrics.DEADLINE_PARTIAL_TOKENS.observe(seq.num_generated)
                shed += 1
            elif (
                self.admission_deadline_ms
                and seq.preempt_count == 0
                and now - seq.arrival_t > admission_s
            ):
                self._event("shed", seq, where="admission")
                seq.fail(
                    AdmissionDeadlineExceeded(
                        f"request waited {(now - seq.arrival_t) * 1000:.0f}ms "
                        f"in queue (> {self.admission_deadline_ms:.0f}ms "
                        "admission deadline)"
                    )
                )
                shed += 1
            else:
                kept.append(seq)
        if shed:
            self.waiting = kept
            self.total_deadline_shed += shed
            metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))
            logger.warning(
                "shed requests past deadline",
                extra={"extra_data": {"shed": shed}},
            )

    def _prefix_chain(self, seq: Sequence) -> List[bytes]:
        """Chain digests, one per full prompt page, cached on the sequence
        (re-admission attempts under memory pressure must not rehash the
        prompt every tick).  sha256 over the token bytes — a collision
        would silently share another request's KV (the weakness behind
        vLLM's prefix-cache CVE-2025-25183), so the builtin hash() is not
        acceptable here."""
        import hashlib

        key = (len(seq.prompt_ids), seq.preempt_count)
        cached = getattr(seq, "_prefix_chain_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        n_full = seq.num_prompt_tokens // self.page_size
        # never match the ENTIRE prompt: the prefill program must run at
        # least one real token to produce the first sampled token
        if n_full * self.page_size == seq.num_prompt_tokens:
            n_full -= 1
        chain: List[bytes] = []
        h = b""
        for i in range(n_full):
            block = np.asarray(
                seq.prompt_ids[
                    i * self.page_size : (i + 1) * self.page_size
                ],
                np.int64,
            ).tobytes()
            h = hashlib.sha256(h + block).digest()
            chain.append(h)
        seq._prefix_chain_cache = (key, chain)  # type: ignore[attr-defined]
        return chain

    def _select_next(self) -> Optional[Sequence]:
        """Admission candidate: the oldest sequence of the most
        important waiting tier (rank, then seq_id — FIFO within a
        tier; a preempted sequence's old seq_id keeps it ahead of
        younger tier-mates on re-admission).  Aborted sequences are
        skipped here and reaped by ``_reap_aborted``.  Without priority
        tiers in play this is the head of the queue (O(1))."""
        if not self._priority_seen:
            for seq in self.waiting:  # head modulo an aborted prefix
                if not seq.abort_requested:
                    return seq
            return None
        best = None
        for seq in self.waiting:
            if seq.abort_requested:
                continue
            if best is None or (_rank(seq), seq.seq_id) < (
                _rank(best), best.seq_id
            ):
                best = seq
        return best

    def _dequeue(self, seq: Sequence) -> None:
        """Remove a selected sequence from the waiting queue — O(1) for
        the head (the only case without priority tiers in play)."""
        if self.waiting and self.waiting[0] is seq:
            self.waiting.popleft()
        else:
            self.waiting.remove(seq)

    def _reap_aborted(self) -> None:
        """Settle client-cancelled waiting sequences WHEREVER they sit.
        Head-only reaping is not enough once priority selection admits
        around the head: an aborted sequence parked behind a bypassed
        lower-tier head would otherwise never settle — its future (and
        the gateway's admission backlog charge) would leak forever."""
        if not any(s.abort_requested for s in self.waiting):
            return
        kept: Deque[Sequence] = deque()
        for seq in self.waiting:
            if seq.abort_requested:
                self.abort(seq)
            else:
                kept.append(seq)
        self.waiting = kept
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    def try_admit(self) -> Optional[PrefillPlan]:
        self._shed_expired()
        self._reap_aborted()
        if not self.waiting:
            return None
        slot = self._free_slot()
        if slot is None:
            return None
        seq = self._select_next()
        if seq is None:
            return None
        n_pages = cdiv(max(1, seq.num_prompt_tokens), self.page_size)

        # prefix cache: match the longest chain of full prompt pages
        # already resident; only the remainder allocates + prefills
        matched: List[int] = []
        chain: List[bytes] = []
        if self.prefix_cache:
            chain = self._prefix_chain(seq)
            for h in chain:
                page = self.allocator.lookup(h)
                if page is None:
                    break
                matched.append(page)

        pages = self.allocator.allocate(n_pages - len(matched))
        if pages is None:
            self.allocator.release(matched)
            if self.preempt_on_oom and not self.running:
                # nothing to preempt and still no memory: the prompt can
                # never fit — fail it rather than deadlock
                self._dequeue(seq)
                seq.fail(
                    RuntimeError(
                        "KV cache too small for prompt "
                        f"({seq.num_prompt_tokens} tokens)"
                    )
                )
            return None
        self._dequeue(seq)
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))
        seq.pages = matched + pages
        seq.slot = slot
        seq.status = SeqStatus.RUNNING
        self.slots[slot] = seq
        self.total_admitted += 1
        metrics.ACTIVE_SEQUENCES.set(len(self.running))
        cached_len = len(matched) * self.page_size
        self.total_prefix_hit_tokens += cached_len
        # hits count only on successful admission (a failed allocate above
        # rolls the references back and must not inflate the stat)
        self.allocator.prefix_hits += len(matched)
        # pages this prefill will fill (full prompt pages beyond the
        # matched prefix), for the ENGINE to index AFTER it dispatched the
        # program — registering here would let a same-tick reader's
        # program be grouped ahead of this writer's and gather unwritten
        # pages (same-wave identical prompts are the batcher dedup's job)
        register_hashes = [
            (seq.pages[i], chain[i]) for i in range(len(matched), len(chain))
        ]
        suffix_len = seq.num_prompt_tokens - cached_len
        top = self.prefill_buckets[-1]
        if suffix_len > top:
            # chunked prefill: serial suffix passes of `top` tokens
            return PrefillPlan(
                seq=seq, slot=slot, bucket=top, cached_len=cached_len,
                register_hashes=register_hashes, chunked=True,
            )
        bucket = bucket_for(suffix_len, self.prefill_buckets)
        return PrefillPlan(
            seq=seq, slot=slot, bucket=bucket, cached_len=cached_len,
            register_hashes=register_hashes,
        )

    def prepare_decode(
        self, active: List[Sequence], horizon: int = 1
    ) -> bool:
        """Allocate pages so every sequence can decode ``horizon`` steps
        (KV writes land at positions ``pos .. pos+horizon-1``) without
        crossing into unowned memory; preempt the youngest sequences on
        exhaustion.  Returns True when a decode step can proceed."""
        max_pages = cdiv(self.max_model_len, self.page_size)
        # higher tiers claim pages first, so when the pool runs dry
        # mid-loop it is the lower tiers that trigger preemption
        for seq in sorted(active, key=lambda s: (_rank(s), s.seq_id)):
            if seq.status is not SeqStatus.RUNNING:
                continue  # preempted by an earlier iteration
            # pages only need to cover the steps this sequence will KEEP
            # (overshoot past its budget is discarded at readback; those
            # writes fall through to the trash page once the page-table row
            # runs out of real pages)
            rem = max(1, seq.params.max_tokens) - seq.num_generated
            steps = max(1, min(horizon, rem))
            while True:
                # last position written within the horizon (clamped: steps
                # past max_model_len clip into the final page harmlessly)
                pos = seq.total_len - 1
                needed = min((pos + steps - 1) // self.page_size + 1,
                             max_pages)
                if len(seq.pages) >= needed:
                    break
                pages = self.allocator.allocate(1)
                if pages is not None:
                    seq.pages.extend(pages)
                    continue  # horizon may need several pages
                if not self.preempt_on_oom:
                    seq.fail(RuntimeError("KV pages exhausted"))
                    self.remove(seq)
                    break
                victim = self._pick_victim()
                if victim is None or (
                    victim is seq and len(self.running) == 1
                ):
                    # alone and still no memory: the context can never fit
                    seq.fail(RuntimeError("KV pages exhausted"))
                    self.remove(seq)
                    break
                self._preempt(victim)
                if victim is seq:
                    break  # requester preempted itself; skip its decode
        return any(s is not None for s in self.slots)

    def _pick_victim(self) -> Optional[Sequence]:
        """Lowest-tier running sequence, youngest within the tier —
        under KV pressure batch work yields to interactive before any
        same-tier sequence is touched.  Possibly the requester itself."""
        running = self.running
        if not running:
            return None
        return max(running, key=lambda s: (_rank(s), s.seq_id))

    def _event(self, kind: str, seq: Sequence, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record_tick(
                kind,
                seq_id=seq.seq_id,
                request_id=seq.request_id,
                queue_depth=len(self.waiting),
                **fields,
            )

    def _preempt(self, seq: Sequence) -> None:
        logger.warning(
            "preempting sequence for KV pressure",
            extra={
                "extra_data": {
                    "seq_id": seq.seq_id,
                    "request_id": seq.request_id,
                    "trace_id": getattr(seq.trace, "trace_id", None),
                    "resident_tokens": seq.total_len,
                }
            },
        )
        self._event("preempt", seq, resident_tokens=seq.total_len)
        if self.recorder is not None:
            # phase accounting: accrue the interrupted compute phase,
            # re-enter queue time (re-admission resumes at on_admit)
            self.recorder.on_preempt(seq)
        if seq.trace is not None:
            seq.trace.preempted()
        slot = seq.slot
        self.allocator.release(seq.pages)
        if slot is not None:
            self.slots[slot] = None
        seq.reset_for_recompute()
        self.waiting.appendleft(seq)
        self.total_preemptions += 1
        metrics.PREEMPTED_SEQUENCES.inc()
        metrics.ACTIVE_SEQUENCES.set(len(self.running))
        metrics.ENGINE_QUEUE_DEPTH.set(len(self.waiting))

    # -- completion --

    def _release_residency(self, seq: Sequence) -> None:
        if seq.pages:
            self.allocator.release(seq.pages)
            seq.pages = []
        if seq.slot is not None and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        seq.slot = None
        metrics.ACTIVE_SEQUENCES.set(len(self.running))

    def remove(self, seq: Sequence) -> None:
        """Release residency after finish/failure."""
        self._release_residency(seq)
        self.total_finished += 1

    def abort(self, seq: Sequence) -> None:
        """Client cancellation: release any residency, account it as
        aborted (NOT finished — the two are disjoint outcomes), and
        finish the sequence with reason "abort".  The single owner of
        abort bookkeeping for both the running and queued paths."""
        self._release_residency(seq)
        self.total_aborted += 1
        metrics.CANCELLED_REQUESTS.labels(reason=seq.abort_reason).inc()
        self._event("abort", seq, reason=seq.abort_reason)
        seq.finish("abort")

    def shed(self, seq: Sequence, exc: DeadlineExceededError) -> None:
        """Deadline shed of a RUNNING sequence (the engine detected
        ``past_deadline`` between decode ticks and built the exception,
        which carries the partial text): release residency immediately —
        slot and KV pages free this tick, not at natural completion —
        and fail the owed future.  Counted with the queued sheds in
        ``total_deadline_shed``."""
        self._release_residency(seq)
        self.total_deadline_shed += 1
        metrics.CANCELLED_REQUESTS.labels(reason="deadline").inc()
        metrics.DEADLINE_PARTIAL_TOKENS.observe(seq.num_generated)
        self._event(
            "shed", seq, where="running",
            partial_tokens=seq.num_generated,
        )
        seq.fail(exc)

    def get_stats(self) -> dict:
        return {
            "waiting": len(self.waiting),
            "running": len(self.running),
            "slots": len(self.slots),
            "free_pages": self.allocator.num_free,
            "used_pages": self.allocator.num_used,
            "admitted": self.total_admitted,
            "finished": self.total_finished,
            "preemptions": self.total_preemptions,
            "deadline_shed": self.total_deadline_shed,
            "aborted": self.total_aborted,
            "prefix_cache": {
                "enabled": self.prefix_cache,
                "hit_tokens": self.total_prefix_hit_tokens,
                "hit_pages": self.allocator.prefix_hits,
                "cached_pages": self.allocator.num_cached,
                "evictions": self.allocator.prefix_evictions,
            },
        }
