"""Host-RAM KV swap tier: a budgeted page pool UNDER the device pool.

Every KV-pressure response the engine had before this module destroys
work: preemption releases the victim's pages and re-prefills from
scratch (``Sequence.reset_for_recompute``), and radix-cache eviction
discards warm prefix pages outright — so under sustained overload the
engine burns prefill FLOPs re-deriving KV it just threw away, exactly
when it can least afford to (the recompute storm).  vLLM's answer is a
CPU swap space behind the paged allocator; this is its first-party
twin: a pinned host-RAM pool (``kv_cache.host_swap_bytes``, 0 = off ⇒
byte-identical engine) that gives the pressure ladder a third tier
between "resident" and "gone":

* **Preemption swap-out**: the victim's valid KV pages are read back
  device→host (chunked, at a tick boundary) *instead of* being
  recomputed later; re-admission scatters them host→device and decode
  resumes at the exact position it stopped — token-identical, zero
  prefill.  ``reset_for_recompute`` stays as the fallback when the
  pool is full or the ticket went stale (engine restart, migration).
* **Radix demotion (victim cache)**: pressure/LRU eviction of
  lock-free leaf pages demotes them here before truly discarding; a
  later ``match()`` promotes them back into fresh device pages, so a
  warm prefix tree survives a KV squeeze.

The manager is pure host-side policy — the device work is behind an
injected *executor* (``read_pages(pages) -> payload`` /
``write_pages(pages, payload)``), so the whole tier is unit-testable
with a fake device exactly like the scheduler and radix cache
(tests/test_kv_swap.py; the randomized radix drill drives demote/
promote/discard against the allocator invariants).  All mutation runs
on the engine thread; the gateway only ever reads the plain-int
occupancy gauges through ``pressure_signals``.

Priority under budget pressure: client-owed work wins.  A preemption
swap-out may discard prefix (victim-cache) tickets LRU-first to make
room; a prefix demotion never discards anything but stale tickets —
rotating the victim cache to admit a colder entry would be pure churn.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from vgate_tpu import metrics
from vgate_tpu.analysis.annotations import engine_thread_only
from vgate_tpu.logging_config import get_logger
from vgate_tpu.runtime.sequence import Sequence, SeqStatus

logger = get_logger(__name__)

# Obligation contract (vgtlint obligations checker): host-pool bytes
# are charged exactly once per ticket and refunded exactly once — the
# PR-11 review-round bug was a DOUBLE refund on the sweep-then-settle
# path (the registry, not the seq attribute, is the accounting truth).
# A charge is discharged by parking the ticket in its registry
# (transfer_assign) or refunding; _count_discard subsumes _refund.
VGT_OBLIGATIONS = {
    "host-pool-bytes": {
        "acquire": ("self._charge",),
        "release": ("self._refund", "self._count_discard"),
        "transfer_assign": ("self._seq_tickets", "self._prefix_lru"),
    },
}


class SwapTicket:
    """One swapped-out run of KV pages parked in host RAM.

    ``kind`` is ``"seq"`` (a preempted sequence's resident KV; validity
    is epoch-guarded by ``seq.preempt_count`` so a checkpoint/replay or
    a second fold can never resume against stale content) or
    ``"prefix"`` (a demoted radix-tree leaf; the owning node keeps the
    ticket on ``node.swapped`` and the token-keyed tree itself is the
    lookup index).  ``payload`` is opaque to the pool — the device
    executor produced it and only the device executor reads it.
    """

    __slots__ = (
        "kind", "num_pages", "nbytes", "payload", "seq_id", "epoch",
        "node", "created_t",
    )

    def __init__(
        self,
        kind: str,
        num_pages: int,
        nbytes: int,
        payload: Any,
        seq_id: Optional[int] = None,
        epoch: int = 0,
        node: Any = None,
    ) -> None:
        self.kind = kind
        self.num_pages = num_pages
        self.nbytes = nbytes
        self.payload = payload
        self.seq_id = seq_id
        self.epoch = epoch
        self.node = node
        self.created_t = time.monotonic()


class KVSwapManager:
    """Budgeted host-RAM page pool + swap policy (the "host pool").

    ``lock`` is the publication guard shared with the engine's readback
    lock: the chunked device read for a swap-out can block for a long
    time, and a watchdog containment may fold the victim meanwhile —
    the ticket is only published under the lock, against a re-checked
    status/epoch, mirroring every other readback path.
    """

    def __init__(
        self,
        budget_bytes: int,
        page_bytes: int,
        executor: Any,
        lock: Optional[threading.Lock] = None,
    ) -> None:
        self.budget_bytes = max(0, int(budget_bytes))
        self.page_bytes = max(1, int(page_bytes))
        self.executor = executor
        self._lock = lock if lock is not None else threading.Lock()
        self.used_bytes = 0
        # seq tickets by seq_id (the seq also holds seq._swap_ticket);
        # prefix tickets in LRU order (oldest first) for capacity drops
        self._seq_tickets: Dict[int, tuple] = {}  # seq_id -> (seq, ticket)
        self._prefix_lru: Dict[int, SwapTicket] = {}  # id(ticket) -> ticket
        # brownout L4 ("bypass cache writes"): stop demotions, keep
        # serving promotions — flipped cross-thread via
        # EngineCore.set_prefix_insert_suspended (GIL-atomic bool store)
        self.demote_suspended = False
        # radix hook: called when a prefix ticket is dropped for
        # capacity so the tree unlinks the page-less node
        self.on_drop_node: Optional[Callable[[Any], None]] = None
        self.total_swap_out_pages = {"preempt": 0, "prefix": 0}
        self.total_swap_in_pages = {"preempt": 0, "prefix": 0}
        self.total_discard_pages: Dict[str, int] = {}
        self.total_refused = 0

    # ------------------------------------------------------- accounting

    @property
    def free_bytes(self) -> int:
        return max(0, self.budget_bytes - self.used_bytes)

    @engine_thread_only
    def _charge(self, nbytes: int) -> None:
        self.used_bytes += nbytes
        metrics.KV_HOST_POOL_BYTES.set(self.used_bytes)

    @engine_thread_only
    def _refund(self, nbytes: int) -> None:
        self.used_bytes = max(0, self.used_bytes - nbytes)
        metrics.KV_HOST_POOL_BYTES.set(self.used_bytes)

    @engine_thread_only
    def _count_discard(self, ticket: SwapTicket, reason: str) -> None:
        self._refund(ticket.nbytes)
        ticket.payload = None
        self.total_discard_pages[reason] = (
            self.total_discard_pages.get(reason, 0) + ticket.num_pages
        )
        metrics.KV_SWAP_DISCARD_PAGES.labels(reason=reason).inc(
            ticket.num_pages
        )

    @engine_thread_only
    def _sweep_stale(self) -> None:
        """Drop seq tickets whose owner can never claim them: settled
        (finished/failed/aborted elsewhere) or epoch-mismatched (the
        sequence was folded for recompute/replay/migration — its
        generation now rides inside the prompt and the parked KV is
        for a dead epoch).  The explicit discard hooks on every settle
        path make this a backstop, not the mechanism."""
        dead = []
        for seq_id, (seq, ticket) in self._seq_tickets.items():
            if seq.status in (SeqStatus.FINISHED, SeqStatus.FAILED):
                dead.append((seq_id, ticket, "settled"))
            elif (
                seq.preempt_count != ticket.epoch
                or getattr(seq, "_swap_ticket", None) is not ticket
            ):
                dead.append((seq_id, ticket, "stale"))
        for seq_id, ticket, reason in dead:
            seq = self._seq_tickets.pop(seq_id)[0]
            if getattr(seq, "_swap_ticket", None) is ticket:
                seq._swap_ticket = None  # type: ignore[attr-defined]
            self._count_discard(ticket, reason)

    @engine_thread_only
    def _make_room(self, nbytes: int, evict_prefix: bool) -> bool:
        if nbytes > self.budget_bytes:
            return False
        if self.free_bytes >= nbytes:
            return True
        self._sweep_stale()
        while evict_prefix and self.free_bytes < nbytes and self._prefix_lru:
            # oldest victim-cache entry goes first; client-owed seq
            # tickets are never discarded to make room
            key = next(iter(self._prefix_lru))
            ticket = self._prefix_lru.pop(key)
            self._count_discard(ticket, "capacity")
            if self.on_drop_node is not None and ticket.node is not None:
                self.on_drop_node(ticket.node)
            ticket.node = None
        return self.free_bytes >= nbytes

    # ---------------------------------------------- preempted sequences

    @engine_thread_only
    def swap_out_seq(self, seq: Sequence, pages: List[int]) -> bool:
        """Park a preemption victim's valid KV pages in the host pool.

        Called by the scheduler BEFORE the pages are released; on True
        the caller resumes the sequence later via swap-in instead of
        recompute (``Sequence.reset_for_swap``).  The ticket's epoch is
        the preempt_count the sequence will have AFTER that reset, so a
        containment fold in between (which bumps the epoch again)
        invalidates it automatically."""
        if self.budget_bytes <= 0 or not pages:
            return False
        nbytes = len(pages) * self.page_bytes
        if not self._make_room(nbytes, evict_prefix=True):
            self.total_refused += 1
            return False
        epoch0 = seq.preempt_count
        try:
            payload = self.executor.read_pages(pages)
        except Exception:
            logger.warning(
                "swap-out readback failed; falling back to recompute",
                exc_info=True,
            )
            return False
        with self._lock:
            # stale-wake guard, mirroring every other readback: a
            # watchdog containment may have folded this sequence while
            # the device read above was blocked — its epoch moved, and
            # publishing the ticket now would resume a dead epoch
            if (
                seq.status is not SeqStatus.RUNNING
                or seq.preempt_count != epoch0
            ):
                return False
            ticket = SwapTicket(
                "seq", len(pages), nbytes, payload,
                seq_id=seq.seq_id, epoch=epoch0 + 1,
            )
            seq._swap_ticket = ticket  # type: ignore[attr-defined]
            seq.swap_count += 1
            # charge, then park in the registry that owns the refund
            # from here on — nothing can raise between the two, so the
            # charge can never outlive an unregistered ticket
            self._charge(nbytes)
            self._seq_tickets[seq.seq_id] = (seq, ticket)
        self.total_swap_out_pages["preempt"] += len(pages)
        metrics.KV_SWAP_OUT_PAGES.labels(kind="preempt").inc(len(pages))
        return True

    @engine_thread_only
    def adopt_remote(
        self, seq: Sequence, payload: Any, num_pages: int
    ) -> bool:
        """Park a payload that arrived OVER THE WIRE (disaggregated
        prefill→decode handoff, runtime/handoff.py) as ``seq``'s swap
        ticket, exactly as if this worker had swapped it out itself —
        the normal ``try_admit`` swap-in path then restores the pages
        with zero recompute.  The sequence is WAITING (never ran here),
        so the ticket epoch is its CURRENT preempt_count; any later
        containment fold bumps the epoch and invalidates the ticket,
        and the fold's prompt then carries the generation instead.
        False (no budget / no room) sends the caller to the recompute
        fallback — correct, just slower."""
        if self.budget_bytes <= 0 or num_pages <= 0:
            return False
        nbytes = num_pages * self.page_bytes
        if not self._make_room(nbytes, evict_prefix=True):
            self.total_refused += 1
            return False
        with self._lock:
            ticket = SwapTicket(
                "seq", num_pages, nbytes, payload,
                seq_id=seq.seq_id, epoch=seq.preempt_count,
            )
            seq._swap_ticket = ticket  # type: ignore[attr-defined]
            seq.swap_count += 1
            self._charge(nbytes)
            self._seq_tickets[seq.seq_id] = (seq, ticket)
        self.total_swap_in_pages["handoff"] = (
            self.total_swap_in_pages.get("handoff", 0) + num_pages
        )
        metrics.KV_SWAP_IN_PAGES.labels(kind="handoff").inc(num_pages)
        return True

    @engine_thread_only
    def ticket_for(self, seq: Sequence) -> Optional[SwapTicket]:
        """The sequence's live swap ticket, or None — an invalid ticket
        (epoch moved under a fold, pool lost it) is discarded here so
        the caller falls back to the recompute path cleanly."""
        ticket = getattr(seq, "_swap_ticket", None)
        if ticket is None:
            return None
        if (
            seq.status is not SeqStatus.WAITING
            or seq.preempt_count != ticket.epoch
            or self._seq_tickets.get(seq.seq_id, (None, None))[1]
            is not ticket
        ):
            self.discard_for(seq, reason="stale")
            return None
        return ticket

    @engine_thread_only
    def swap_in_seq(self, seq: Sequence, pages: List[int]) -> int:
        """Scatter a parked sequence's KV into its freshly-allocated
        device pages (engine thread, at admission).  Returns the page
        count; the ticket is consumed.  An executor failure propagates
        — a failed device dispatch is an engine fatal like any other,
        and containment folds the sequence for replay."""
        ticket = getattr(seq, "_swap_ticket", None)
        assert ticket is not None and len(pages) == ticket.num_pages
        self._seq_tickets.pop(seq.seq_id, None)
        seq._swap_ticket = None  # type: ignore[attr-defined]
        try:
            self.executor.write_pages(pages, ticket.payload)
        finally:
            self._refund(ticket.nbytes)
            ticket.payload = None
        self.total_swap_in_pages["preempt"] += len(pages)
        metrics.KV_SWAP_IN_PAGES.labels(kind="preempt").inc(len(pages))
        return len(pages)

    @engine_thread_only
    def discard_for(self, seq: Sequence, reason: str = "settled") -> None:
        """Drop a sequence's parked KV (idempotent): the sequence
        settled, was evacuated, or folded to the recompute path.  The
        registry is the single accounting truth — a ticket the stale
        sweep already discarded (registry entry gone) must not refund
        its bytes a second time just because the seq attribute
        lingered."""
        if getattr(seq, "_swap_ticket", None) is not None:
            seq._swap_ticket = None  # type: ignore[attr-defined]
        entry = self._seq_tickets.pop(seq.seq_id, None)
        if entry is not None:
            self._count_discard(entry[1], reason)

    # --------------------------------------------- radix prefix victims

    @engine_thread_only
    def demote_node(self, node: Any, pages: List[int]) -> Optional[SwapTicket]:
        """Victim-cache a radix leaf's pages before eviction frees
        them.  Only stale tickets are swept to make room — a demotion
        never rotates other victim-cache entries out (see module
        docstring).  Returns the ticket (the caller parks it on
        ``node.swapped``) or None to discard as before."""
        if (
            self.budget_bytes <= 0
            or self.demote_suspended
            or not pages
        ):
            return None
        nbytes = len(pages) * self.page_bytes
        if not self._make_room(nbytes, evict_prefix=False):
            self.total_refused += 1
            return None
        try:
            payload = self.executor.read_pages(pages)
        except Exception:
            logger.warning("prefix demotion readback failed", exc_info=True)
            return None
        ticket = SwapTicket(
            "prefix", len(pages), nbytes, payload, node=node
        )
        # charge, then park in the LRU registry that owns the refund
        self._charge(nbytes)
        self._prefix_lru[id(ticket)] = ticket
        self.total_swap_out_pages["prefix"] += len(pages)
        metrics.KV_SWAP_OUT_PAGES.labels(kind="prefix").inc(len(pages))
        return ticket

    @engine_thread_only
    def promote_node(self, ticket: SwapTicket, pages: List[int]) -> bool:
        """Restore a demoted leaf's KV into fresh device pages (a
        ``match()`` walked into it).  Consumes the ticket.  Promotion
        is always served, even at brownout L4 — existing warm content
        saving prefill is exactly what overload needs."""
        assert len(pages) == ticket.num_pages
        self._prefix_lru.pop(id(ticket), None)
        try:
            self.executor.write_pages(pages, ticket.payload)
        finally:
            self._refund(ticket.nbytes)
            ticket.payload = None
            ticket.node = None
        self.total_swap_in_pages["prefix"] += len(pages)
        metrics.KV_SWAP_IN_PAGES.labels(kind="prefix").inc(len(pages))
        return True

    @engine_thread_only
    def drop_node_ticket(
        self, ticket: SwapTicket, reason: str = "settled"
    ) -> None:
        """Radix-side discard (tree reset, failed promotion)."""
        if self._prefix_lru.pop(id(ticket), None) is not None:
            self._count_discard(ticket, reason)
            ticket.node = None

    # ----------------------------------------------------- introspection

    def signal_block(self) -> Dict[str, Any]:
        """Plain-int gauges for ``pressure_signals`` (cross-thread
        reads; GIL-atomic)."""
        budget = max(1, self.budget_bytes)
        return {
            "kv_swap_enabled": True,
            "kv_host_pool_bytes": self.used_bytes,
            "kv_host_pool_budget_bytes": self.budget_bytes,
            "kv_host_free_ratio": round(
                (budget - self.used_bytes) / budget, 4
            ),
            "kv_swapped_seqs": len(self._seq_tickets),
        }

    def get_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.budget_bytes > 0,
            "budget_bytes": self.budget_bytes,
            "used_bytes": self.used_bytes,
            "page_bytes": self.page_bytes,
            "swapped_seqs": len(self._seq_tickets),
            "prefix_tickets": len(self._prefix_lru),
            "swap_out_pages": dict(self.total_swap_out_pages),
            "swap_in_pages": dict(self.total_swap_in_pages),
            "discard_pages": dict(self.total_discard_pages),
            "refused": self.total_refused,
            "demote_suspended": self.demote_suspended,
        }
