"""The TPU engine core: a device-owning continuous-batching loop.

Architecture (SURVEY.md section 7, stages 3-4):

* One **engine thread** owns the device.  Each iteration it asks the
  scheduler for a plan: admit-and-prefill one waiting prompt, or run one
  decode step over every active slot.  New sequences therefore join between
  decode steps — no stop-the-world batch (the reference's design it
  replaces: vgate/batcher.py:195's global lock around blocking generate).
* **Two compiled programs** cover all steady-state work: a decode step at
  the static shape [max_batch_slots], and one prefill program per sequence
  bucket.  Sampling runs inside both programs with per-slot parameters.
* KV pages are donated through every call so XLA updates them in place.
* The async serving world talks to the thread via a submit queue +
  ``threading.Event`` per sequence; token streaming via per-token callbacks.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

import jax
import jax.numpy as jnp
import numpy as np

from vgate_tpu import metrics
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import VGTConfig, get_config
from vgate_tpu.logging_config import get_logger
from vgate_tpu.models.decoder import decode_forward, prefill_forward
from vgate_tpu.models.specs import ModelSpec, spec_for_model_id
from vgate_tpu.ops.sampling import sample_tokens
from vgate_tpu.parallel.mesh import build_mesh
from vgate_tpu.parallel.sharding import kv_pspec, named, shard_params
from vgate_tpu.runtime.kv_cache import (
    KVGeometry,
    PageAllocator,
    auto_num_pages,
    make_kv_buffers,
)
from vgate_tpu.runtime.scheduler import DecodePlan, PrefillPlan, Scheduler
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.tokenizer import get_tokenizer
from vgate_tpu.runtime.weights import load_or_init_params
from vgate_tpu.utils.math import cdiv

logger = get_logger(__name__)

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}


@functools.partial(jax.jit, static_argnames=("spec",), donate_argnames=("k_pages", "v_pages"))
def _prefill_step(
    params, spec: ModelSpec, tokens, seq_lens, k_pages, v_pages,
    page_tables, temps, top_ps, top_ks, key,
):
    logits, k_pages, v_pages = prefill_forward(
        params, spec, tokens, seq_lens, k_pages, v_pages, page_tables
    )
    next_tokens = sample_tokens(logits, temps, top_ps, top_ks, key)
    return next_tokens, k_pages, v_pages


@functools.partial(
    jax.jit,
    static_argnames=("spec", "use_pallas"),
    donate_argnames=("k_pages", "v_pages"),
)
def _decode_step(
    params, spec: ModelSpec, tokens, positions, k_pages, v_pages,
    page_tables, active, temps, top_ps, top_ks, base_key, counter,
    use_pallas=False,
):
    """One decode step.  tokens/positions/counter are device-resident state
    threaded between steps (the host only re-uploads them when slot
    membership changes — see EngineCore._run_decode)."""
    key = jax.random.fold_in(base_key, counter)
    logits, k_pages, v_pages = decode_forward(
        params, spec, tokens, positions, k_pages, v_pages, page_tables,
        active=active, use_pallas=use_pallas,
    )
    next_tokens = sample_tokens(logits, temps, top_ps, top_ks, key)
    positions_next = positions + active.astype(positions.dtype)
    return next_tokens, positions_next, counter + 1, k_pages, v_pages


class EngineCore:
    """Owns params, KV pages, the mesh and the engine thread."""

    def __init__(
        self,
        config: Optional[VGTConfig] = None,
        spec: Optional[ModelSpec] = None,
        params: Optional[Any] = None,
        devices: Optional[list] = None,
    ) -> None:
        self.config = config or get_config()
        self.spec = spec or spec_for_model_id(self.config.model.model_id)
        tpu_cfg = self.config.tpu
        self.dtype = _DTYPES[self.config.model.dtype]
        self.mesh = build_mesh(tpu_cfg, devices)
        self.tokenizer = get_tokenizer(
            self.spec,
            self.config.model.tokenizer_path
            or self.config.model.checkpoint_path,
        )

        load_start = time.perf_counter()
        if params is None:
            params = load_or_init_params(
                self.spec, self.config.model.checkpoint_path, self.dtype
            )
        self.params = shard_params(params, self.spec, self.mesh)
        if self.config.model.quantization == "int8":
            from vgate_tpu.ops.quant import quantize_decoder_params

            # quantize after sharding: the eager ops run SPMD on the mesh,
            # so scales inherit the weights' tp layout
            self.params = quantize_decoder_params(self.params, self.spec)
        jax.block_until_ready(jax.tree.leaves(self.params)[0])
        self.load_time_s = time.perf_counter() - load_start

        params_bytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(self.params)
        )
        # more pages than every slot's full context can never be used, and
        # bounding the pool keeps the page-scatter/gather programs small
        pages_per_seq = cdiv(
            self.config.model.max_model_len, tpu_cfg.kv_page_size
        )
        max_useful = tpu_cfg.max_batch_slots * pages_per_seq + 1
        num_pages = tpu_cfg.kv_num_pages or min(
            max_useful,
            auto_num_pages(
                self.spec,
                tpu_cfg.kv_page_size,
                tpu_cfg.hbm_utilization,
                device=self.mesh.devices.flat[0],
                params_bytes=params_bytes,
            ),
        )
        self.geometry = KVGeometry(
            num_layers=self.spec.num_layers,
            num_pages=num_pages,
            page_size=tpu_cfg.kv_page_size,
            kv_heads=self.spec.num_kv_heads,
            head_dim=self.spec.head_dim,
            max_model_len=self.config.model.max_model_len,
        )
        kv_sharding = named(self.mesh, kv_pspec(self.spec, self.mesh))
        self.k_pages, self.v_pages = make_kv_buffers(
            self.geometry, self.dtype, kv_sharding
        )
        self.allocator = PageAllocator(num_pages)
        self.max_slots = tpu_cfg.max_batch_slots
        self.scheduler = Scheduler(
            allocator=self.allocator,
            max_slots=self.max_slots,
            page_size=tpu_cfg.kv_page_size,
            prefill_buckets=tpu_cfg.prefill_buckets,
            max_model_len=self.config.model.max_model_len,
            max_queue_size=self.config.scheduler.max_queue_size,
            preempt_on_oom=self.config.scheduler.preempt_on_oom,
        )

        # host-side mirror of the device page tables, one row per slot
        self._page_tables_np = np.zeros(
            (self.max_slots, self.geometry.pages_per_seq), np.int32
        )
        self._base_key = jax.random.PRNGKey(self.config.model.max_model_len)
        self._step_counter = 0
        self._compiled_buckets: set = set()
        self._decode_compiled = False
        self._dec_state: Optional[Dict[str, Any]] = None
        self._decode_signature_cache: Optional[tuple] = None

        # Pallas kernels require a real TPU backend (tests run interpret-mode
        # kernels separately; the engine's jnp twins serve CPU meshes)
        self.use_pallas = bool(
            tpu_cfg.use_pallas
            and self.mesh.devices.flat[0].platform == "tpu"
        )
        self._submit_q: "queue.Queue[Sequence]" = queue.Queue()
        self._wakeup = threading.Event()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._fatal: Optional[BaseException] = None
        self.total_steps = 0
        self.total_prefills = 0
        self.total_decode_tokens = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="vgt-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wakeup.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # ------------------------------------------------------------ submission

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
    ) -> Sequence:
        if self._fatal is not None:
            raise RuntimeError("engine is dead") from self._fatal
        seq = Sequence(
            prompt_ids=list(prompt_ids),
            params=params,
            stream_cb=stream_cb,
        )
        self._submit_q.put(seq)
        self._wakeup.set()
        return seq

    def submit_prompt(
        self,
        prompt: str,
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
    ) -> Sequence:
        ids = self.tokenizer.encode(prompt)
        max_prompt = self.config.model.max_model_len - 1
        if len(ids) > max_prompt:
            ids = ids[-max_prompt:]  # keep the suffix (chat-style truncation)
        return self.submit_tokens(ids or [self.tokenizer.bos_id], params, stream_cb)

    def generate(
        self, prompts: Seq[str], params: Seq[SamplingParams]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API used by the sync backend seam."""
        seqs = [
            self.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            text = self.tokenizer.decode(seq.generated_ids)
            gen_time = (seq.finish_t or 0) - seq.arrival_t
            n = seq.num_output_tokens
            results.append(
                {
                    "text": text,
                    "token_ids": list(seq.generated_ids),
                    "num_tokens": n,
                    "prompt_tokens": seq.orig_prompt_len,
                    "finish_reason": seq.finish_reason,
                    "metrics": {
                        "ttft": seq.ttft or 0.0,
                        "tpot": seq.tpot or 0.0,
                        "gen_time": gen_time,
                    },
                }
            )
        return results

    # ------------------------------------------------------------ the loop

    def _loop(self) -> None:
        logger.info("engine thread started")
        while self._running:
            try:
                self._drain_submissions()
                plan = self.scheduler.schedule()
                if plan is None:
                    self._wakeup.wait(timeout=0.005)
                    self._wakeup.clear()
                    continue
                if isinstance(plan, PrefillPlan):
                    self._run_prefill(plan)
                else:
                    self._run_decode(plan)
                self.total_steps += 1
            except Exception as exc:  # pragma: no cover - engine fatal path
                logger.error("engine loop fatal error", exc_info=True)
                self._fatal = exc
                for seq in list(self.scheduler.running) + list(
                    self.scheduler.waiting
                ):
                    seq.fail(exc)
                self.scheduler.waiting.clear()
                for i in range(len(self.scheduler.slots)):
                    self.scheduler.slots[i] = None
                self._running = False
        logger.info("engine thread stopped")

    def _drain_submissions(self) -> None:
        while True:
            try:
                seq = self._submit_q.get_nowait()
            except queue.Empty:
                return
            try:
                self.scheduler.add(seq)
            except Exception as exc:
                seq.fail(exc)

    def _step_key(self):
        self._step_counter += 1
        return jax.random.fold_in(self._base_key, self._step_counter)

    def _run_prefill(self, plan: PrefillPlan) -> None:
        seq, bucket = plan.seq, plan.bucket
        ps = self.geometry.page_size
        n_prompt = seq.num_prompt_tokens
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n_prompt] = seq.prompt_ids
        # page table row for this prefill: real pages then trash padding
        row = np.zeros((self.geometry.pages_per_seq,), np.int32)
        row[: len(seq.pages)] = seq.pages
        self._page_tables_np[plan.slot] = row
        n_bucket_pages = bucket // ps
        prefill_pt = np.zeros((1, n_bucket_pages), np.int32)
        prefill_pt[0, : len(seq.pages)] = seq.pages[:n_bucket_pages]

        sp = seq.params
        if bucket not in self._compiled_buckets:
            metrics.RECOMPILES.labels(kind="prefill").inc()
            self._compiled_buckets.add(bucket)
        start = time.perf_counter()
        next_tokens, self.k_pages, self.v_pages = _prefill_step(
            self.params,
            self.spec,
            jnp.asarray(tokens),
            jnp.asarray([n_prompt], jnp.int32),
            self.k_pages,
            self.v_pages,
            jnp.asarray(prefill_pt),
            jnp.asarray([sp.temperature], jnp.float32),
            jnp.asarray([sp.top_p], jnp.float32),
            jnp.asarray([sp.top_k], jnp.int32),
            self._step_key(),
        )
        token = int(np.asarray(next_tokens)[0])
        metrics.ENGINE_STEP_TIME.labels(kind="prefill").observe(
            time.perf_counter() - start
        )
        self.total_prefills += 1
        seq.append_token(token)
        self._maybe_finish(seq, token)

    def _decode_signature(self, plan: DecodePlan):
        """Cheap membership signature: when unchanged, every device input
        except tokens/positions (which flow device→device) is reusable."""
        return tuple(
            (seq.seq_id, seq.slot, len(seq.pages)) for seq in plan.seqs
        )

    def _build_decode_state(self, plan: DecodePlan) -> None:
        B = self.max_slots
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        for seq in plan.seqs:
            slot = seq.slot
            assert slot is not None
            row = self._page_tables_np[slot]
            row[:] = 0
            row[: len(seq.pages)] = seq.pages
            tokens[slot] = seq.output_ids[-1]
            positions[slot] = seq.total_len - 1
            active[slot] = True
            temps[slot] = seq.params.temperature
            top_ps[slot] = seq.params.top_p
            top_ks[slot] = seq.params.top_k
        self._dec_state = {
            "tokens": jnp.asarray(tokens),
            "positions": jnp.asarray(positions),
            "page_tables": jnp.asarray(self._page_tables_np),
            "active": jnp.asarray(active),
            "temps": jnp.asarray(temps),
            "top_ps": jnp.asarray(top_ps),
            "top_ks": jnp.asarray(top_ks),
            "counter": jnp.asarray(self._step_counter, jnp.uint32),
        }

    def _run_decode(self, plan: DecodePlan) -> None:
        signature = self._decode_signature(plan)
        if signature != self._decode_signature_cache:
            self._build_decode_state(plan)
            self._decode_signature_cache = signature
        state = self._dec_state

        if not self._decode_compiled:
            metrics.RECOMPILES.labels(kind="decode").inc()
            self._decode_compiled = True
        start = time.perf_counter()
        (
            next_tokens,
            state["positions"],
            state["counter"],
            self.k_pages,
            self.v_pages,
        ) = _decode_step(
            self.params,
            self.spec,
            state["tokens"],
            state["positions"],
            self.k_pages,
            self.v_pages,
            state["page_tables"],
            state["active"],
            state["temps"],
            state["top_ps"],
            state["top_ks"],
            self._base_key,
            state["counter"],
            use_pallas=self.use_pallas,
        )
        state["tokens"] = next_tokens
        self._step_counter += 1
        sampled = np.asarray(next_tokens)
        metrics.ENGINE_STEP_TIME.labels(kind="decode").observe(
            time.perf_counter() - start
        )
        for seq in plan.seqs:
            token = int(sampled[seq.slot])
            seq.append_token(token)
            self.total_decode_tokens += 1
            self._maybe_finish(seq, token)

    def _maybe_finish(self, seq: Sequence, token: int) -> None:
        reason = None
        if token == self.tokenizer.eos_id:
            reason = "stop"
        elif seq.num_generated >= max(1, seq.params.max_tokens):
            reason = "length"
        elif seq.total_len >= self.config.model.max_model_len:
            reason = "length"
        if reason is not None:
            self.scheduler.remove(seq)
            seq.finish(reason)

    # ------------------------------------------------------------- utilities

    def warmup(self, buckets: Optional[List[int]] = None) -> float:
        """Pre-compile the decode program and the given (default: smallest)
        prefill buckets so first requests don't pay XLA compile latency."""
        start = time.perf_counter()
        was_running = self._running
        if not was_running:
            self.start()
        sp = SamplingParams(max_tokens=2, temperature=0.0)
        buckets = buckets or [self.scheduler.prefill_buckets[0]]
        for bucket in buckets:
            n = max(1, min(bucket - 1, 8))
            seq = self.submit_tokens([5] * n, sp)
            seq.done_event.wait(timeout=600)
        if not was_running:
            self.stop()
        return time.perf_counter() - start

    def device_health(self) -> Dict[str, Any]:
        try:
            device = self.mesh.devices.flat[0]
            value = float(jnp.asarray([1.0]).sum())
            return {
                "alive": value == 1.0,
                "platform": device.platform,
                "device_kind": getattr(device, "device_kind", "unknown"),
                "num_devices": int(self.mesh.devices.size),
            }
        except Exception as exc:  # pragma: no cover
            return {"alive": False, "error": str(exc)}

    def get_stats(self) -> Dict[str, Any]:
        return {
            "scheduler": self.scheduler.get_stats(),
            "steps": self.total_steps,
            "prefills": self.total_prefills,
            "decode_tokens": self.total_decode_tokens,
            "kv_pages_total": self.geometry.num_pages - 1,
            "kv_token_capacity": self.geometry.total_tokens,
            "model": self.spec.name,
            "mesh": {
                axis: int(size) for axis, size in self.mesh.shape.items()
            },
            "load_time_s": round(self.load_time_s, 2),
        }
