"""The TPU engine core: a device-owning continuous-batching loop.

Architecture (SURVEY.md section 7, stages 3-4):

* One **engine thread** owns the device.  Each tick it admits every
  waiting prompt it can (prefills dispatched back-to-back, first tokens
  read in one transfer), then runs decode in **chunks** of up to
  ``tpu.decode_chunk`` fused steps — no stop-the-world batch (the
  reference's design it replaces: vgate/batcher.py:195's global lock
  around blocking generate).
* **A small set of compiled programs** covers all steady-state work: one
  decode-chunk program per power-of-two chunk length at the static shape
  [max_batch_slots], and one prefill program per sequence bucket.
  Sampling runs inside both with per-slot parameters.
* **Latency-hiding pipeline**: up to ``tpu.decode_pipeline`` chunks stay
  in flight before the host blocks on the oldest readback, so host-side
  token processing (and, over a remote-device tunnel, per-call round-trip
  latency) overlaps device execution.  EOS/length stops are detected at
  readback; overshoot steps are discarded and their KV writes land in
  horizon pages the scheduler reserved (see Scheduler.prepare_decode).
* KV pages are donated through every call so XLA updates them in place;
  tokens/positions/rng-counter stay device-resident between chunks and are
  re-uploaded only when slot membership changes.
* The async serving world talks to the thread via a submit queue +
  ``threading.Event`` per sequence; token streaming via per-token callbacks.
"""

from __future__ import annotations

import functools
import os
import queue
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

import jax
import jax.numpy as jnp
import numpy as np

from vgate_tpu import faults, integrity, metrics
from vgate_tpu.analysis.witness import named_lock
from vgate_tpu.analysis.annotations import (
    engine_thread_only,
    engine_thread_root,
)
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.errors import (
    DeadlineExceededError,
    EngineRecoveringError,
    IntegrityError,
    MigrationError,
    PoisonRequestError,
    ResumeExhaustedError,
)
from vgate_tpu.config import VGTConfig, apply_platform, get_config
from vgate_tpu.logging_config import bound_request, get_logger
from vgate_tpu.models.decoder import (
    decode_forward,
    prefill_forward,
    prefill_suffix_forward,
    spec_verify_forward,
)
from vgate_tpu.models.specs import ModelSpec, spec_for_model_id
from vgate_tpu.ops.sampling import (
    apply_logit_bias,
    apply_penalties,
    sample_tokens,
    sample_tokens_with_logprobs,
    suppress_stop_tokens,
    verify_and_sample,
)
from vgate_tpu.observability.flight import FlightRecorder
from vgate_tpu.observability.perf import PerfRecorder
from vgate_tpu.observability.reqtrace import RequestMeta, RequestTrace
from vgate_tpu.observability.roofline import (
    EngineRoofline,
    kv_bytes_per_token,
    stream_weight_bytes,
)
from vgate_tpu.ops.kv_quant import (
    SCALE_BYTES,
    copy_page_prefix,
    dtype_short_name,
)
from vgate_tpu.parallel.mesh import build_mesh, initialize_distributed
from vgate_tpu.parallel.sharding import kv_pspec, named, shard_params
from vgate_tpu.runtime.kv_cache import (
    KVGeometry,
    PageAllocator,
    auto_num_pages,
    make_kv_buffers,
)
from vgate_tpu.runtime.kv_swap import KVSwapManager
from vgate_tpu.runtime.radix_cache import RadixCache
from vgate_tpu.runtime.scheduler import PrefillPlan, Scheduler, SwapInPlan
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.tokenizer import get_tokenizer
from vgate_tpu.runtime.weights import load_or_init_params
from vgate_tpu.utils.math import bucket_for, cdiv

logger = get_logger(__name__)

# Threading contract (enforced by scripts/vgt_lint.py, checker
# thread-discipline — see docs/static_analysis.md): cross-module call
# resolution for self.scheduler.*, and the fields only ever mutated
# under their paired lock.
VGT_COMPONENTS = {"scheduler": "Scheduler"}
# Epoch-guard contract (vgtlint epoch-guard checker): token-append
# readbacks publish sequence state a cross-thread containment fold may
# have invalidated while the device call blocked.  Every append must
# run under the readback lock AND be dominated by a staleness
# comparison on the sequence's preempt epoch — the PR-5/8/11 bug
# shape, previously re-verified by hand each PR.
VGT_EPOCH_GUARDS = {
    "append_token": {"lock": "_readback_lock", "epoch": "preempt_count"},
}
VGT_LOCK_GUARDS = {
    # the containment fold vs. token-append readbacks publication
    # guard (PR-5 hardening): a woken stalled thread must observe
    # either pre-fold or fully-folded state, never a fold in progress
    "_checkpointed": "_readback_lock",
    # first-entry-only containment arbitration
    "_fatal": "_contain_lock",
}

# top-alternatives returned per position when a request asks for
# logprobs (requests may ask for fewer; the schema clamps to this)
LOGPROBS_K = 8

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}


@functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "mesh", "use_pallas", "num_logprobs", "kv_carry"
    ),
    donate_argnames=("k_pages", "v_pages"),
)
def _prefill_step(
    params, spec: ModelSpec, tokens, seq_lens, k_pages, v_pages,
    page_tables, temps, top_ps, top_ks, key, mesh=None, use_pallas=False,
    seeds=None, steps=None, num_logprobs: int = 0,
    counts=None, freq_pens=None, pres_pens=None,
    min_toks=None, stop_id_mat=None, kv_carry: bool = False,
    bias_ids=None, bias_vals=None,
):
    logits, k_pages, v_pages = prefill_forward(
        params, spec, tokens, seq_lens, k_pages, v_pages, page_tables,
        mesh=mesh, use_pallas=use_pallas, kv_carry=kv_carry,
    )
    if counts is not None:
        # post-preemption re-prefill: folded outputs still count toward
        # the penalties of the re-sampled first token
        logits = apply_penalties(logits, counts, freq_pens, pres_pens)
    if bias_ids is not None:
        logits = apply_logit_bias(logits, bias_ids, bias_vals)
    if min_toks is not None:
        logits = suppress_stop_tokens(logits, steps, min_toks, stop_id_mat)
    if num_logprobs > 0:
        next_tokens, lp, tids, tlps = sample_tokens_with_logprobs(
            logits, temps, top_ps, top_ks, key, seeds=seeds, steps=steps,
            num_top=num_logprobs,
        )
        return (next_tokens, (lp, tids, tlps)), k_pages, v_pages
    # NOTE: no all_greedy fast path in prefill programs — one sample per
    # PROMPT makes the top-k cost negligible, and skipping the variant
    # split halves the (expensive) batched-prefill compile ladder
    next_tokens = sample_tokens(
        logits, temps, top_ps, top_ks, key, seeds=seeds, steps=steps
    )
    return (next_tokens, None), k_pages, v_pages


@functools.partial(
    jax.jit,
    static_argnames=("spec", "num_logprobs", "kv_carry", "use_pallas",
                     "mesh", "unaligned"),
    donate_argnames=("k_pages", "v_pages"),
)
def _suffix_prefill_step(
    params, spec: ModelSpec, tokens, prefix_lens, suffix_lens, k_pages,
    v_pages, suffix_page_tables, ctx_page_tables, temps, top_ps, top_ks,
    key, seeds=None, steps=None, num_logprobs: int = 0,
    counts=None, freq_pens=None, pres_pens=None,
    min_toks=None, stop_id_mat=None, kv_carry: bool = False,
    bias_ids=None, bias_vals=None, use_pallas: bool = False, mesh=None,
    unaligned: bool = False,
):
    """Prompt pass for the uncached suffix of a prefix-cache hit, with
    fused first-token sampling (models/decoder.py prefill_suffix_forward).
    ``unaligned`` is the copy-on-write variant: prefix_lens may fall
    mid-page and the KV write becomes a per-token scatter."""
    logits, k_pages, v_pages = prefill_suffix_forward(
        params, spec, tokens, prefix_lens, suffix_lens, k_pages, v_pages,
        suffix_page_tables, ctx_page_tables, kv_carry=kv_carry,
        use_pallas=use_pallas, mesh=mesh, unaligned=unaligned,
    )
    if counts is not None:
        logits = apply_penalties(logits, counts, freq_pens, pres_pens)
    if bias_ids is not None:
        logits = apply_logit_bias(logits, bias_ids, bias_vals)
    if min_toks is not None:
        logits = suppress_stop_tokens(logits, steps, min_toks, stop_id_mat)
    if num_logprobs > 0:
        next_tokens, lp, tids, tlps = sample_tokens_with_logprobs(
            logits, temps, top_ps, top_ks, key, seeds=seeds, steps=steps,
            num_top=num_logprobs,
        )
        return (next_tokens, (lp, tids, tlps)), k_pages, v_pages
    next_tokens = sample_tokens(
        logits, temps, top_ps, top_ks, key, seeds=seeds, steps=steps
    )
    return (next_tokens, None), k_pages, v_pages


@functools.partial(jax.jit, donate_argnames=("k_pages", "v_pages"))
def _cow_copy_pages(k_pages, v_pages, src, dst, upto):
    """Copy-on-write page copy (runtime/radix_cache.py): duplicate the
    first ``upto`` token slots of page ``src`` into page ``dst`` across
    every layer and head, so a sequence diverging mid-page gets the
    shared head's KV without recomputing it.  Scalars are traced — one
    compile serves every (src, dst, upto) combination.  int8 pools copy
    the per-slot SCALES with the data (ops/kv_quant.copy_page_prefix):
    a COW'd head dequantizes bit-identically to the page it came from,
    so shared and diverged readers never disagree."""
    ps = k_pages.shape[-2]
    keep = jnp.arange(ps) < upto  # [ps]
    return (
        copy_page_prefix(k_pages, src, dst, keep),
        copy_page_prefix(v_pages, src, dst, keep),
    )


# pages moved per device call when swapping KV to/from host RAM
# (runtime/kv_swap.py): fixed so each direction compiles exactly one
# program per pool dtype — short runs pad their index vector with the
# reserved trash page 0, which absorbs the padding writes on swap-in
# and whose padding rows are dropped host-side on swap-out
SWAP_CHUNK_PAGES = 16


@jax.jit
def _gather_swap_pages(k_pages, v_pages, idx):
    """Device->host half of a KV swap: pull ``idx``'s page slices out
    of the pools (page axis 2 on data AND int8 scale leaves) in one
    program; the caller device_gets the result.  NOT donated — the
    pools stay resident."""
    return jax.tree.map(
        lambda x: jnp.take(x, idx, axis=2), (k_pages, v_pages)
    )


@functools.partial(jax.jit, donate_argnames=("k_pages", "v_pages"))
def _scatter_swap_pages(k_pages, v_pages, idx, k_data, v_data):
    """Host->device half: scatter saved page content back into freshly
    allocated pages.  Duplicate padding indices all target trash page
    0, whose content is never read."""
    put = lambda x, d: x.at[:, :, idx].set(d)
    return (
        jax.tree.map(put, k_pages, k_data),
        jax.tree.map(put, v_pages, v_data),
    )


class _DeviceSwapExecutor:
    """The device half of the host swap tier (runtime/kv_swap.py): the
    manager stays pure host-side policy, and every device touch —
    chunked ``jax.device_get`` of page slices on swap-out, the jitted
    scatter on swap-in — happens here, on the engine thread, at tick
    boundaries.  Reads heartbeat like every other blocking readback so
    the hang watchdog attributes a wedged transfer correctly."""

    def __init__(self, core: "EngineCore") -> None:
        self._core = core

    def read_pages(self, pages: List[int]):
        core = self._core
        k_chunks: list = []
        v_chunks: list = []
        for i in range(0, len(pages), SWAP_CHUNK_PAGES):
            chunk = pages[i : i + SWAP_CHUNK_PAGES]
            idx = np.zeros((SWAP_CHUNK_PAGES,), np.int32)
            idx[: len(chunk)] = chunk
            core._beat("swap_readback", batch=len(chunk))
            k_c, v_c = _gather_swap_pages(
                core.k_pages, core.v_pages, jnp.asarray(idx)
            )
            host = jax.device_get((k_c, v_c))
            trim = lambda x: np.asarray(x)[:, :, : len(chunk)]
            k_chunks.append(jax.tree.map(trim, host[0]))
            v_chunks.append(jax.tree.map(trim, host[1]))
        cat = lambda *xs: np.concatenate(xs, axis=2)
        return (
            jax.tree.map(cat, *k_chunks),
            jax.tree.map(cat, *v_chunks),
        )

    def write_pages(self, pages: List[int], payload) -> None:
        core = self._core
        k_data, v_data = payload
        for i in range(0, len(pages), SWAP_CHUNK_PAGES):
            chunk = pages[i : i + SWAP_CHUNK_PAGES]
            idx = np.zeros((SWAP_CHUNK_PAGES,), np.int32)
            idx[: len(chunk)] = chunk

            def pad(x):
                sl = x[:, :, i : i + len(chunk)]
                if len(chunk) < SWAP_CHUNK_PAGES:
                    shape = list(sl.shape)
                    shape[2] = SWAP_CHUNK_PAGES
                    out = np.zeros(shape, sl.dtype)
                    out[:, :, : len(chunk)] = sl
                    return out
                return sl

            core.k_pages, core.v_pages = _scatter_swap_pages(
                core.k_pages,
                core.v_pages,
                jnp.asarray(idx),
                jax.tree.map(pad, k_data),
                jax.tree.map(pad, v_data),
            )


def _decode_step(
    params, spec: ModelSpec, tokens, positions, k_pages, v_pages,
    page_tables, active, temps, top_ps, top_ks, base_key, counter,
    use_pallas=False, mesh=None,
):
    """One decode step — thin wrapper over ``_decode_chunk(num_steps=1)``
    kept for single-step callers (e.g. __graft_entry__.dryrun_multichip)."""
    (
        chunk_tokens, _lp, _tokens, positions, counter, _steps, _counts,
        k_pages, v_pages, _flags,
    ) = _decode_chunk(
        params, spec, tokens, positions, k_pages, v_pages, page_tables,
        active, temps, top_ps, top_ks, base_key, counter,
        num_steps=1, use_pallas=use_pallas, mesh=mesh,
    )
    return chunk_tokens[0], positions, counter, k_pages, v_pages


@functools.partial(
    jax.jit,
    static_argnames=("spec", "num_steps", "use_pallas", "max_position",
                     "mesh", "num_logprobs", "all_greedy", "kv_carry",
                     "guard", "guard_threshold"),
    donate_argnames=("k_pages", "v_pages", "counts"),
)
def _decode_chunk(
    params, spec: ModelSpec, tokens, positions, k_pages, v_pages,
    page_tables, active, temps, top_ps, top_ks, base_key, counter,
    num_steps: int = 1, use_pallas=False, max_position: int = 0,
    seeds=None, steps=None, mesh=None, num_logprobs: int = 0,
    counts=None, freq_pens=None, pres_pens=None,
    min_toks=None, stop_id_mat=None, all_greedy: bool = False,
    kv_carry: bool = False, bias_ids=None, bias_vals=None,
    guard: bool = False, guard_threshold: float = 1.0e4,
):
    """``num_steps`` decode steps fused into one device program.

    The host reads sampled tokens once per *chunk* instead of once per
    step — essential when the host<->device link has high per-call latency
    (remote TPU tunnels) and still a win locally (fewer dispatches).  EOS /
    max_tokens are detected on the host after readback; steps a sequence ran
    past its stopping point are discarded there, and their KV writes land in
    pages the scheduler reserved for the horizon (harmless: the sequence is
    removed and its pages freed).  Returns ``chunk_tokens`` of shape
    ``[num_steps, B]`` plus the threaded device state.

    ``guard`` (integrity.logit_guard) additionally computes a per-step
    per-slot sentinel flag word over the RAW model logits — before
    penalties/bias/min-token suppression, whose deliberate -inf writes
    must not trip the NaN/Inf check — returned as ``[num_steps, B]``
    uint8 (integrity.logit_guard flag bits).  Static, so the guard-off
    program is byte-identical to the pre-integrity one.
    """

    if steps is None:
        steps = jnp.zeros_like(positions)

    def body(carry, _):
        tokens, positions, counter, steps, counts, k_pages, v_pages = carry
        key = jax.random.fold_in(base_key, counter)
        logits, k_pages, v_pages = decode_forward(
            params, spec, tokens, positions, k_pages, v_pages, page_tables,
            active=active, use_pallas=use_pallas, mesh=mesh,
            kv_carry=kv_carry,
        )
        if guard:
            step_flags = integrity.logit_guard(logits, guard_threshold)
        if counts is not None:
            # frequency/presence penalties over the generated-token
            # histogram (ops/sampling.py apply_penalties)
            logits = apply_penalties(logits, counts, freq_pens, pres_pens)
        if bias_ids is not None:
            logits = apply_logit_bias(logits, bias_ids, bias_vals)
        if min_toks is not None:
            logits = suppress_stop_tokens(
                logits, steps, min_toks, stop_id_mat
            )
        if num_logprobs > 0:
            next_tokens, lp, tids, tlps = sample_tokens_with_logprobs(
                logits, temps, top_ps, top_ks, key, seeds=seeds,
                steps=steps, num_top=num_logprobs,
            )
            ys = (next_tokens, lp, tids, tlps)
        else:
            next_tokens = sample_tokens(
                logits, temps, top_ps, top_ks, key, seeds=seeds,
                steps=steps, all_greedy=all_greedy,
            )
            ys = (next_tokens,)
        if guard:
            ys = ys + (step_flags,)
        positions = positions + active.astype(positions.dtype)
        steps = steps + active.astype(steps.dtype)
        if counts is not None:
            counts = counts.at[
                jnp.arange(counts.shape[0]), next_tokens
            ].add(active.astype(counts.dtype))
        if max_position > 0:
            # overshoot steps (chunk sized by MAX headroom across slots) must
            # stay in-bounds: on the Pallas path seq_len = position+1 drives
            # the page loop, and past max_pages the DMA reads are undefined
            # rather than clamped like XLA gathers
            positions = jnp.minimum(positions, max_position)
        return (
            next_tokens, positions, counter + 1, steps, counts,
            k_pages, v_pages,
        ), ys

    carry, ys = jax.lax.scan(
        body,
        (tokens, positions, counter, steps, counts, k_pages, v_pages),
        None,
        length=num_steps,
    )
    tokens, positions, counter, steps, counts, k_pages, v_pages = carry
    # [num_steps, B] uint8 sentinel words when guarded (host ORs the
    # step axis at readback), None otherwise
    chunk_flags = ys[-1] if guard else None
    if guard:
        ys = ys[:-1]
    chunk_tokens = ys[0]
    # ([steps, B], [steps, B, K], [steps, B, K]) when logprobs, else None
    chunk_lp = ys[1:] if num_logprobs > 0 else None
    return (
        chunk_tokens, chunk_lp, tokens, positions, counter, steps, counts,
        k_pages, v_pages, chunk_flags,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "use_pallas", "num_logprobs", "all_greedy", "kv_carry",
        "mesh",
    ),
    donate_argnames=("k_pages", "v_pages"),
)
def _spec_verify_step(
    params, spec: ModelSpec, tokens, positions0, input_lens, k_pages,
    v_pages, page_tables, active, temps, top_ps, top_ks, base_key, counter,
    seeds=None, steps=None, use_pallas=False, num_logprobs: int = 0,
    counts=None, freq_pens=None, pres_pens=None,
    min_toks=None, stop_id_mat=None, all_greedy: bool = False,
    kv_carry: bool = False, bias_ids=None, bias_vals=None, mesh=None,
):
    """One speculative round: score current token + drafts in a single
    forward (models/decoder.py spec_verify_forward), then verify every
    draft position with the per-slot sampling params — greedy slots by
    exact argmax match, temperature>0 slots by distribution-preserving
    rejection sampling (ops/sampling.py verify_and_sample: accept draft
    t with prob p(t), resample from p minus t on rejection) — and count
    accepted drafts on device.  Returns (model_toks [B, S], accepted
    [B], caches)."""
    from vgate_tpu.runtime.speculative import count_accepted

    logits, k_pages, v_pages = spec_verify_forward(
        params, spec, tokens, positions0, input_lens, k_pages, v_pages,
        page_tables, active=active, use_pallas=use_pallas,
        kv_carry=kv_carry, mesh=mesh,
    )  # [B, S, V]
    B, S = tokens.shape
    if counts is not None:
        # position j's penalties include the drafts accepted before it
        # (run 1..j); if draft j+1 is later rejected, position j+1's
        # output is discarded anyway, so exactness holds for every token
        # actually appended
        run = counts
        pen = []
        for j in range(S):
            pen.append(
                apply_penalties(logits[:, j], run, freq_pens, pres_pens)
            )
            if j + 1 < S:
                inc = ((j + 1) < input_lens) & active
                run = run.at[jnp.arange(B), tokens[:, j + 1]].add(
                    inc.astype(run.dtype)
                )
        logits = jnp.stack(pen, axis=1)
    key = jax.random.fold_in(base_key, counter)
    # one batched sampler over all (slot, position) rows — per-position
    # step indices keep seeded reproducibility aligned with the token
    # index, exactly like the decode chunk's per-step `steps` increment
    rep = functools.partial(jnp.repeat, repeats=S, axis=0)
    steps_flat = (
        None
        if steps is None
        else (steps[:, None] + jnp.arange(S)[None, :]).reshape(-1)
    )
    if bias_ids is not None:
        # per-slot biases apply at every candidate position
        flat = apply_logit_bias(
            logits.reshape(B * S, -1), rep(bias_ids), rep(bias_vals)
        )
        logits = flat.reshape(logits.shape)
    if min_toks is not None:
        assert steps_flat is not None, "min_tokens requires steps"
        flat = suppress_stop_tokens(
            logits.reshape(B * S, -1),
            steps_flat,
            rep(min_toks),
            rep(stop_id_mat),
        )
        logits = flat.reshape(logits.shape)
    # row (b, j) verifies draft tokens[b, j+1]; the row at input_len-1
    # (and any garbage row past it) draws the plain bonus sample instead
    draft_next = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)], axis=1
    )
    is_bonus = jnp.arange(S)[None, :] >= (input_lens[:, None] - 1)
    flat_toks, _accept, lp_flat = verify_and_sample(
        logits.reshape(B * S, -1),
        draft_next.reshape(-1),
        is_bonus.reshape(-1),
        rep(temps), rep(top_ps), rep(top_ks), key,
        seeds=None if seeds is None else rep(seeds),
        steps=steps_flat,
        num_top=num_logprobs,
        all_greedy=all_greedy,
    )
    model_toks = flat_toks.reshape(B, S)
    if num_logprobs > 0:
        lp, tids, tlps = lp_flat
        lp_data = (
            lp.reshape(B, S),
            tids.reshape(B, S, -1),
            tlps.reshape(B, S, -1),
        )
    else:
        lp_data = None
    accepted = count_accepted(model_toks, tokens, input_lens)
    if counts is not None:
        # fold the tokens this round actually appends (accepted run +
        # bonus) into the histogram on device
        app = (
            (jnp.arange(S)[None, :] <= accepted[:, None])
            & active[:, None]
        )
        b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, S))
        counts = counts.at[b_idx, model_toks].add(app.astype(counts.dtype))
    return model_toks, accepted, lp_data, counts, k_pages, v_pages


def rebuild_core(
    old: "EngineCore",
    config: VGTConfig,
    devices: Optional[list],
    reload_weights: bool = False,
) -> "EngineCore":
    """Tear a dead core down and construct its successor — the ONE
    rebuild sequence both the dp=1 supervisor and the dp repair thread
    use (a per-core device buffer freed in one copy but not the other
    would keep the dead incarnation's pool alive and OOM every rebuild
    on real hardware).  Stops the old core, releases its device KV pool
    and decode state BEFORE the new pool is sized (auto-sized pools
    fill most of HBM; the old core stays referenced by its owner until
    the swap, pinning anything still shared), rebuilds with weights
    KEPT (the old tree is already quantized/sharded on these devices),
    and carries the brownout spec-suspension flag so a crash at level
    >= 3 cannot silently re-enable speculative decoding.  The caller
    swaps it in, re-attaches on_fatal, and start()s it.

    Silent-corruption defense (vgate_tpu/integrity.py): a kept tree is
    ALWAYS re-verified against its checksum baseline first — restarting
    on a bit-flipped tree would preserve the corruption through every
    incarnation — and a mismatch raises :class:`IntegrityError` so the
    caller escalates to ``reload_weights=True``, which drops the old
    tree and reloads from the checkpoint (the ``corrupt``-classified
    fatal path)."""
    old.stop()
    old.k_pages = None
    old.v_pages = None
    old._dec_state = None
    old._pending_chunks.clear()
    old._spec_pen = None
    # the host swap pool dies with its core: every parked ticket's
    # epoch went stale when containment folded the owners, and the new
    # core builds a fresh (empty) pool — free the host RAM now rather
    # than holding both pools across the rebuild
    old.kv_swap = None
    old_integrity = getattr(old, "integrity", None)
    if (
        not reload_weights
        and old_integrity is not None
        and old_integrity.verifier is not None
        and old.params is not None
    ):
        mismatch = old_integrity.verifier.verify_all(old.params)
        if mismatch is not None:
            metrics.INTEGRITY_EVENTS.labels(
                kind="rebuild_verify_failed"
            ).inc()
            raise IntegrityError(
                "kept-weights rebuild verification failed: shard "
                f"{mismatch['leaf']!r} no longer matches its load-time "
                "checksum; escalate to a weight reload",
                kind="checksum_mismatch",
                detail=mismatch,
            )
    if reload_weights:
        # free the suspect tree BEFORE the reload materializes a fresh
        # one — two full trees would OOM the chip
        old.params = None
        metrics.CORRUPT_RELOADS.inc()
        metrics.INTEGRITY_EVENTS.labels(kind="corrupt_reload").inc()
        logger.warning(
            "rebuilding engine with a FULL WEIGHT RELOAD "
            "(corrupt-classified fatal; weights-kept would preserve "
            "the corruption)"
        )
        new_core = EngineCore(config, spec=old.spec, devices=devices)
    else:
        new_core = EngineCore(
            config,
            spec=old.spec,
            params=old.params,
            devices=devices,
            params_ready=True,
        )
    new_core.spec_suspended = bool(
        getattr(old, "spec_suspended", False)
    )
    # same carry for brownout L4: a crash while cache writes were
    # bypassed must not silently resume prefix-tree inserts (the method
    # also propagates the flag onto the fresh core's radix cache)
    new_core.set_prefix_insert_suspended(
        getattr(old, "prefix_insert_suspended", False)
    )
    return new_core


def replay_into(
    core: "EngineCore",
    seq: Sequence,
    quarantine: set,
    retry_after: float = 1.0,
    kind: str = "resume",
    **tick_fields: Any,
) -> str:
    """Replay ONE checkpointed sequence into ``core`` — the shared
    per-sequence pipeline behind the supervisor's restart replay, the
    dp router's failover redistribution AND planned live migration
    (one definition so lost/resumed accounting can never drift between
    dp=1 and dp>1): quarantined fingerprints fail with the 400 poison
    error, a refused resubmission fails with the retryable 503,
    success records the ``kind`` flight tick ("resume" for crash
    replay, "migrate" for planned movement) and bumps
    vgt_resumed_sequences (resume only — migrations have their own
    vgt_migrations counter, labeled by reason, owned by the caller).
    Returns "replayed" | "quarantined" | "failed"; callers fold the
    outcome into their own counters."""
    fp = faults.fingerprint(seq.prompt_ids[: seq.orig_prompt_len])
    if fp in quarantine:
        metrics.LOST_SEQUENCES.labels(reason="quarantined").inc()
        seq.fail(
            PoisonRequestError(
                f"request {fp} was quarantined while its generation "
                "was checkpointed and will not be replayed"
            )
        )
        return "quarantined"
    try:
        core.submit_existing(seq)
    except Exception:
        logger.error("resume resubmission failed", exc_info=True)
        metrics.LOST_SEQUENCES.labels(reason="resubmit_failed").inc()
        seq.fail(
            EngineRecoveringError(
                "engine restarted but the checkpointed request could "
                "not be replayed; retry shortly",
                retry_after=retry_after,
            )
        )
        return "failed"
    if kind == "resume":
        metrics.RESUMED_SEQUENCES.inc()
    core.flight.record_tick(
        kind,
        seq_id=seq.seq_id,
        request_id=seq.request_id,
        tokens=seq.num_generated,
        attempt=seq.resume_count if kind == "resume" else seq.migrate_count,
        **tick_fields,
    )
    return "replayed"


class _EvacRequest:
    """One planned-evacuation command in flight between a caller thread
    (dp drain/rebalance coordinator, admin surface) and the engine
    thread: the engine fills ``result`` (the checkpointed live
    sequences) or ``error`` and sets ``event``.  ``lock`` arbitrates
    the timeout race — a caller that gives up sets ``cancelled`` under
    it, and the engine checks it both before starting and before
    publishing, so a stale command can never strand ownerless
    sequences: not-yet-started work is skipped, just-finished work is
    folded straight back into the source scheduler."""

    __slots__ = (
        "seq_ids", "reason", "event", "result", "error",
        "lock", "cancelled",
    )

    def __init__(
        self, seq_ids: Optional[List[int]], reason: str
    ) -> None:
        self.seq_ids = seq_ids
        self.reason = reason
        self.event = threading.Event()
        self.result: Optional[List[Sequence]] = None
        self.error: Optional[BaseException] = None
        self.lock = threading.Lock()
        self.cancelled = False


class EngineCore:
    """Owns params, KV pages, the mesh and the engine thread."""

    def __init__(
        self,
        config: Optional[VGTConfig] = None,
        spec: Optional[ModelSpec] = None,
        params: Optional[Any] = None,
        devices: Optional[list] = None,
        params_ready: bool = False,
    ) -> None:
        self.config = config or get_config()
        self.spec = spec or spec_for_model_id(self.config.model.model_id)
        tpu_cfg = self.config.tpu
        apply_platform(tpu_cfg)
        # multi-host pods: join the process group before any device touch
        # (no-op on single hosts / CPU test meshes; VERDICT r1 missing-5)
        initialize_distributed()
        self.dtype = _DTYPES[self.config.model.dtype]
        self.mesh = build_mesh(tpu_cfg, devices)
        # model-level stop set: the tokenizer's eos plus the spec's extra
        # generation_config stops (e.g. Llama-3.1's end_of_text/eom)
        self._stop_ids = frozenset(self.spec.extra_stop_ids)
        self.tokenizer = get_tokenizer(
            self.spec,
            self.config.model.tokenizer_path
            or self.config.model.checkpoint_path,
        )

        load_start = time.perf_counter()
        quant = self.config.model.quantization
        quant_bits = int(quant[3:]) if quant in ("int8", "int4") else None
        # Single-device quantized loads stage on the HOST: a 7B-class
        # model's bf16 tree (~15 GB) would OOM a 16 GB chip before
        # quantization could ever run, so init/load and quantize on the
        # CPU backend and place only the narrow-int tree (the same shape
        # a real AWQ-style pre-quantized load has).  Multi-device meshes
        # keep the place-then-quantize order so the eager quantize ops
        # run SPMD and scales inherit the tp layout.
        host_stage = None
        if params_ready:
            # supervised restart (runtime/supervisor.py): `params` is the
            # previous incarnation's tree, already quantized/sharded on
            # these same devices — re-quantizing or re-sharding it would
            # corrupt it, so place it verbatim and skip the load path
            assert params is not None, "params_ready requires params"
        elif quant_bits and self.mesh.devices.size == 1:
            try:
                host_stage = jax.devices("cpu")[0]
            except RuntimeError:  # pragma: no cover - cpu backend absent
                host_stage = None
                logger.warning(
                    "no cpu backend for host-staged quantized load; "
                    "falling back to on-device quantization (a 7B-class "
                    "bf16 tree may OOM the chip) — pin tpu.platform so "
                    "apply_platform keeps cpu registered"
                )
        if params_ready:
            self.params = params
        elif host_stage is not None:
            from vgate_tpu.ops.quant import quantize_decoder_params

            with jax.default_device(host_stage):
                if params is None:
                    params = load_or_init_params(
                        self.spec,
                        self.config.model.checkpoint_path,
                        self.dtype,
                        log_digests=self.config.integrity.enabled,
                    )
                params = quantize_decoder_params(
                    params, self.spec, bits=quant_bits
                )
            self.params = jax.device_put(
                params, self.mesh.devices.flat[0]
            )
        else:
            if params is None:
                params = load_or_init_params(
                    self.spec, self.config.model.checkpoint_path, self.dtype,
                    log_digests=self.config.integrity.enabled,
                )
            self.params = shard_params(params, self.spec, self.mesh)
            if quant_bits:
                from vgate_tpu.ops.quant import quantize_decoder_params

                self.params = quantize_decoder_params(
                    self.params, self.spec, bits=quant_bits
                )
        jax.block_until_ready(jax.tree.leaves(self.params)[0])
        self.load_time_s = time.perf_counter() - load_start
        # silent-corruption defense (vgate_tpu/integrity.py): sentinel
        # scanner + weight-checksum baseline over the FINAL serving tree
        # (post-quantize/shard — the tree supervised rebuilds keep).
        # None when disabled, keeping every probe site a single
        # attribute check and the decode program byte-identical.
        icfg = self.config.integrity
        self.integrity: Optional[integrity.EngineIntegrity] = None
        if icfg.enabled:
            self.integrity = integrity.EngineIntegrity(
                icfg, self.spec.vocab_size
            )
            self.integrity.record_baseline(self.params)

        params_bytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(self.params)
        )
        # KV storage format (kv_cache.dtype — ops/kv_quant.py): int8
        # halves page data bytes (plus a bf16 scale per page/head/slot),
        # so the same HBM budget below yields ~2x the bf16 page count —
        # resident-batch capacity is what governs tail latency under
        # load (PAPERS.md vLLM/TGI study), and decode HBM traffic per
        # token halves with it.  Quantization happens at every KV write
        # site (models/decoder.py kv_write) and dequantization inside
        # the attention reads (Pallas VMEM loop / jnp gather twins).
        kv_mode = self.config.kv_cache.dtype
        self._kv_quant = kv_mode == "int8"
        if self._kv_quant:
            model_axes = {
                a: int(self.mesh.shape.get(a, 1))
                for a in ("tp", "pp", "sp", "ep")
            }
            bad = {a: n for a, n in model_axes.items() if n > 1}
            if bad:
                raise ValueError(
                    f"kv_cache.dtype=int8 requires a plain mesh, got "
                    f"{bad}: the quantized pool is a (data, scale) pair "
                    "the sp/pp relays and tp shard_map kernels do not "
                    "thread — dp composes (each replica owns its pool)"
                )
            kv_pool_dtype = jnp.int8
        elif kv_mode == "bf16":
            kv_pool_dtype = jnp.bfloat16
        else:  # auto: pages store the model compute dtype
            kv_pool_dtype = self.dtype
        kv_dtype_name = (
            "int8" if self._kv_quant else dtype_short_name(kv_pool_dtype)
        )
        kv_dtype_bytes = 1 if self._kv_quant else jnp.dtype(
            kv_pool_dtype
        ).itemsize
        kv_scale_bytes = SCALE_BYTES if self._kv_quant else 0
        # more pages than every slot's full context can never be used, and
        # bounding the pool keeps the page-scatter/gather programs small
        pages_per_seq = cdiv(
            self.config.model.max_model_len, tpu_cfg.kv_page_size
        )
        sp_shards = int(self.mesh.shape.get("sp", 1))
        max_useful = (
            tpu_cfg.max_batch_slots * pages_per_seq + sp_shards
        )
        num_pages = tpu_cfg.kv_num_pages or min(
            max_useful,
            auto_num_pages(
                self.spec,
                tpu_cfg.kv_page_size,
                tpu_cfg.hbm_utilization,
                device=self.mesh.devices.flat[0],
                params_bytes=params_bytes,
                dtype_bytes=kv_dtype_bytes,
                hbm_bytes=tpu_cfg.hbm_bytes,
                scale_bytes=kv_scale_bytes,
            ),
        )
        if sp_shards > 1:
            # the pool shards contiguously over sp (parallel/sp_decode.py);
            # round UP so the computed capacity is preserved (at most
            # sp-1 extra pages, noise next to the pool)
            num_pages = num_pages + (-num_pages) % sp_shards
        self.geometry = KVGeometry(
            num_layers=self.spec.num_layers,
            num_pages=num_pages,
            page_size=tpu_cfg.kv_page_size,
            kv_heads=self.spec.num_kv_heads,
            head_dim=self.spec.head_dim,
            max_model_len=self.config.model.max_model_len,
            dtype_bytes=kv_dtype_bytes,
            num_reserved=sp_shards,
            scale_bytes=kv_scale_bytes,
            kv_dtype=kv_dtype_name,
        )
        kv_sharding = named(
            self.mesh, kv_pspec(self.spec, self.mesh, num_pages)
        )
        self.k_pages, self.v_pages = make_kv_buffers(
            self.geometry, kv_pool_dtype, kv_sharding
        )
        self.allocator = PageAllocator(num_pages, num_shards=sp_shards)
        self.allocator.quantized = self._kv_quant
        for name in ("bf16", "f32", "f16", "int8"):
            metrics.KV_DTYPE.labels(dtype=name).set(
                1 if name == kv_dtype_name else 0
            )
        self.max_slots = tpu_cfg.max_batch_slots
        # prefix caching rides the suffix prefill program, which runs on
        # plain meshes AND sp-sharded pools (parallel/sp_decode.py
        # sp_suffix_attention_and_write — long-context serving is
        # exactly where shared-prefix reuse pays); only the pp relay
        # still reshapes the prompt pass incompatibly
        mesh_sp = int(self.mesh.shape.get("sp", 1))
        mesh_pp = int(self.mesh.shape.get("pp", 1))
        pc = tpu_cfg.prefix_cache
        self.prefix_cache_enabled = bool(pc.enabled and mesh_pp == 1)
        # radix-tree prefix index (runtime/radix_cache.py): page-granular
        # cross-request sharing with COW partial pages and
        # pressure-integrated eviction; the tree registers itself as the
        # allocator's reclaimer so cached pages stay allocatable.  COW
        # needs the unsharded pool (the copy program indexes pages
        # globally), so it gates off under sp > 1 while full-page radix
        # sharing stays on.
        self.radix_cache = None
        if self.prefix_cache_enabled and pc.radix:
            self.radix_cache = RadixCache(
                self.allocator,
                tpu_cfg.kv_page_size,
                min_share_pages=pc.min_share_pages,
                cow=bool(pc.cow and mesh_sp == 1),
                cow_min_tokens=pc.cow_min_tokens,
            )
            self.allocator.set_reclaimer(self.radix_cache)
        # host-RAM KV swap tier (runtime/kv_swap.py): a budgeted pinned
        # host pool under the paged allocator — preemption parks the
        # victim's pages device->host instead of recomputing, and
        # radix eviction demotes warm prefixes into it (victim cache).
        # 0 = off keeps the engine byte-identical; the device half
        # (chunked gather/scatter) lives in _DeviceSwapExecutor and the
        # readback lock shared below epoch-guards swap-out publication
        # exactly like every other readback.
        self.kv_swap: Optional[KVSwapManager] = None
        host_swap_bytes = int(self.config.kv_cache.host_swap_bytes)
        if host_swap_bytes > 0:
            swap_axes = {
                a: int(self.mesh.shape.get(a, 1))
                for a in ("tp", "pp", "sp", "ep")
            }
            bad_axes = {a: n for a, n in swap_axes.items() if n > 1}
            if bad_axes:
                raise ValueError(
                    f"kv_cache.host_swap_bytes requires a plain mesh, "
                    f"got {bad_axes}: the swap gather/scatter indexes "
                    "pages globally across an unsharded pool — dp "
                    "composes (each replica owns its pool + host tier)"
                )
        # brownout L4 upstream state, carried across supervisor rebuilds
        # exactly like spec_suspended
        self.prefix_insert_suspended = False
        if tpu_cfg.prefill_chunk > 0 and mesh_pp > 1:
            raise ValueError(
                "prefill_chunk (chunked prefill) requires pp == 1 — the "
                "relay prompt pass reshapes the program incompatibly "
                "(sp is fine: chunks ride the sp-capable suffix program)"
            )
        # flight recorder (vgate_tpu/observability/flight.py): per-tick
        # + per-request post-mortem rings; the supervisor snapshots it
        # on every crash and /debug serves it live
        self.flight = FlightRecorder(self.config.observability)
        # perf attribution (vgate_tpu/observability/perf.py): per-tick
        # phase decomposition, compile ledger, live MFU/roofline gauges
        # from the engine's own geometry — served via /debug/perf and
        # the /stats perf block.  Rebuilt fresh on supervised restart
        # like the flight recorder (a rebuilt core recompiles, and the
        # ledger must say so).
        self.perf = PerfRecorder(
            self.config.observability,
            roofline=EngineRoofline(
                device_kind=getattr(
                    self.mesh.devices.flat[0], "device_kind", "unknown"
                ),
                num_chips=int(self.mesh.devices.size),
                num_params=int(self.spec.num_params),
                weight_stream_bytes=stream_weight_bytes(
                    self.params, self.spec.tie_embeddings
                ),
                kv_token_bytes=kv_bytes_per_token(
                    self.spec.num_layers,
                    self.spec.num_kv_heads,
                    self.spec.head_dim,
                    dtype_bytes=kv_dtype_bytes,
                    scale_bytes=kv_scale_bytes,
                ),
            ),
        )
        # see the long rationale further down where the readback paths
        # use it; constructed here so the swap manager can share it
        self._readback_lock = named_lock("EngineCore._readback_lock")
        if host_swap_bytes > 0:
            self.kv_swap = KVSwapManager(
                budget_bytes=host_swap_bytes,
                page_bytes=self.geometry.page_bytes,
                executor=_DeviceSwapExecutor(self),
                lock=self._readback_lock,
            )
            if self.radix_cache is not None:
                self.radix_cache.attach_swap(self.kv_swap)
        self.scheduler = Scheduler(
            allocator=self.allocator,
            max_slots=self.max_slots,
            page_size=tpu_cfg.kv_page_size,
            prefill_buckets=tpu_cfg.prefill_buckets,
            max_model_len=self.config.model.max_model_len,
            max_queue_size=self.config.scheduler.max_queue_size,
            preempt_on_oom=self.config.scheduler.preempt_on_oom,
            admission_deadline_ms=(
                self.config.scheduler.admission_deadline_ms
            ),
            prefix_cache=self.prefix_cache_enabled,
            prefill_chunk=tpu_cfg.prefill_chunk,
            text_fn=self.final_text,
            recorder=self.flight,
            radix=self.radix_cache,
            cache_aware_sched=pc.cache_aware_sched,
            insert_generated=pc.insert_generated,
            evict_watermark=pc.evict_watermark,
            swap=self.kv_swap,
        )

        # host-side mirror of the device page tables, one row per slot
        self._page_tables_np = np.zeros(
            (self.max_slots, self.geometry.pages_per_seq), np.int32
        )
        self._base_key = jax.random.PRNGKey(self.config.model.max_model_len)
        self._step_counter = 0
        self._compiled_buckets: set = set()
        self._compiled_chunks: set = set()
        self._dec_state: Optional[Dict[str, Any]] = None
        self._decode_signature_cache: Optional[tuple] = None
        # in-flight decode chunks awaiting host readback:
        # (seq snapshot, chunk length, [chunk, B] device tokens, start time)
        self._pending_chunks: list = []
        self.decode_chunk = max(1, tpu_cfg.decode_chunk)
        self.pipeline_depth = max(1, tpu_cfg.decode_pipeline)
        # Speculative decoding (runtime/speculative.py): per-sequence
        # prompt-lookup drafts verified in one multi-token step.  The
        # drafter is pluggable (tests inject oracles).
        self.spec_k = max(0, tpu_cfg.speculative_k)
        self.spec_ngram = max(1, tpu_cfg.speculative_ngram)
        # brownout level >= 3 (vgate_tpu/admission.py) suspends
        # speculative decoding at runtime: drafting burns verify-step
        # compute that plain decode gives back under saturation.  One
        # boolean read per tick; flipped cross-thread via
        # set_spec_suspended (bool stores are atomic under the GIL).
        self.spec_suspended = False
        self.drafter: Callable[[Sequence, int], List[int]] = (
            self._ngram_drafter
        )
        # model.draft_model_id upgrades drafting from prompt-lookup to a
        # small draft MODEL (runtime/speculative.py DraftModelDrafter).
        # Plain meshes only: the drafter is a second single-device
        # program; model-parallel engines keep n-gram drafting.
        self.draft_model = None
        draft_id = self.config.model.draft_model_id
        if draft_id and self.spec_k <= 0:
            logger.warning(
                "model.draft_model_id has no effect with "
                "tpu.speculative_k=0 — speculative decoding is off",
                extra={"extra_data": {"draft_model_id": draft_id}},
            )
        if self.spec_k > 0 and draft_id:
            if all(
                int(self.mesh.shape.get(a, 1)) == 1
                for a in ("tp", "pp", "sp", "ep")
            ):
                from vgate_tpu.runtime.speculative import DraftModelDrafter

                self.draft_model = DraftModelDrafter(
                    draft_id,
                    k_max=self.spec_k,
                    dtype=self.dtype,
                    window=int(tpu_cfg.draft_window),
                    checkpoint_path=self.config.model.draft_checkpoint_path,
                    target_vocab=self.spec.vocab_size,
                    device=self.mesh.devices.flat[0],
                    # ADVICE r5: a randomly-initialized drafter next to
                    # a real target checkpoint is a pure slowdown —
                    # DraftModelDrafter warns loudly on the combination
                    target_has_checkpoint=bool(
                        self.config.model.checkpoint_path
                    ),
                )
                self.drafter = self.draft_model.draft_for
            else:
                logger.warning(
                    "draft_model_id ignored on a model-parallel mesh; "
                    "using n-gram drafting",
                    extra={"extra_data": {"draft_model_id": draft_id}},
                )
        self.total_spec_drafted = 0
        self.total_spec_accepted = 0
        # device-resident penalty histogram for speculative mode, keyed
        # by a membership signature (rebuilt from host token lists when
        # membership changes; updated in-program otherwise)
        self._spec_pen: Optional[Dict[str, Any]] = None
        # membership-cached min-token arrays (immutable per sequence)
        self._spec_mt: Optional[Dict[str, Any]] = None

        # sp>1: prefill attention runs sequence-parallel (ring attention
        # over the sp axis); buckets must then split evenly across shards.
        # pp>1: prefill AND decode run through the GPipe stage relay
        # (parallel/pipeline.py).  The two reshape the same forward in
        # incompatible ways, so they are mutually exclusive.
        sp_size = int(self.mesh.shape.get("sp", 1))
        pp_size = int(self.mesh.shape.get("pp", 1))
        if sp_size > 1 and pp_size > 1:
            raise ValueError(
                f"sp={sp_size} and pp={pp_size} cannot combine: ring-"
                "attention prefill and the pipeline relay restructure "
                "the same forward along incompatible axes (sequence-"
                "inside-layers vs layers-across-stages) — a permanent "
                "design exclusion, not a missing feature; rationale and "
                "the supported matrix: docs/composition.md. For large "
                "meshes use sp*tp (long context) or pp*tp (deep model) "
                "with dp over the remainder."
            )
        if pp_size > 1 and self.spec.num_layers % pp_size:
            raise ValueError(
                f"{self.spec.num_layers} layers not divisible by "
                f"pp={pp_size}"
            )
        self._fwd_mesh = (
            self.mesh if (sp_size > 1 or pp_size > 1) else None
        )
        self._pp = pp_size
        self._sp = sp_size
        # carry-threaded KV pools (config.tpu.kv_carry): plain meshes
        # only — the sp/pp forwards keep their own threading
        self._kv_carry = bool(
            tpu_cfg.kv_carry and self._fwd_mesh is None
        )
        if sp_size > 1:
            bad = [
                b for b in self.scheduler.prefill_buckets if b % sp_size
            ]
            if bad:
                raise ValueError(
                    f"prefill buckets {bad} not divisible by sp={sp_size}; "
                    "ring-attention prefill shards the sequence axis evenly"
                )

        # sliding-window/softcap families (Gemma-2) ride every mesh: the
        # ring prefill takes window/softcap natively, and the pp relay
        # threads per-layer windows + softcap/scale through the stage
        # scan (parallel/pipeline.py, r4 — the r3 gate is gone)
        if tpu_cfg.speculative_k > 0 and pp_size > 1:
            raise ValueError(
                "speculative decoding cannot combine with pp>1: a "
                "verify round would relay candidates through every "
                "stage and roll back rejected KV writes per stage, "
                "serializing the pipeline — a permanent design "
                "exclusion (docs/composition.md). Speculation composes "
                "with sp, its long-context home turf; pp's throughput "
                "workloads are served by continuous batching."
            )
        # speculative x sp composes (r4): the verify step rides
        # sp_multitok_attention_and_write on the sharded pool — the
        # long-context single-stream case is speculation's home turf

        # Pallas kernels require a real TPU backend (tests run interpret-mode
        # kernels separately; the engine's jnp twins serve CPU meshes).
        # Local-attention families (Gemma-2) ride both kernels: they take
        # window/softcap/scale natively, and the decode kernel skips DMA
        # for pages below the window.
        self.use_pallas = bool(
            tpu_cfg.use_pallas
            and self.mesh.devices.flat[0].platform == "tpu"
        )
        if (
            self.use_pallas
            and int(getattr(tpu_cfg, "decode_block_slots", 1)) > 1
        ):
            import dataclasses as _dc

            # threaded on the spec (a static jit arg), like quant_kernel
            self.spec = _dc.replace(
                self.spec,
                decode_block_slots=int(tpu_cfg.decode_block_slots),
            )
        # tp>1 with Pallas on: the forwards need the mesh so attention
        # kernels run per tp shard (parallel/tp_attention.py) instead of
        # GSPMD replicating the pallas_call's operands.  The sp/pp
        # routing mesh (self._fwd_mesh) takes precedence when set.
        tp_size = int(self.mesh.shape.get("tp", 1))
        self._attn_mesh = self._fwd_mesh
        if self._attn_mesh is None and tp_size > 1 and self.use_pallas:
            self._attn_mesh = self.mesh
        # suffix-prefill / spec-verify dispatch mesh: sp shard path, or
        # the tp mesh (those forwards then gate their kernels off and
        # ride the auto-partitioned jnp paths)
        self._mt_mesh = (
            self.mesh
            if (self._sp > 1 or (tp_size > 1 and self.use_pallas))
            else None
        )
        if self.config.model.quantization in ("int8", "int4"):
            import dataclasses

            # the fused dequant kernels don't auto-partition under jit
            # sharding; model-parallel meshes keep the jnp einsum path.
            # Threaded on the spec (a static jit arg) so engines with
            # different meshes in one process never share the setting.
            # tpu.quant_kernel gates them independently of the attention
            # kernels (r4: int8 serving warmup hung in kernel compile).
            self.spec = dataclasses.replace(
                self.spec,
                quant_kernel=self.use_pallas
                and bool(tpu_cfg.quant_kernel)
                and all(
                    int(self.mesh.shape.get(a, 1)) == 1
                    for a in ("tp", "pp", "sp", "ep")
                ),
                # W8A8/W4A8 native-int8 GEMMs: pure jnp, so no mesh or
                # Pallas restriction (auto-partitions under jit sharding)
                int8_native=bool(getattr(tpu_cfg, "int8_native", False)),
            )
        elif bool(getattr(tpu_cfg, "int8_native", False)):
            logger.warning(
                "tpu.int8_native has no effect without model.quantization "
                "(int8 or int4) — serving stays on the plain dtype path"
            )
        self._submit_q: "queue.Queue[Sequence]" = queue.Queue()
        # abort commands from OTHER threads: (seq_id | None for all,
        # reason).  Processed on the engine thread each tick — the
        # scheduler's deques are engine-thread-owned, so cross-thread
        # iteration (a drain sweep racing try_admit) is never safe.
        self._abort_q: "queue.Queue[tuple]" = queue.Queue()
        # planned-evacuation commands (live migration): same
        # cross-thread discipline as aborts — the caller blocks on the
        # request's event while the engine thread checkpoints the
        # selected sequences between ticks.  See evacuate().
        self._evac_q: "queue.Queue[_EvacRequest]" = queue.Queue()
        # disaggregated prefill→decode handoff (runtime/handoff.py):
        # sequences submitted with handoff_requested are watched here
        # until their first token exists, then folded + staged via
        # scheduler.hold_for_handoff and announced through
        # on_handoff_staged (the pod worker wires it to a gateway
        # notification).  _handoff_q carries the cross-thread verdicts
        # back in — ("done", seq): the decode worker accepted, evacuate;
        # ("cancel", seq): transfer fell through, release the hold and
        # resume monolithic decode here.
        self._handoff_pending: List[Sequence] = []
        self._handoff_q: "queue.Queue[tuple]" = queue.Queue()
        self.on_handoff_staged: Optional[Callable[[Sequence, bool], None]] = (
            None
        )
        self._wakeup = threading.Event()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._fatal: Optional[BaseException] = None
        # hang-watchdog heartbeat: the loop stamps a fresh dict around
        # every dispatch/readback (whole-dict store — atomic under the
        # GIL, so the watchdog thread reads a consistent beat without a
        # lock).  `compiling` beats get recovery.compile_grace_s instead
        # of step_stall_s before the watchdog declares a stall.
        self._heartbeat: Dict[str, Any] = {
            "t": time.monotonic(), "kind": "init", "compiling": True,
        }
        # set by declare_stalled(): the engine thread is presumed stuck
        # in a device call — stop() then joins briefly instead of 30s
        self._stalled = False
        # containment entry gate: the watchdog thread and the engine
        # thread can both reach _contain_fatal (a woken stalled thread
        # typically raises against the swept state) — only the first
        # entry may run, or the second would overwrite _checkpointed
        # and silently drop the in-flight sequences awaiting replay
        self._contain_lock = named_lock("EngineCore._contain_lock")
        # readback/containment mutual exclusion: every token-append
        # readback loop holds this, and so does containment's
        # checkpoint sweep — the status/epoch guards alone are
        # check-then-append, and a woken stalled thread interleaving
        # appends with prepare_resume's prompt fold would corrupt the
        # generation (a token streamed to the client but excluded from
        # the folded prompt gets regenerated by the replay).
        # Uncontended in steady state: one acquire per readback.
        # Created EARLY (before the scheduler) because the kv-swap
        # manager's swap-out publication guard shares it: a ticket is
        # only published under this lock against a re-checked
        # status/epoch, so a containment fold can never interleave.
        # published at the END of containment (before on_fatal): the dp
        # repair thread polls _fatal, which is set FIRST — acting on a
        # mid-containment core would take an empty checkpoint and then
        # stop() the old core, turning the late-published checkpoint
        # into shutdown-lost sequences
        self._containment_done = False
        # in-flight sequences checkpointed by fatal containment for the
        # supervisor / dp router to replay (resume_in_flight); consumed
        # via take_checkpointed()
        self._checkpointed: List[Sequence] = []
        # sequences containment gave up on (max_resume_attempts); the
        # replayer folds this into its lost accounting via
        # take_resume_losses()
        self._resume_losses = 0
        self._resume_enabled = bool(
            self.config.recovery.resume_in_flight
        )
        self._max_resume_attempts = max(
            0, int(self.config.recovery.max_resume_attempts)
        )
        # first-dispatch tracking for spec-verify program variants (the
        # prefill/decode ladders have their own sets): heartbeat
        # compile-grace only — spec rounds recompile on width changes
        self._compiled_spec: set = set()
        # flight snapshot taken on the dying engine thread, while the
        # crashed tick's residents are still live (supervisor reads it)
        self._crash_snapshot: Optional[Dict[str, Any]] = None
        # supervision hook (runtime/supervisor.py): called once from the
        # engine thread after a fatal error is fully contained.  When set,
        # owed futures fail with a *retryable* error (the supervisor is
        # about to restart the core) instead of the raw fault.
        self.on_fatal: Optional[Callable[[BaseException], None]] = None
        # (fingerprint, resume_count) of the requests resident when the
        # loop died — the supervisor's poison heuristic counts repeat
        # offenders, but only FRESH submissions (resume_count == 0)
        # increment a streak: with resume_in_flight, innocent bystanders
        # ride consecutive crashes by design, and counting replays would
        # quarantine all traffic after any two rapid crashes
        self._fatal_suspects: List[tuple] = []
        self.total_steps = 0
        self.total_prefills = 0
        self.total_decode_tokens = 0
        self.total_state_rebuilds = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="vgt-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wakeup.set()
        if self._thread is not None:
            # a watchdog-declared stall means the thread is presumed
            # stuck inside a device call: don't hold the rebuild
            # hostage for 30s waiting on it (it is a daemon; the epoch
            # checks discard anything it does if it ever wakes)
            self._thread.join(timeout=1 if self._stalled else 30)
            self._thread = None
        # resolve every owed future: a sequence still resident (or still
        # in the submit queue) when the loop exits would leave its
        # waiter blocked on done_event forever.  Runs after the join, so
        # no engine thread races these mutations.  Checkpointed
        # sequences nobody claimed (supervisor stopped before replay)
        # are owed too.
        self._fail_pending_evacuations(
            RuntimeError("engine stopped")
        )
        checkpointed = self.take_checkpointed()
        for _ in checkpointed:
            metrics.LOST_SEQUENCES.labels(reason="shutdown").inc()
        owed = (
            list(self.scheduler.running)
            + list(self.scheduler.waiting)
            + checkpointed
        )
        while True:
            try:
                owed.append(self._submit_q.get_nowait())
            except queue.Empty:
                break
        stop_exc: Optional[BaseException] = None
        for seq in owed:
            if seq.status in (SeqStatus.RUNNING, SeqStatus.WAITING):
                if stop_exc is None:
                    stop_exc = EngineRecoveringError(
                        "engine stopped before the request could finish"
                    )
                # vgt-lint: disable=thread-discipline -- stop() joined the engine thread above; this is single-threaded teardown
                self.scheduler._release_residency(seq)
                seq.fail(stop_exc)
        self.scheduler.waiting.clear()

    # ------------------------------------------------------------ submission

    def _fail_exception(self, exc: BaseException) -> BaseException:
        """The exception owed futures fail with after a fatal: supervised
        engines (on_fatal set) are about to restart, so clients get the
        retryable 503 type with the raw fault chained; unsupervised
        engines keep the raw fault (the dp router's containment
        contract)."""
        if self.on_fatal is None:
            return exc
        wrapped = EngineRecoveringError(
            f"engine crashed and is restarting: {exc}"
        )
        wrapped.__cause__ = exc
        return wrapped

    def _on_seq_settle(self, seq: Sequence) -> None:
        """Single settle observer (Sequence.finish/fail): closes the
        flight-recorder request record and the request's phase spans —
        covers every settle path, scheduler-internal sheds included."""
        self.flight.on_close(seq)
        tr = seq.trace
        if tr is not None:
            if seq.error is None:
                tr.end("decode", tokens=seq.num_generated)
            # failures leave the phase span open so close() annotates
            # it with the exception — a cleanly-ended decode span on a
            # failed request would misread as a normal completion
            tr.close(seq.error)

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[RequestMeta] = None,
    ) -> Sequence:
        if self._fatal is not None:
            raise RuntimeError("engine is dead") from self._fatal
        seq = Sequence(
            prompt_ids=list(prompt_ids),
            params=params,
            stream_cb=stream_cb,
        )
        if self.flight.enabled:
            seq.on_settle = self._on_seq_settle
            if meta is not None:
                seq.request_id = meta.request_id
                seq.trace = RequestTrace(meta)
                # the queue phase starts NOW (caller thread); the engine
                # thread ends it at admission
                seq.trace.start("queue", start_pc=seq.arrival_t)
        self._submit_q.put(seq)
        # Re-check after the put: if the engine died between the check
        # above and the put, the fatal handler may already have drained
        # the queue and will never see this seq — fail everything still
        # queued ourselves so no client hangs on done_event.  NOTE:
        # several submitter threads can race this drain (and the fatal
        # handler's own sweep) over the same queue; get_nowait hands
        # each orphan to exactly one drainer, but the SAME sequence can
        # still see fail() twice when a submitter drains a sibling the
        # handler also holds in `doomed` — correctness relies on
        # Sequence.fail() being idempotent-safe (done_event.set and the
        # _settle_notified guard make the second call a no-op for the
        # waiter and the observer; status/error overwrite with an
        # equivalent terminal value).
        if self._fatal is not None:
            exc = self._fail_exception(self._fatal)
            while True:
                try:
                    orphan = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                orphan.fail(exc)
            if seq.status is SeqStatus.FAILED:
                raise RuntimeError("engine is dead") from exc
        self._wakeup.set()
        return seq

    def encode_prompt(self, prompt: str) -> List[int]:
        """Prompt -> submission token ids (chat-style suffix truncation).
        Split out so the supervisor can fingerprint a prompt for the
        poison quarantine before submission."""
        ids = self.tokenizer.encode(prompt)
        max_prompt = self.config.model.max_model_len - 1
        if len(ids) > max_prompt:
            ids = ids[-max_prompt:]  # keep the suffix (chat-style truncation)
        return ids or [self.tokenizer.bos_id]

    def submit_prompt(
        self,
        prompt: str,
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[RequestMeta] = None,
    ) -> Sequence:
        return self.submit_tokens(
            self.encode_prompt(prompt), params, stream_cb, meta=meta
        )

    def generate(
        self, prompts: Seq[str], params: Seq[SamplingParams]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API used by the sync backend seam."""
        seqs = [
            self.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            text = self.final_text(seq)
            gen_time = (seq.finish_t or 0) - seq.arrival_t
            n = seq.num_output_tokens
            result = {
                "text": text,
                "token_ids": list(seq.generated_ids),
                "num_tokens": n,
                "prompt_tokens": seq.orig_prompt_len,
                "finish_reason": seq.finish_reason,
                "metrics": {
                    "ttft": seq.ttft or 0.0,
                    "tpot": seq.tpot or 0.0,
                    "gen_time": gen_time,
                    **seq.resume_metrics(),
                },
            }
            if seq.params.logprobs:
                result["logprobs"] = self.logprob_entries(seq)
            results.append(result)
        return results

    # ------------------------------------------------------------ the loop

    @engine_thread_root
    def _loop(self) -> None:
        logger.info("engine thread started")
        while self._running:
            try:
                self._beat("tick")
                # perf attribution brackets the whole tick: phases
                # measured inside (dispatch/device/readback/detok) are
                # subtracted from the tick wall, the remainder is
                # host_s — so the five phases sum to the wall by
                # construction (observability/perf.py)
                self.perf.tick_begin()
                worked = self._tick()
                self.perf.tick_end(worked)
                if not worked:
                    self._wakeup.wait(timeout=0.005)
                    self._wakeup.clear()
            except Exception as exc:
                logger.error("engine loop fatal error", exc_info=True)
                self._contain_fatal(exc)
        logger.info("engine thread stopped")

    @engine_thread_only
    def _beat(self, kind: str, compiling: bool = False, **fields) -> None:
        """Stamp the watchdog heartbeat (whole-dict store — atomic under
        the GIL).  Call immediately BEFORE any potentially-blocking
        device dispatch/readback so a wedge there is exactly what ages
        the beat; ``compiling`` widens the stall threshold to
        recovery.compile_grace_s for first-compile pauses."""
        self._heartbeat = {
            "t": time.monotonic(),
            "kind": kind,
            "compiling": bool(compiling),
            **fields,
        }

    def _contain_fatal(self, exc: BaseException) -> bool:
        """Fatal containment, shared by the engine thread's crash
        handler and the watchdog's :meth:`declare_stalled`: record the
        crash tick + flight snapshot, collect poison suspects, then
        either CHECKPOINT resumable in-flight sequences for the
        supervisor / dp router to replay (resume_in_flight under
        supervision) or fail every owed future (the unsupervised
        containment contract).

        The crash becomes the ring's final tick, so a snapshot ends
        with the faulting dispatch; the snapshot runs BEFORE the sweep
        below — the in-flight view must show what was resident at the
        moment of death, not after.

        First entry only (returns False otherwise): after a watchdog
        declare_stalled, the stuck engine thread usually wakes into the
        already-swept state, raises, and lands here AGAIN via the loop's
        except handler — re-running the sweep would overwrite
        _checkpointed (dropping the sequences awaiting replay) and fire
        a duplicate on_fatal."""
        with self._contain_lock:
            if self._fatal is not None:
                logger.warning(
                    "fatal containment skipped: engine already "
                    "contained",
                    extra={
                        "extra_data": {
                            "error": f"{type(exc).__name__}: {exc}",
                            "first": (
                                f"{type(self._fatal).__name__}: "
                                f"{self._fatal}"
                            ),
                        }
                    },
                )
                return False
            self._fatal = exc
        try:
            self._contain_body(exc)
        except Exception:  # pragma: no cover - defensive
            # containment itself failing must NOT strand the system:
            # _fatal is already set, so if _containment_done never
            # published, the supervisor would stay SERVING with hung
            # clients and the dp sweep would skip this replica forever.
            # Swallow (we are already dying of `exc`), log loudly, and
            # fall through so the flag + on_fatal always run.
            logger.error(
                "fatal containment raised; proceeding to publication "
                "with a possibly partial sweep",
                exc_info=True,
            )
            self._running = False
        # published before on_fatal: when the dp repair thread (or the
        # supervisor) wakes on the hook, the checkpoint is complete
        self._containment_done = True
        # unblock any evacuate() caller: the containment checkpoint now
        # owns the residents (the dp sweep will redistribute them)
        self._fail_pending_evacuations(exc)
        if self.on_fatal is not None:
            try:
                self.on_fatal(exc)
            except Exception:  # pragma: no cover - defensive
                logger.error("on_fatal hook failed", exc_info=True)
        return True

    def _contain_body(self, exc: BaseException) -> None:
        """The containment work itself (snapshot, suspects, sweep) —
        split from :meth:`_contain_fatal` so the caller can guarantee
        `_containment_done` + `on_fatal` publication even if any of
        this raises."""
        self.flight.record_tick(
            "crash",
            error=f"{type(exc).__name__}: {exc}",
            batch=len(self.scheduler.running),
            queue_depth=len(self.scheduler.waiting),
        )
        self._crash_snapshot = self.flight.crash_snapshot(exc)
        # poison-heuristic evidence: the requests resident at the
        # crash (keyed by their ORIGINAL prompt, which survives
        # preemption's and resume's prompt folding), with the resume
        # attempt count so the supervisor can tell client persistence
        # (fresh submissions) from the engine's own replays
        self._fatal_suspects = [
            (
                faults.fingerprint(s.prompt_ids[: s.orig_prompt_len]),
                s.resume_count,
            )
            for s in self.scheduler.running
            # integrity canaries are the ENGINE's own probes: never
            # poison suspects (quarantining the canary prompt would
            # blind every future self-probe)
            if not s.canary
        ]
        # sweep EVERY owed future: running, waiting, and anything still
        # sitting in the submit queue (a client blocked on one of those
        # would otherwise hang forever).  Under supervision with
        # resume_in_flight, resumable sequences are checkpointed as
        # prefill-continues instead of failed — the supervisor replays
        # them into the rebuilt core and clients see a latency blip,
        # not a 503.
        checkpointing = (
            self._resume_enabled and self.on_fatal is not None
        )
        fail_exc: Optional[BaseException] = None
        kept: List[Sequence] = []
        # the sweep excludes token-append readbacks (see
        # _readback_lock): a woken stalled thread must observe either
        # pre-fold state (its epoch check passes, containment waits) or
        # fully-folded state (epoch bumped, it skips) — never a fold in
        # progress.  BOUNDED acquire, fail-open: the append sections
        # run stream_cb/settle callbacks, and a wedge *there* is
        # precisely a stall — blocking the watchdog thread on it
        # forever would wedge the monitor itself (no rebuild, no
        # further stall detection).  Proceeding without the lock risks
        # only the narrow interleaving the lock exists for; a wedged
        # monitor loses everything.
        locked = self._readback_lock.acquire(timeout=5.0)
        if not locked:
            logger.error(
                "containment proceeding WITHOUT the readback lock "
                "(append section appears wedged — likely a stuck "
                "stream callback); sequences mid-append may replay "
                "with a duplicated token"
            )
        try:
            doomed = list(self.scheduler.running) + list(
                self.scheduler.waiting
            )
            while True:
                try:
                    doomed.append(self._submit_q.get_nowait())
                except queue.Empty:
                    break
            for seq in doomed:
                if (
                    checkpointing
                    and not seq.abort_requested
                    and not seq.canary
                ):
                    if seq.resume_count >= self._max_resume_attempts:
                        # replaying a request that has now ridden
                        # through max_resume_attempts restarts is more
                        # likely the crashes' cause than their victim:
                        # typed 503
                        metrics.LOST_SEQUENCES.labels(
                            reason="max_attempts"
                        ).inc()
                        self._resume_losses += 1
                        seq.fail(
                            ResumeExhaustedError(
                                "request was in flight across "
                                f"{seq.resume_count} engine restarts "
                                "and was given up on; retry shortly"
                            )
                        )
                        continue
                    if seq.trace is not None:
                        seq.trace.resumed()
                    # stamp the pool format the checkpoint's sampling
                    # history was produced under: submit_existing on the
                    # replay target refuses a mismatch (a replica fleet
                    # mid-rollout can mix kv dtypes; replaying into a
                    # different format would silently change numerics
                    # mid-generation).  getattr: bare-core test fakes
                    # run containment without ever building a pool.
                    geo = getattr(self, "geometry", None)
                    if geo is not None:
                        seq.kv_dtype = geo.kv_dtype
                    seq.prepare_resume()
                    kept.append(seq)
                    continue
                if fail_exc is None:
                    fail_exc = self._fail_exception(exc)
                seq.fail(fail_exc)
            self._checkpointed = kept
            self.scheduler.waiting.clear()
            for i in range(len(self.scheduler.slots)):
                self.scheduler.slots[i] = None
            self._pending_chunks.clear()
            self._running = False
        finally:
            if locked:
                self._readback_lock.release()

    def declare_stalled(self, exc: BaseException) -> bool:
        """Watchdog containment, called OFF the engine thread when the
        heartbeat went stale: the loop is presumed stuck inside a
        device call (Mosaic hang, stuck TPU grant, wedged transfer) —
        nothing will ever *raise*, so the monitor declares the fault.
        Stops the loop flag first (the stuck thread exits if it ever
        wakes), then runs the same containment as an on-thread crash.
        The small window where a merely-slow thread wakes mid-sweep is
        covered by the preempt-epoch checks on every readback path:
        checkpointed sequences bumped their epoch, so late tokens are
        discarded.  Returns False when the engine already died (or
        stopped) another way."""
        if self._fatal is not None or not self._running:
            return False
        self._stalled = True
        self._running = False
        self._wakeup.set()
        hb = self._heartbeat
        self.flight.record_tick(
            "stall",
            phase=hb.get("kind"),
            stalled_s=round(time.monotonic() - hb.get("t", 0.0), 3),
            compiling=hb.get("compiling", False),
            batch=len(self.scheduler.running),
            queue_depth=len(self.scheduler.waiting),
        )
        # False when an on-thread crash won the containment race — the
        # caller must not count a stall the engine didn't die of
        return self._contain_fatal(exc)

    def take_checkpointed(self) -> List[Sequence]:
        """Hand the fatal-containment checkpoint to its replayer
        (supervisor restart / dp failover); idempotent-empty after."""
        # vgt-lint: disable=thread-discipline -- single GIL-atomic swap; callers gate on _containment_done, after which the folding writer is done
        out, self._checkpointed = self._checkpointed, []
        return out

    def take_resume_losses(self) -> int:
        """Sequences containment gave up on (already failed typed);
        the replayer folds the count into its lost total.  Zeroing like
        take_checkpointed so repeated sweeps never double-count."""
        n, self._resume_losses = self._resume_losses, 0
        return n

    def submit_existing(self, seq: Sequence) -> None:
        """Re-admit a checkpointed sequence from another engine
        incarnation (supervisor replay) or a dead dp replica
        (failover).  The SAME Sequence object rides in — done_event
        waiter, stream_cb, cancel-token abort hooks and the absolute
        deadline all stay valid — re-wired to this core's settle
        observer, and prefilled-continue on admission (prepare_resume
        already folded the partial generation into the prompt)."""
        if self._fatal is not None:
            raise RuntimeError("engine is dead") from self._fatal
        if (
            seq.kv_dtype is not None
            and seq.kv_dtype != self.geometry.kv_dtype
        ):
            # fail cleanly instead of replaying garbage: the generated
            # prefix being folded into the prompt was sampled against a
            # different KV storage format — continuing it here would
            # splice two numerically different streams.  replay_into
            # turns this into the typed retryable 503.
            raise ValueError(
                f"checkpoint was taken under kv dtype "
                f"{seq.kv_dtype!r} but this core serves "
                f"{self.geometry.kv_dtype!r}; refusing the replay"
            )
        seq.on_settle = (
            self._on_seq_settle if self.flight.enabled else None
        )
        self._submit_q.put(seq)
        # same post-put re-check as submit_tokens: a crash between the
        # gate and the put may have swept the queue already
        if self._fatal is not None:
            exc = self._fail_exception(self._fatal)
            while True:
                try:
                    orphan = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                orphan.fail(exc)
            if seq.status is SeqStatus.FAILED:
                raise RuntimeError("engine is dead") from exc
        self._wakeup.set()

    # --------------------------------------------- planned evacuation

    def evacuate(
        self,
        seq_ids: Optional[List[int]] = None,
        reason: str = "drain",
        timeout: float = 30.0,
    ) -> List[Sequence]:
        """Checkpoint selected RUNNING/WAITING sequences WITHOUT a
        fatal — the planned-movement twin of ``_contain_fatal``'s
        checkpoint path (replica drain, hot-replica rebalance, dp
        scale-down).  The core stays alive and keeps serving its other
        residents; the selected sequences' slots + KV pages free this
        tick, nothing settles, and the LIVE Sequence objects come back
        folded as prefill-continues (``prepare_migrate``: the PR-5
        staleness epoch bumped so in-flight chunk readbacks discard,
        the kv-dtype stamp set so a mismatched replay target refuses
        cleanly).  ``seq.checkpoint()`` yields the pure-data
        ``SequenceCheckpoint`` form of each.

        Thread-safe: enqueues a command the engine thread applies
        between ticks (the scheduler's deques are engine-thread-owned)
        and blocks up to ``timeout`` — generous by default because the
        loop may legitimately be inside a long device dispatch.
        ``seq_ids=None`` selects everything resident or queued.
        Raises RuntimeError when the engine is (or dies while)
        evacuating — the caller's failover machinery then owns the
        residents — and MigrationError on timeout."""
        if self._fatal is not None:
            raise RuntimeError("engine is dead") from self._fatal
        req = _EvacRequest(
            list(seq_ids) if seq_ids is not None else None, reason
        )
        self._evac_q.put(req)
        self._wakeup.set()
        if not req.event.wait(timeout=timeout):
            with req.lock:
                published = (
                    req.result is not None or req.error is not None
                )
                if not published:
                    req.cancelled = True
            if not published:
                if self._fatal is not None:
                    raise RuntimeError(
                        "engine died while evacuating"
                    ) from self._fatal
                raise MigrationError(
                    f"evacuation did not complete within "
                    f"{timeout:.1f}s (engine loop busy or wedged); "
                    "sequences stayed put"
                )
            # publication raced the timeout: the evacuation completed
            # and we own the result after all — fall through
        if req.error is not None:
            raise req.error
        return req.result or []

    def _fail_pending_evacuations(self, exc: BaseException) -> None:
        """Unblock evacuate() callers when the loop can no longer serve
        them (stop/fatal); their sequences are untouched — containment
        or shutdown accounting owns the residents from here."""
        while True:
            try:
                req = self._evac_q.get_nowait()
            except queue.Empty:
                return
            with req.lock:
                if not req.cancelled:
                    req.error = RuntimeError(
                        f"engine unavailable for evacuation: {exc}"
                    )
            req.event.set()

    @engine_thread_only
    def _process_evacuations(self) -> None:
        """Apply queued evacuation commands (engine thread only)."""
        while True:
            try:
                req = self._evac_q.get_nowait()
            except queue.Empty:
                return
            with req.lock:
                if req.cancelled:
                    continue  # caller timed out; sequences stayed put
            result: Optional[List[Sequence]] = None
            error: Optional[BaseException] = None
            try:
                result = self._evacuate_now(req.seq_ids, req.reason)
            except Exception as exc:  # pragma: no cover - defensive
                logger.error("evacuation failed", exc_info=True)
                error = exc
            with req.lock:
                if req.cancelled:
                    # the caller gave up MID-evacuation: nobody will
                    # place these sequences — fold them straight back
                    # into this core so their clients keep streaming
                    # here, exactly as if nothing had moved
                    if result:
                        for seq in result:
                            try:
                                self.submit_existing(seq)
                            except RuntimeError:
                                # went fatal mid-undo: settle typed
                                # rather than strand the sequence
                                # outside every scheduler
                                seq.fail(self._fail_exception(
                                    self._fatal
                                    or RuntimeError("engine stopped")
                                ))
                        logger.warning(
                            "evacuation abandoned by timed-out "
                            "caller; re-admitted locally",
                            extra={"extra_data": {
                                "count": len(result),
                                "reason": req.reason,
                            }},
                        )
                else:
                    req.result = result
                    req.error = error
            req.event.set()

    @engine_thread_only
    def _evacuate_now(
        self, seq_ids: Optional[List[int]], reason: str
    ) -> List[Sequence]:
        targets = None if seq_ids is None else set(seq_ids)
        candidates = list(self.scheduler.running) + list(
            self.scheduler.waiting
        )
        if not any(
            targets is None or s.seq_id in targets for s in candidates
        ):
            return []
        # fold in-flight decode chunks into host state FIRST: tokens
        # already sampled on device would otherwise be discarded by the
        # epoch guard and regenerated on the target (correct for greedy/
        # seeded, but wasted compute — and a distribution re-draw for
        # unseeded sampling, exactly like preemption)
        if self._pending_chunks:
            self._process_chunks(drain=True)
            self._decode_signature_cache = None
            candidates = list(self.scheduler.running) + list(
                self.scheduler.waiting
            )
        out: List[Sequence] = []
        for seq in candidates:
            if targets is not None and seq.seq_id not in targets:
                continue
            if seq.status not in (SeqStatus.RUNNING, SeqStatus.WAITING):
                continue  # settled while the chunks drained
            if seq.abort_requested:
                continue  # about to settle as abort; nothing to move
            # stamp the KV storage format the generated prefix was
            # sampled under — submit_existing on the target refuses a
            # mismatch (same guard as crash checkpoints)
            geo = getattr(self, "geometry", None)
            if geo is not None:
                seq.kv_dtype = geo.kv_dtype
            if seq.trace is not None:
                seq.trace.migrated()
            self.scheduler.evacuate(seq)
            seq.prepare_migrate()
            self.flight.record_tick(
                "migrate",
                seq_id=seq.seq_id,
                request_id=seq.request_id,
                tokens=seq.num_generated,
                reason=reason,
            )
            out.append(seq)
        if out:
            # membership changed: any device decode state is stale
            self._decode_signature_cache = None
            logger.info(
                "evacuated sequences for planned migration",
                extra={
                    "extra_data": {
                        "count": len(out), "reason": reason,
                    }
                },
            )
        return out

    # ------------------ disaggregated prefill→decode handoff staging

    def handoff_done(self, seq: Sequence) -> None:
        """Cross-thread (worker RPC plane): the decode worker ACCEPTED
        this sequence's KV transfer — drop its queue slot and local
        staged ticket WITHOUT settling it (the decode worker owns the
        stream now).  Processed on the engine thread next tick."""
        self._handoff_q.put(("done", seq))
        self._wakeup.set()

    def handoff_cancel(self, seq: Sequence) -> None:
        """Cross-thread: the transfer fell through (retries exhausted,
        decode pool drained, gateway raced a loss) — lift the hold so
        the next try_admit swap-ins the staged KV and decode continues
        MONOLITHICALLY here with zero recompute."""
        self._handoff_q.put(("cancel", seq))
        self._wakeup.set()

    @engine_thread_only
    def _process_handoffs(self) -> None:
        """Handoff staging pump (runtime/handoff.py), run each tick
        after evacuations: apply cross-thread done/cancel verdicts,
        then fold+stage any watched sequence whose first token now
        exists and announce it via ``on_handoff_staged``."""
        while True:
            try:
                verb, seq = self._handoff_q.get_nowait()
            except queue.Empty:
                break
            if verb == "done":
                if getattr(seq, "_handoff_hold", False):
                    seq._handoff_hold = False  # type: ignore[attr-defined]
                    self.scheduler.evacuate(seq)
                    self.flight.record_tick(
                        "handoff_done", seq_id=seq.seq_id,
                        request_id=seq.request_id,
                    )
            else:  # "cancel"
                self.scheduler.release_hold(seq)
        if not self._handoff_pending:
            return
        pending: List[Sequence] = []
        ready: List[Sequence] = []
        for seq in self._handoff_pending:
            if (
                not seq.handoff_requested
                or seq.status not in (SeqStatus.WAITING, SeqStatus.RUNNING)
                or seq.abort_requested
            ):
                continue  # settled/cancelled — stop watching
            if seq.status is SeqStatus.RUNNING and seq.num_generated >= 1:
                ready.append(seq)
            else:
                pending.append(seq)  # still queued or mid-prefill
        self._handoff_pending = pending
        if not ready:
            return
        if self._pending_chunks:
            # fold in-flight decode chunks first (like _evacuate_now):
            # the staged KV must cover every token already streamed
            self._process_chunks(drain=True)
            self._decode_signature_cache = None
        for seq in ready:
            seq.handoff_requested = False
            if seq.status is not SeqStatus.RUNNING or seq.abort_requested:
                staged = False  # settled while the chunks drained
            else:
                # stamp the KV storage format like every checkpoint
                # path — submit_existing on the decode worker refuses
                # a mismatched pool
                geo = getattr(self, "geometry", None)
                if geo is not None:
                    seq.kv_dtype = geo.kv_dtype
                staged = self.scheduler.hold_for_handoff(seq)
            if staged:
                self._decode_signature_cache = None
                self.flight.record_tick(
                    "handoff_stage", seq_id=seq.seq_id,
                    request_id=seq.request_id, tokens=seq.num_generated,
                )
            cb = self.on_handoff_staged
            if cb is not None:
                try:
                    cb(seq, staged)
                except Exception:  # pragma: no cover - defensive
                    logger.error(
                        "on_handoff_staged callback failed", exc_info=True
                    )

    @engine_thread_only
    def _tick(self) -> bool:
        """One iteration of the engine loop.

        1. Dispatch every admissible prefill asynchronously, then read all
           their first tokens back in a single transfer.
        2. Keep up to ``pipeline_depth`` decode chunks in flight: dispatch
           the next chunk against device-resident state, then block on the
           *oldest* chunk's readback — host-side token processing overlaps
           device execution of the newer chunk (and, over a remote device
           tunnel, the transfer latency of one chunk hides under the
           execution of the next).

        Returns False when there was no work (the loop then sleeps).
        """
        self._drain_submissions()
        # planned evacuations before anything dispatches: a drain/
        # rebalance coordinator is blocked on this, and the selected
        # sequences must not burn another decode chunk here first
        self._process_evacuations()
        # then handoff staging (disaggregated prefill→decode): fold
        # first-token'd handoff candidates off the device before they
        # burn decode chunks that belong on the decode pool
        self._process_handoffs()
        # stall fault probe (vgate_tpu/faults.py): a `delay` armed here
        # past recovery.step_stall_s simulates a wedged loop for the
        # hang watchdog.  Only probed while work is resident, so chaos
        # arming cannot stall an idle engine into a pointless restart.
        if faults.is_active() and self.scheduler.has_work():
            faults.check("stall")
            if not self._running:
                # the watchdog declared this core stalled while the
                # armed delay slept: containment already swept the
                # residents — touching scheduler state now would race
                # the replay on the rebuilt core
                return False
        self._drain_abort_requests()
        self._handle_aborts()
        self._handle_deadlines()
        # proactive prefix-cache trim (two int compares when healthy):
        # keep truly-free pages above the evict watermark so allocation
        # bursts never pay the eviction walk synchronously and
        # admission's kv_pressure shedding only ever sees a drained cache
        self.scheduler.maybe_trim()
        if self.spec_k > 0 and not self.spec_suspended:
            if self._pending_chunks:
                # chunked decode ran while a brownout suspended
                # speculation: fold the in-flight chunks into host
                # state before a spec round reads last-token/positions,
                # and kill the chunk path's signature cache (spec
                # rounds advance positions behind its back)
                self._process_chunks(drain=True)
                self._decode_signature_cache = None
            worked = self._admit_and_prefill()
            worked = self._tick_speculative() or worked
            if (
                self.integrity is not None
                and not worked
                and not self.scheduler.has_work()
            ):
                # idle-tick checksum sweep, speculative path (the
                # non-spec twin below)
                self.integrity.idle_tick(self)
            return worked
        worked = self._admit_and_prefill()

        active = self._running_seqs()
        if active:
            signature = self._decode_signature(active)
            if signature != self._decode_signature_cache:
                # membership changed: all in-flight chunks must be folded
                # into host state before rebuilding the device state.  The
                # cache is dead from here until a rebuild succeeds — leaving
                # the old value would let a later identical-looking
                # membership dispatch against stale device tokens/positions.
                self._decode_signature_cache = None
                self._process_chunks(drain=True)
                active = self._running_seqs()
                if active:
                    chunk = self._pick_chunk(active)
                    if self.scheduler.prepare_decode(active, horizon=chunk):
                        active = self._running_seqs()  # minus any victims
                        if active:
                            self._build_decode_state(active)
                            self._decode_signature_cache = (
                                self._decode_signature(active)
                            )
                            self._dispatch_chunk(active, chunk)
                worked = True
            elif len(self._pending_chunks) < self.pipeline_depth:
                in_flight = sum(c[1] for c in self._pending_chunks)
                chunk = self._pick_chunk(active, lead=in_flight)
                if chunk == 0:
                    # every sequence's budget is already covered by the
                    # in-flight steps — a new chunk would be pure overshoot
                    self._process_chunks()
                elif self.scheduler.prepare_decode(
                    active, horizon=in_flight + chunk
                ):
                    # preemption changes membership -> handled next tick;
                    # dispatch when the slot set survived intact, refreshing
                    # only the page-table upload when pages merely grew
                    # (tokens/positions stay device-resident — a drain here
                    # would collapse the pipeline at every page boundary)
                    survivors = self._running_seqs()
                    new_sig = self._decode_signature(survivors)
                    if new_sig == self._decode_signature_cache:
                        self._dispatch_chunk(active, chunk)
                    elif [
                        t[:3] for t in new_sig
                    ] == [
                        t[:3] for t in self._decode_signature_cache or ()
                    ]:
                        # identity (incl. preempt epoch) intact, only page
                        # counts grew -> page-table refresh is sufficient
                        self._refresh_page_tables(survivors)
                        self._decode_signature_cache = new_sig
                        self._dispatch_chunk(active, chunk)
                worked = True

        if self._pending_chunks and (
            len(self._pending_chunks) >= self.pipeline_depth
            or not active
        ):
            self._process_chunks(drain=not active)
            worked = True
        if (
            self.integrity is not None
            and not worked
            and not self._pending_chunks
            and not self.scheduler.has_work()
        ):
            # idle tick: advance the budgeted weight-checksum sweep
            # (integrity.sweep_leaves_per_tick small on-device
            # reductions) — never on a tick that did decode/prefill
            # work, so the sweep cannot steal serving latency.  A
            # mismatch raises IntegrityError: containment routes it to
            # the supervisor / dp repair as a `corrupt` fatal and the
            # rebuild reloads weights instead of keeping them.
            self.integrity.idle_tick(self)
        # re-tick immediately when processing just opened a slot for a
        # waiting prompt (otherwise the loop would nap 5ms before admitting)
        return (
            worked
            or bool(self._pending_chunks)
            or self.scheduler.has_admissible_waiting()
        )

    @engine_thread_only
    def _running_seqs(self) -> List[Sequence]:
        return [
            s for s in self.scheduler.running
            if s.status is SeqStatus.RUNNING
        ]

    @engine_thread_only
    def _handle_aborts(self) -> None:
        """Drop RUNNING sequences whose client cancelled (SSE disconnect
        etc.): slot + pages free immediately, finish_reason "abort".
        In-flight chunks may still hold the sequence — the per-chunk
        epoch/status check discards their tokens at readback.  Waiting-
        queue aborts drop when they reach the queue head
        (scheduler.try_admit)."""
        for seq in self._running_seqs():
            if seq.abort_requested:
                # bind the owning request so every log record emitted
                # while dropping the sequence carries its identity
                # (logging_config falls back to the thread-local when
                # the engine thread has no active span)
                with bound_request(
                    seq.request_id, getattr(seq.trace, "trace_id", None)
                ):
                    self.scheduler.abort(seq)

    @engine_thread_only
    def _handle_deadlines(self) -> None:
        """Shed RUNNING sequences past their end-to-end deadline between
        decode ticks: the client's budget is blown, so decoding on would
        only burn batchmates' step time.  The owed future fails with a
        DeadlineExceededError carrying the partial generation (→ 504
        with partial-tokens metadata at the gateway); slot + KV pages
        free this tick.  Waiting-queue deadlines are the scheduler's
        ``_shed_expired``.  In-flight chunks holding the sequence are
        harmless: the per-chunk status check discards their tokens."""
        now = time.perf_counter()
        for seq in self._running_seqs():
            if not seq.past_deadline(now):
                continue
            if seq.trace is not None:
                seq.trace.event("deadline_shed")
            with bound_request(
                seq.request_id, getattr(seq.trace, "trace_id", None)
            ):
                self._shed_deadline(seq)

    @engine_thread_only
    def _shed_deadline(self, seq: Sequence) -> None:
        self.scheduler.shed(
            seq,
            DeadlineExceededError(
                f"request deadline ({seq.params.timeout_s:.3f}s) "
                f"passed mid-generation after "
                f"{seq.num_generated} tokens",
                partial_text=self.final_text(seq),
                partial_tokens=seq.num_generated,
                deadline_s=seq.params.timeout_s or 0.0,
                # where the budget went (flight recorder): lets a 504
                # distinguish "queued forever" from "decoded slowly"
                # without server access
                phases=self.flight.phases_of(seq),
            ),
        )

    def abort(self, seq_id: int, reason: str = "client_disconnect") -> None:
        """Request-scoped cancellation by sequence id (the vLLM
        ``abort_request`` surface): enqueues an abort command the engine
        thread applies at its next tick (shed within one tick; slot +
        KV pages freed).  Thread-safe by construction — the scheduler's
        deques are only ever touched on the engine thread."""
        self._abort_q.put((seq_id, reason))
        self._wakeup.set()

    def abort_in_flight(self, reason: str = "drain") -> None:
        """Request-abort EVERY waiting/running sequence (the graceful
        drain's straggler sweep once ``lifecycle.drain_timeout_s``
        passes).  Applied on the engine thread at its next tick."""
        self._abort_q.put((None, reason))
        self._wakeup.set()

    @engine_thread_only
    def _drain_abort_requests(self) -> None:
        """Apply queued abort commands (engine thread only)."""
        while True:
            try:
                seq_id, reason = self._abort_q.get_nowait()
            except queue.Empty:
                return
            for seq in list(self.scheduler.running) + list(
                self.scheduler.waiting
            ):
                if (
                    (seq_id is None or seq.seq_id == seq_id)
                    and seq.status
                    in (SeqStatus.RUNNING, SeqStatus.WAITING)
                    and not seq.abort_requested
                ):
                    seq.request_abort(reason)

    @staticmethod
    def _all_greedy(seqs, num_lp: int) -> bool:
        """Static all-greedy program-variant predicate, shared by the
        decode-chunk and spec-verify dispatches (one definition so the
        compile-cache split can never diverge between paths)."""
        return num_lp == 0 and all(
            s.params.temperature == 0.0 for s in seqs
        )

    # ------------------------------------------------------------- prefill

    @engine_thread_only
    def _drain_submissions(self) -> None:
        while True:
            try:
                seq = self._submit_q.get_nowait()
            except queue.Empty:
                return
            adopt = getattr(seq, "_handoff_adopt", None)
            if adopt is not None:
                # decode-side arrival of a prefill→decode handoff: park
                # the shipped KV payload as a local swap ticket so
                # try_admit swap-ins with ZERO recompute.  A refusal
                # (no swap tier / pool full) folds to the recompute
                # path instead — slower, still token-identical.
                seq._handoff_adopt = None  # type: ignore[attr-defined]
                payload, num_pages = adopt
                adopted = (
                    self.kv_swap is not None
                    and self.kv_swap.adopt_remote(seq, payload, num_pages)
                )
                if not adopted:
                    seq.reset_for_recompute()
                    logger.warning(
                        "handoff payload adoption refused; falling back "
                        "to re-prefill",
                        extra={"extra_data": {
                            "seq_id": seq.seq_id,
                            "request_id": seq.request_id,
                            "pages": num_pages,
                        }},
                    )
            try:
                self.scheduler.add(seq)
            except Exception as exc:
                seq.fail(exc)
                continue
            if seq.handoff_requested:
                self._handoff_pending.append(seq)

    @engine_thread_only
    def _step_key(self):
        self._step_counter += 1
        return jax.random.fold_in(self._base_key, self._step_counter)

    @engine_thread_only
    def _admit_and_prefill(self) -> bool:
        """Admit waiting prompts a free slot + pages exist for, then prefill
        them in **batched programs**: same-bucket admissions stack into one
        ``[B, bucket]`` dispatch (B padded to the next power of two, padding
        rows writing trash page 0), so a burst of N prompts costs
        ~N/prefill_batch_max dispatches instead of N — the dominant cost
        over a high-RTT device tunnel.  First tokens for the whole wave are
        read back in a single transfer.

        While sequences are actively decoding, at most
        ``tpu.prefill_admit_limit`` prompts are admitted per tick, so a
        burst of prefills cannot stall resident slots for the whole burst —
        decode chunks keep flowing between admission waves (VERDICT r1
        weak-2; the capability vLLM's continuous batching provides opaquely
        at the reference's vgate/backends/vllm_backend.py:51)."""
        limit = self.config.tpu.prefill_admit_limit
        decoding = bool(self._running_seqs())
        plans: List[PrefillPlan] = []
        swap_plans: List[SwapInPlan] = []
        start = time.perf_counter()
        while True:
            if decoding and limit and len(plans) + len(swap_plans) >= limit:
                break
            plan = self.scheduler.try_admit()
            if plan is None:
                break
            if isinstance(plan, SwapInPlan):
                swap_plans.append(plan)
            else:
                plans.append(plan)
        for plan in swap_plans:
            # host-swap re-admission: a jitted host->device scatter
            # replaces the re-prefill entirely — zero recompute tokens
            self._dispatch_swap_in(plan)
        if not plans:
            return bool(swap_plans)
        # stale-wake epochs: if a watchdog-declared stall checkpoints
        # (preempt_count bump) and replays these sequences while this
        # thread is stuck in the device_get below, the replay may
        # already be RUNNING again on the NEW core when we wake — a
        # status check alone would pass, so readback also compares the
        # epoch captured here (mirrors the chunked-decode path)
        plan_epochs = {
            id(plan): plan.seq.preempt_count for plan in plans
        }
        if self.flight.enabled:
            for plan in plans:
                seq = plan.seq
                preview = None
                if not self.flight.redact_prompts:
                    try:
                        preview = self.tokenizer.decode(
                            seq.prompt_ids[:32]
                        )
                    except Exception:  # pragma: no cover - defensive
                        preview = None
                self.flight.on_admit(
                    seq, plan.bucket, plan.cached_len, preview=preview
                )
                if seq.trace is not None:
                    seq.trace.end("queue")
                    seq.trace.start(
                        "prefill",
                        bucket=plan.bucket,
                        cached_tokens=plan.cached_len,
                        chunked=plan.chunked,
                    )
        if faults.is_active():
            # fault probe (vgate_tpu/faults.py): payload is the request's
            # ORIGINAL prompt so a poison fault can target one request.
            # Gated so the disarmed hot path never pays the per-plan
            # prompt copy.
            for plan in plans:
                faults.check(
                    "prefill",
                    payload=tuple(
                        plan.seq.prompt_ids[: plan.seq.orig_prompt_len]
                    ),
                )
        # group same-bucket plans into batched dispatches; prefix-cache
        # hits (suffix-only prompt pass) compile a different program and
        # group separately, as do COW hits (unaligned start: the write
        # is a scatter and the suffix table carries an extra column).
        # Chunked plans (prompt > the bucket cap) run serial suffix
        # passes and never batch with others.
        by_bucket: Dict[tuple, List[PrefillPlan]] = {}
        dispatched = []  # (group plans, [B] device tokens)
        for plan in plans:
            if plan.chunked:
                dispatched.append(
                    ([plan], self._dispatch_chunked_prefill(plan))
                )
                continue
            key = (
                plan.bucket,
                plan.cached_len > 0,
                plan.cached_len % self.geometry.page_size != 0,
            )
            by_bucket.setdefault(key, []).append(plan)
        batch_max = max(1, self.config.tpu.prefill_batch_max)
        for (bucket, cached, unaligned), group in sorted(by_bucket.items()):
            for i in range(0, len(group), batch_max):
                chunk = group[i : i + batch_max]
                if cached:
                    handle = self._dispatch_suffix_group(
                        chunk, bucket, unaligned=unaligned
                    )
                else:
                    handle = self._dispatch_prefill_group(chunk, bucket)
                dispatched.append((chunk, handle))
        # index the freshly-filled prompt pages only now, with every
        # writer program enqueued: a reader admitted in a LATER tick is
        # guaranteed to dispatch after the writer (device program order).
        # A sequence a watchdog containment checkpointed mid-dispatch
        # (its pages are already released) must not be indexed — the
        # epoch guard mirrors the readback one below.
        for plan in plans:
            stale = (
                plan.seq.status is not SeqStatus.RUNNING
                or plan.seq.preempt_count != plan_epochs[id(plan)]
            )
            self.scheduler.commit_prefill(plan, stale=stale)
        self._beat("prefill_readback", batch=len(plans))
        # the perf split of the one existing sync (see _process_chunks):
        # wait-for-compute (device_s), then the device_get transfer
        # (readback_s)
        readback_t0 = time.perf_counter()
        handles = [h for _, h in dispatched]
        jax.block_until_ready(handles)
        device_s = time.perf_counter() - readback_t0
        firsts = jax.device_get(handles)  # [(tok, lp)]
        readback_s = time.perf_counter() - readback_t0 - device_s
        self.perf.phase("device", device_s)
        self.perf.phase("readback", readback_s)
        # batched admission costs one combined dispatch+readback; attribute
        # an equal share to each prefill so observation count stays
        # one-per-prefill and the histogram sum stays the true wall time
        share = (time.perf_counter() - start) / len(plans)
        for plan in plans:
            metrics.observe_with_exemplar(
                metrics.ENGINE_STEP_TIME.labels(kind="prefill"),
                share,
                trace_id=getattr(plan.seq.trace, "trace_id", None),
            )
        detok_t0 = time.perf_counter()
        delivered = 0
        for (group, _), (tokens, lp) in zip(dispatched, firsts):
            self.flight.record_tick(
                "prefill",
                batch=len(group),
                bucket=group[0].bucket,
                step_s=round(share * len(group), 6),
                device_s=round(
                    device_s * len(group) / len(plans), 6
                ),
                readback_s=round(
                    readback_s * len(group) / len(plans), 6
                ),
                kv_used=self.allocator.num_used,
                kv_free=self.allocator.num_free,
                queue_depth=len(self.scheduler.waiting),
            )
            arr = np.asarray(tokens)
            # append under the readback lock (device waits all happened
            # above): the stale-wake guard is check-then-append, and a
            # watchdog containment folding these sequences mid-loop
            # would otherwise interleave with the appends
            with self._readback_lock:
                for row, plan in enumerate(group):
                    # stale-wake guard: a watchdog-declared stall may
                    # have checkpointed this sequence while the
                    # readback above was stuck — appending its token
                    # now would corrupt the replay (which may already
                    # be RUNNING on the rebuilt core, hence the epoch
                    # check, not just status)
                    if (
                        plan.seq.status is not SeqStatus.RUNNING
                        or plan.seq.preempt_count
                        != plan_epochs[id(plan)]
                    ):
                        continue
                    token = int(arr[row])
                    self.total_prefills += 1
                    if lp is not None and plan.seq.params.logprobs:
                        self._attach_logprob(plan.seq, lp, 0, row)
                    # a RE-prefill (post-preemption) keeps the original
                    # first_token_t; its phase boundary is NOW, not the
                    # first incarnation's first token
                    fresh_first = plan.seq.first_token_t is None
                    plan.seq.append_token(token)
                    delivered += 1
                    self.flight.on_first_token(plan.seq)
                    tr = plan.seq.trace
                    if tr is not None:
                        boundary = (
                            plan.seq.first_token_t
                            if fresh_first
                            else time.perf_counter()
                        )
                        tr.end("prefill", end_pc=boundary)
                        tr.start("decode", start_pc=boundary)
                    self._maybe_finish(plan.seq, token)
        self.perf.phase("detok", time.perf_counter() - detok_t0)
        self.perf.note_tokens(delivered)
        return True

    @engine_thread_only
    def _dispatch_swap_in(self, plan: SwapInPlan) -> None:
        """Re-admit a host-swapped preemption victim: scatter its
        parked KV into the freshly-allocated ``seq.pages``
        (runtime/kv_swap.py) and let it rejoin decode at the exact
        position it stopped — token-identical, no prefill program, no
        first-token readback (its last sampled token is the next
        decode feed; ``_build_decode_state`` re-uploads it when the
        membership signature changes this tick)."""
        seq = plan.seq
        t0 = time.perf_counter()
        self._beat("swap_in", batch=1)
        n = self.kv_swap.swap_in_seq(seq, seq.pages)
        if self.flight.enabled:
            self.flight.on_admit(
                seq, bucket=0, cached_len=seq.total_len - 1
            )
            # the sequence is mid-decode, not prefilling: flip the
            # phase record straight to decode
            self.flight.on_first_token(seq)
            if seq.trace is not None:
                seq.trace.end("queue")
                seq.trace.start("decode", swapped_in_pages=n)
        self.flight.record_tick(
            "swap_in",
            batch=1,
            pages=n,
            step_s=round(time.perf_counter() - t0, 6),
            kv_used=self.allocator.num_used,
            kv_free=self.allocator.num_free,
            queue_depth=len(self.scheduler.waiting),
            seq_id=seq.seq_id,
            request_id=seq.request_id,
        )

    @engine_thread_only
    def _penalty_arrays(self, B: int, rows):
        """Build (counts [B, V] uint16, freq [B], pres [B]) device arrays
        from ``rows`` = iterable of (row_index, Sequence) — the one
        histogram constructor shared by prefill groups, the decode state
        and the speculative round (callers decide gating/row mapping)."""
        counts = np.zeros((B, self.spec.vocab_size), np.uint16)
        freq = np.zeros((B,), np.float32)
        pres = np.zeros((B,), np.float32)
        for row, seq in rows:
            freq[row] = seq.params.frequency_penalty
            pres[row] = seq.params.presence_penalty
            if seq.generated_ids:
                # histogram over everything generated (generated_ids
                # survives preemption folds, matching OpenAI's "tokens
                # generated so far")
                np.add.at(
                    counts[row], np.asarray(seq.generated_ids, np.int64), 1
                )
        return jnp.asarray(counts), jnp.asarray(freq), jnp.asarray(pres)

    @engine_thread_only
    def _min_token_arrays(self, B: int, rows):
        """(min_toks [B], stop_id_mat [B, K]) device arrays, or
        (None, None) when no row sets min_tokens.  Each row's stop set is
        the model stop set plus its request stop_token_ids; padding uses
        an out-of-vocab id (scatter drops it).  K buckets to a power of
        two so the program-variant count stays bounded."""
        rows = list(rows)
        if not any(seq.params.min_tokens > 0 for _, seq in rows):
            return None, None
        base = [self.tokenizer.eos_id, *self.spec.extra_stop_ids]
        # only floor rows ever have their ids scattered, so only they
        # size K (a zero-floor neighbour with many stop_token_ids must
        # not widen the matrix and fork extra compiled variants)
        per = {
            row: base + list(seq.params.stop_token_ids or [])
            for row, seq in rows
            if seq.params.min_tokens > 0
        }
        K = max(len(v) for v in per.values())
        K = 1 << (max(1, K) - 1).bit_length()
        V = self.spec.vocab_size
        mat = np.full((B, K), V, np.int32)
        min_toks = np.zeros((B,), np.int32)
        for row, seq in rows:
            if row not in per:
                continue  # zero floor: never suppressed, ids irrelevant
            ids = per[row]  # K = next_pow2(max floor-row len)
            mat[row, : len(ids)] = ids
            min_toks[row] = seq.params.min_tokens
        return jnp.asarray(min_toks), jnp.asarray(mat)

    @engine_thread_only
    def _logit_bias_arrays(self, B: int, rows):
        """(bias_ids [B, K] int32, bias_vals [B, K] f32) device arrays,
        or (None, None) when no row carries a logit_bias.  Padding uses
        an out-of-vocab id (scatter-add drops it); K buckets to a power
        of two so the program-variant count stays bounded — the same
        discipline as _min_token_arrays."""
        per = {
            row: seq.params.logit_bias
            for row, seq in rows
            if seq.params.logit_bias
        }
        if not per:
            return None, None
        K = 1 << (max(len(v) for v in per.values()) - 1).bit_length()
        V = self.spec.vocab_size
        ids = np.full((B, K), V, np.int32)
        vals = np.zeros((B, K), np.float32)
        for row, items in per.items():
            for j, (tid, b) in enumerate(sorted(items.items())):
                ids[row, j] = tid
                vals[row, j] = b
        return jnp.asarray(ids), jnp.asarray(vals)

    @engine_thread_only
    def _group_penalties(self, plans: List[PrefillPlan], B: int):
        """Penalty arrays for a prefill group, or (None, None, None).
        Counts only matter when a penalized plan already generated tokens
        (post-preemption re-prefill) — an all-zero histogram is a
        mathematical no-op, so fresh prompts skip the upload and the
        counts program variant entirely."""
        if not any(
            p.seq.params.has_penalties and p.seq.generated_ids
            for p in plans
        ):
            return None, None, None
        return self._penalty_arrays(
            B, ((row, p.seq) for row, p in enumerate(plans))
        )

    @engine_thread_only
    def _dispatch_prefill_group(self, plans: List[PrefillPlan], bucket: int):
        """Launch ONE prefill program for up to prefill_batch_max same-
        bucket sequences; returns the (async) [B] first-token device array.
        B pads to a power of two so the compile ladder stays small
        ({1,2,4,...,prefill_batch_max} x buckets); padding rows use trash
        page tables, temp 0 and seq_len 1 — their sampled tokens are
        discarded at readback."""
        n = len(plans)
        B = 1 << (n - 1).bit_length()  # next power of two
        ps = self.geometry.page_size
        n_bucket_pages = bucket // ps
        tokens = np.zeros((B, bucket), np.int32)
        seq_lens = np.ones((B,), np.int32)
        prefill_pt = np.zeros((B, n_bucket_pages), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        steps = np.zeros((B,), np.int32)
        for row, plan in enumerate(plans):
            seq = plan.seq
            n_prompt = seq.num_prompt_tokens
            tokens[row, :n_prompt] = seq.prompt_ids
            seq_lens[row] = n_prompt
            # decode-side page table row: real pages then trash padding
            slot_row = self._page_tables_np[plan.slot]
            slot_row[:] = 0
            slot_row[: len(seq.pages)] = seq.pages
            prefill_pt[row, : len(seq.pages)] = seq.pages[:n_bucket_pages]
            sp = seq.params
            temps[row] = sp.temperature
            top_ps[row] = sp.top_p
            top_ks[row] = sp.top_k
            if sp.seed is not None:
                # token i always draws from (seed, i): the prefill samples
                # token index num_generated (0 fresh, >0 after preemption)
                seeds[row] = sp.seed
            steps[row] = seq.num_generated
        pen_counts, pen_freq, pen_pres = self._group_penalties(plans, B)
        mt, mt_ids = self._min_token_arrays(
            B, ((row, p.seq) for row, p in enumerate(plans))
        )
        lb_ids, lb_vals = self._logit_bias_arrays(
            B, ((row, p.seq) for row, p in enumerate(plans))
        )
        num_lp = (
            LOGPROBS_K
            if any(p.seq.params.logprobs for p in plans)
            else 0
        )
        key = (
            bucket, B, pen_counts is not None,
            None if mt is None else mt_ids.shape[1], num_lp,
            None if lb_ids is None else lb_ids.shape[1],
        )
        fresh = key not in self._compiled_buckets
        if fresh:
            metrics.RECOMPILES.labels(kind="prefill").inc()
            self._compiled_buckets.add(key)
            self.flight.record_tick(
                "recompile", program="prefill", bucket=bucket, batch=B
            )
            for plan in plans:
                if plan.seq.trace is not None:
                    plan.seq.trace.event("xla_compile", bucket=bucket)
        self._beat("prefill", compiling=fresh, bucket=bucket, batch=B)
        dispatch_t0 = time.perf_counter()
        out, self.k_pages, self.v_pages = _prefill_step(
            self.params,
            self.spec,
            jnp.asarray(tokens),
            jnp.asarray(seq_lens),
            self.k_pages,
            self.v_pages,
            jnp.asarray(prefill_pt),
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            self._step_key(),
            mesh=self._attn_mesh,
            use_pallas=self.use_pallas,
            seeds=jnp.asarray(seeds),
            steps=jnp.asarray(steps),
            num_logprobs=num_lp,
            counts=pen_counts,
            freq_pens=pen_freq,
            pres_pens=pen_pres,
            min_toks=mt,
            stop_id_mat=mt_ids,
            kv_carry=self._kv_carry,
            bias_ids=lb_ids,
            bias_vals=lb_vals,
        )
        dispatch_s = time.perf_counter() - dispatch_t0
        self.perf.phase("dispatch", dispatch_s)
        if fresh:
            self.perf.record_compile(
                "prefill", key, dispatch_s, trigger="bucket"
            )
        return out  # (first tokens [B], logprob triple or None)

    @staticmethod
    @engine_thread_only
    def _suffix_key(
        bucket, B, ctx_pages, has_pen, mt_width, num_lp, lb_width,
        unaligned=False,
    ):
        """Compile-variant key for one _suffix_prefill_step shape — the
        single definition both the batched suffix-group dispatch and
        the chunked-prefill loop count RECOMPILES against."""
        return (
            "suffix", bucket, B, ctx_pages, has_pen, mt_width, num_lp,
            lb_width, unaligned,
        )

    @engine_thread_only
    def _dispatch_suffix_group(
        self, plans: List[PrefillPlan], bucket: int, unaligned: bool = False
    ):
        """Launch ONE suffix-prefill program for up to prefill_batch_max
        prefix-cache hits whose suffix lengths share a bucket.  The cached
        prefix pages are read-only shared KV; only the suffix pages are
        written.  ``unaligned`` is the COW group: each plan's page copy
        is dispatched first (device program order guarantees the copy
        reads the source before any later program could reuse it), the
        suffix then starts mid-page and the suffix table carries one
        extra column.  Returns the (async) [B] first-token device array."""
        n = len(plans)
        B = 1 << (n - 1).bit_length()
        ps = self.geometry.page_size
        n_suffix_pages = bucket // ps + (1 if unaligned else 0)
        # copy-on-write: duplicate the shared head of each diverging
        # page into the sequence's own first page BEFORE the suffix
        # program that writes the rest of that page
        for plan in plans:
            if plan.cow is not None:
                src, dst, upto = plan.cow
                self.k_pages, self.v_pages = _cow_copy_pages(
                    self.k_pages, self.v_pages,
                    jnp.asarray(src, jnp.int32),
                    jnp.asarray(dst, jnp.int32),
                    jnp.asarray(upto, jnp.int32),
                )
                if self.radix_cache is not None:
                    self.radix_cache.total_cow_copies += 1
                metrics.PREFIX_COW_COPIES.inc()
        # context window bucketed to a power of two of pages: bounds both
        # the KV gather and the compile-variant count
        max_ctx_pages = max(
            cdiv(p.seq.num_prompt_tokens, ps) for p in plans
        )
        ctx_pages = min(
            self.geometry.pages_per_seq,
            1 << max(0, max_ctx_pages - 1).bit_length(),
        )
        tokens = np.zeros((B, bucket), np.int32)
        prefix_lens = np.zeros((B,), np.int32)
        suffix_lens = np.ones((B,), np.int32)
        suffix_pt = np.zeros((B, n_suffix_pages), np.int32)
        full_pt = np.zeros((B, ctx_pages), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        steps = np.zeros((B,), np.int32)
        for row, plan in enumerate(plans):
            seq = plan.seq
            cached_pages = plan.cached_len // ps
            suffix = seq.prompt_ids[plan.cached_len :]
            tokens[row, : len(suffix)] = suffix
            prefix_lens[row] = plan.cached_len
            suffix_lens[row] = len(suffix)
            own = seq.pages[cached_pages:]
            suffix_pt[row, : len(own)] = own[:n_suffix_pages]
            slot_row = self._page_tables_np[plan.slot]
            slot_row[:] = 0
            slot_row[: len(seq.pages)] = seq.pages
            full_pt[row, : len(seq.pages)] = seq.pages[:ctx_pages]
            sp = seq.params
            temps[row] = sp.temperature
            top_ps[row] = sp.top_p
            top_ks[row] = sp.top_k
            if sp.seed is not None:
                seeds[row] = sp.seed
            steps[row] = seq.num_generated
        pen_counts, pen_freq, pen_pres = self._group_penalties(plans, B)
        mt, mt_ids = self._min_token_arrays(
            B, ((row, p.seq) for row, p in enumerate(plans))
        )
        lb_ids, lb_vals = self._logit_bias_arrays(
            B, ((row, p.seq) for row, p in enumerate(plans))
        )
        num_lp = (
            LOGPROBS_K
            if any(p.seq.params.logprobs for p in plans)
            else 0
        )
        key = self._suffix_key(
            bucket, B, ctx_pages, pen_counts is not None,
            None if mt is None else mt_ids.shape[1], num_lp,
            None if lb_ids is None else lb_ids.shape[1],
            unaligned=unaligned,
        )
        fresh = key not in self._compiled_buckets
        if fresh:
            metrics.RECOMPILES.labels(kind="prefill").inc()
            self._compiled_buckets.add(key)
            self.flight.record_tick(
                "recompile", program="suffix_prefill", bucket=bucket,
                batch=B,
            )
            for plan in plans:
                if plan.seq.trace is not None:
                    plan.seq.trace.event("xla_compile", bucket=bucket)
        self._beat("prefill", compiling=fresh, bucket=bucket, batch=B)
        dispatch_t0 = time.perf_counter()
        out, self.k_pages, self.v_pages = _suffix_prefill_step(
            self.params,
            self.spec,
            jnp.asarray(tokens),
            jnp.asarray(prefix_lens),
            jnp.asarray(suffix_lens),
            self.k_pages,
            self.v_pages,
            jnp.asarray(suffix_pt),
            jnp.asarray(full_pt),
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            self._step_key(),
            seeds=jnp.asarray(seeds),
            steps=jnp.asarray(steps),
            num_logprobs=num_lp,
            counts=pen_counts,
            freq_pens=pen_freq,
            pres_pens=pen_pres,
            min_toks=mt,
            stop_id_mat=mt_ids,
            kv_carry=self._kv_carry,
            bias_ids=lb_ids,
            bias_vals=lb_vals,
            # the multitok kernel's DMA ranges assume page-aligned
            # starts; COW groups take the blockwise jnp path
            use_pallas=self.use_pallas and not unaligned,
            mesh=self._mt_mesh,
            unaligned=unaligned,
        )
        dispatch_s = time.perf_counter() - dispatch_t0
        self.perf.phase("dispatch", dispatch_s)
        if fresh:
            self.perf.record_compile(
                "suffix_prefill", key, dispatch_s, trigger="bucket"
            )
        return out  # (first tokens [B], logprob triple or None)

    @engine_thread_only
    def _dispatch_chunked_prefill(self, plan: PrefillPlan):
        """Serial chunked prefill for a (suffix-)prompt longer than the
        bucket cap (scheduler.prefill_chunk): page-aligned passes of up
        to ``plan.bucket`` tokens through the suffix-prefill program,
        each attending the full resident context.  Long prompts never
        compile a max_model_len-wide program — an 8k prompt at a 1k cap
        is eight dispatches of the SAME compiled 1k-suffix program.
        Only the final chunk's sampled token is real (earlier chunks'
        samples are discarded); the final chunk carries the request's
        sampling extras.  Returns the (async) ([1] tokens, lp) handle of
        the final chunk."""
        seq = plan.seq
        ps = self.geometry.page_size
        chunk = plan.bucket  # page-aligned (scheduler buckets are)
        total = seq.num_prompt_tokens
        slot_row = self._page_tables_np[plan.slot]
        slot_row[:] = 0
        slot_row[: len(seq.pages)] = seq.pages
        start = plan.cached_len  # page-aligned (full cached pages)
        # non-final chunks: lean suffix dispatches (temp 0, no sampling
        # extras — every sampled token here is discarded)
        while total - start > chunk:
            n = chunk
            start_page = start // ps
            tokens = np.zeros((1, chunk), np.int32)
            tokens[0] = seq.prompt_ids[start : start + n]
            suffix_pt = np.asarray(
                seq.pages[start_page : start_page + chunk // ps],
                np.int32,
            )[None]
            # context window bucketed to the next power of two of pages
            # (bounds compile variants exactly like _dispatch_suffix_group)
            ctx_pages = min(
                self.geometry.pages_per_seq,
                1 << max(0, cdiv(start + n, ps) - 1).bit_length(),
            )
            full_pt = np.zeros((1, ctx_pages), np.int32)
            full_pt[0, : min(len(seq.pages), ctx_pages)] = seq.pages[
                :ctx_pages
            ]
            key = self._suffix_key(
                chunk, 1, ctx_pages, False, None, 0, None
            )
            fresh = key not in self._compiled_buckets
            if fresh:
                metrics.RECOMPILES.labels(kind="prefill").inc()
                self._compiled_buckets.add(key)
            self._beat(
                "prefill_chunk", compiling=fresh, bucket=chunk, batch=1
            )
            dispatch_t0 = time.perf_counter()
            _out, self.k_pages, self.v_pages = _suffix_prefill_step(
                self.params,
                self.spec,
                jnp.asarray(tokens),
                jnp.asarray([start], jnp.int32),
                jnp.asarray([n], jnp.int32),
                self.k_pages,
                self.v_pages,
                jnp.asarray(suffix_pt),
                jnp.asarray(full_pt),
                jnp.zeros((1,), jnp.float32),
                jnp.ones((1,), jnp.float32),
                jnp.zeros((1,), jnp.int32),
                self._step_key(),
                seeds=jnp.full((1,), -1, jnp.int32),
                steps=jnp.zeros((1,), jnp.int32),
                kv_carry=self._kv_carry,
                use_pallas=self.use_pallas,
                mesh=self._mt_mesh,
            )
            dispatch_s = time.perf_counter() - dispatch_t0
            self.perf.phase("dispatch", dispatch_s)
            if fresh:
                self.perf.record_compile(
                    "chunked_prefill", key, dispatch_s,
                    trigger="ctx_width",
                )
            start += n
        # final chunk: exactly a B=1 suffix-group dispatch with
        # cached_len=start — delegate so the full sampling surface
        # (seeds/penalties/min_tokens/logprobs) can never drift from the
        # unchunked path
        final = PrefillPlan(
            seq=seq,
            slot=plan.slot,
            bucket=bucket_for(
                total - start, self.scheduler.prefill_buckets
            ),
            cached_len=start,
            register_hashes=None,
        )
        return self._dispatch_suffix_group([final], final.bucket)

    # ------------------------------------------------------------- decode

    @engine_thread_only
    def _decode_signature(self, seqs: List[Sequence]):
        """Cheap membership signature: when unchanged, every device input
        except tokens/positions/counter (which flow device→device) is
        reusable, so chunks can be dispatched without any host upload.

        ``preempt_count`` is part of the identity: a victim re-admitted
        into the same freed slot with the same page count must NOT match
        the pre-preemption cache — its device tokens/positions are stale
        (the re-prefill's first sampled token was never fed to decode).
        """
        return tuple(
            (seq.seq_id, seq.slot, seq.preempt_count, len(seq.pages))
            for seq in seqs
        )

    @engine_thread_only
    def _build_decode_state(self, seqs: List[Sequence]) -> None:
        self.total_state_rebuilds += 1
        B = self.max_slots
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        steps = np.zeros((B,), np.int32)
        want_pen = any(s.params.has_penalties for s in seqs)
        for seq in seqs:
            slot = seq.slot
            assert slot is not None
            row = self._page_tables_np[slot]
            row[:] = 0
            row[: len(seq.pages)] = seq.pages
            tokens[slot] = seq.output_ids[-1]
            positions[slot] = seq.total_len - 1
            active[slot] = True
            temps[slot] = seq.params.temperature
            top_ps[slot] = seq.params.top_p
            top_ks[slot] = seq.params.top_k
            if seq.params.seed is not None:
                seeds[slot] = seq.params.seed
            steps[slot] = seq.num_generated
        if want_pen:
            counts_j, freq_j, pres_j = self._penalty_arrays(
                B, ((s.slot, s) for s in seqs)
            )
        else:
            counts_j, freq_j, pres_j = None, jnp.zeros((B,)), jnp.zeros((B,))
        mt_j, mt_ids_j = self._min_token_arrays(
            B, ((s.slot, s) for s in seqs)
        )
        lb_j, lb_vals_j = self._logit_bias_arrays(
            B, ((s.slot, s) for s in seqs)
        )
        self._dec_state = {
            "tokens": jnp.asarray(tokens),
            "positions": jnp.asarray(positions),
            "page_tables": jnp.asarray(self._page_tables_np),
            "active": jnp.asarray(active),
            "temps": jnp.asarray(temps),
            "top_ps": jnp.asarray(top_ps),
            "top_ks": jnp.asarray(top_ks),
            "seeds": jnp.asarray(seeds),
            "steps": jnp.asarray(steps),
            "counter": jnp.asarray(self._step_counter, jnp.uint32),
            "counts": counts_j,
            "freq_pens": freq_j,
            "pres_pens": pres_j,
            "min_toks": mt_j,
            "stop_id_mat": mt_ids_j,
            "bias_ids": lb_j,
            "bias_vals": lb_vals_j,
        }

    @engine_thread_only
    def _refresh_page_tables(self, seqs: List[Sequence]) -> None:
        """Re-upload ONLY the page tables after in-place page growth (same
        sequences, same slots).  In-flight chunks keep their older table,
        which is valid: the new page is only addressed at positions those
        chunks never reach."""
        state = self._dec_state
        assert state is not None
        for seq in seqs:
            row = self._page_tables_np[seq.slot]
            row[:] = 0
            row[: len(seq.pages)] = seq.pages
        state["page_tables"] = jnp.asarray(self._page_tables_np)

    @engine_thread_only
    def _pick_chunk(self, active: List[Sequence], lead: int = 0) -> int:
        """Chunk length for the next dispatch: the largest power of two that
        neither exceeds ``decode_chunk`` nor overshoots every sequence's
        remaining budget (``lead`` = steps already in flight but not yet
        folded into host state).  Powers of two bound how many chunk-length
        program variants XLA ever compiles.

        Admission pressure: when prompts are WAITING and a free slot
        exists, the chunk caps at decode_chunk/8 so the loop returns to
        admission within a fraction of a full chunk — a mid-serving
        arrival's TTFT is then bounded by a short chunk, not up to
        ``decode_pipeline`` full ones.  With no free slot (or an empty
        queue) full-size chunks keep throughput maximal."""
        max_len = self.config.model.max_model_len
        headroom = 0
        for seq in active:
            rem_tokens = max(1, seq.params.max_tokens) - seq.num_generated
            rem_len = max_len - seq.total_len
            headroom = max(headroom, min(rem_tokens, rem_len) - lead)
        if headroom <= 0:
            # in-flight steps already cover every budget: dispatching more
            # would be pure overshoot (possible only when lead > 0; a
            # sequence with zero remaining budget is finished at readback)
            return 0
        headroom = min(self.decode_chunk, headroom)
        if self.scheduler.has_admissible_waiting():
            headroom = min(headroom, max(1, self.decode_chunk // 8))
        return 1 << (headroom.bit_length() - 1)

    @engine_thread_only
    def _dispatch_chunk(self, active: List[Sequence], chunk: int) -> None:
        faults.check("decode_step")
        state = self._dec_state
        num_lp = (
            LOGPROBS_K
            if any(s.params.logprobs for s in active)
            else 0
        )
        all_greedy = self._all_greedy(active, num_lp)
        chunk_key = (
            chunk,
            state["counts"] is not None,
            None
            if state["min_toks"] is None
            else state["stop_id_mat"].shape[1],
            num_lp,
            all_greedy,
            None
            if state["bias_ids"] is None
            else state["bias_ids"].shape[1],
        )
        fresh = chunk_key not in self._compiled_chunks
        if fresh:
            metrics.RECOMPILES.labels(kind="decode").inc()
            self._compiled_chunks.add(chunk_key)
            self.flight.record_tick(
                "recompile", program="decode", chunk=chunk,
                batch=len(active),
            )
            for seq in active:
                if seq.trace is not None:
                    seq.trace.event("xla_compile", chunk=chunk)
        self._beat(
            "decode", compiling=fresh, chunk=chunk, batch=len(active)
        )
        guard = (
            self.integrity is not None and self.integrity.guard_enabled
        )
        dispatch_t0 = time.perf_counter()
        start = dispatch_t0
        (
            chunk_tokens,
            chunk_lp,
            state["tokens"],
            state["positions"],
            state["counter"],
            state["steps"],
            state["counts"],
            self.k_pages,
            self.v_pages,
            chunk_flags,
        ) = _decode_chunk(
            self.params,
            self.spec,
            state["tokens"],
            state["positions"],
            self.k_pages,
            self.v_pages,
            state["page_tables"],
            state["active"],
            state["temps"],
            state["top_ps"],
            state["top_ks"],
            self._base_key,
            state["counter"],
            num_steps=chunk,
            use_pallas=self.use_pallas,
            max_position=self.config.model.max_model_len - 1,
            seeds=state["seeds"],
            steps=state["steps"],
            mesh=self._attn_mesh,
            num_logprobs=num_lp,
            counts=state["counts"],
            freq_pens=state["freq_pens"],
            pres_pens=state["pres_pens"],
            min_toks=state["min_toks"],
            stop_id_mat=state["stop_id_mat"],
            all_greedy=all_greedy,
            kv_carry=self._kv_carry,
            bias_ids=state["bias_ids"],
            bias_vals=state["bias_vals"],
            guard=guard,
            guard_threshold=(
                self.config.integrity.saturate_threshold if guard else 1.0e4
            ),
        )
        # the jitted-call return is trace+enqueue (dispatch_s); a fresh
        # variant's call also compiles synchronously, so its duration
        # IS the compile cost the ledger records
        dispatch_s = time.perf_counter() - dispatch_t0
        self.perf.phase("dispatch", dispatch_s)
        if fresh:
            self.perf.record_compile(
                "decode", chunk_key, dispatch_s, trigger="chunk_variant"
            )
        self._step_counter += chunk
        # snapshot preempt_count as an epoch: a sequence preempted while
        # this chunk is in flight (and possibly re-admitted before the
        # readback is processed) must NOT receive the stale tokens
        self._pending_chunks.append(
            ([(s, s.preempt_count) for s in active], chunk, chunk_tokens,
             start, chunk_lp, chunk_flags)
        )

    @engine_thread_only
    def _process_chunks(self, drain: bool = False) -> None:
        """Fold the oldest in-flight chunk (all of them when ``drain``) into
        host state: append tokens in order, detect EOS/length stops, discard
        steps past a stop."""
        while self._pending_chunks:
            seqs, chunk, tokens_dev, _start, lp_dev, flags_dev = (
                self._pending_chunks.pop(0)
            )
            # observe only the host-blocking readback time (kind="decode"):
            # dispatch-to-now would double-count deliberate pipeline
            # queueing when more than one chunk is in flight
            self._beat("decode_readback", chunk=chunk, batch=len(seqs))
            block_start = time.perf_counter()
            # perf attribution splits the ONE sync this path already
            # had: block_until_ready is the wait-for-compute share
            # (device_s), the asarray transfers after it (readback_s) —
            # no sync is added the np.asarray would not have paid
            jax.block_until_ready(tokens_dev)
            device_t = time.perf_counter()
            sampled = np.asarray(tokens_dev)  # [chunk, B]
            sampled = faults.corrupt_array("decode_step", sampled)
            lp_np = (
                None
                if lp_dev is None
                else tuple(np.asarray(a) for a in lp_dev)
            )
            block_s = time.perf_counter() - block_start
            device_s = device_t - block_start
            self.perf.phase("device", device_s)
            self.perf.phase("readback", block_s - device_s)
            if self.perf.enabled:
                self.perf.note_decode(
                    steps=chunk,
                    ctx_tokens=sum(s.total_len for s, _ in seqs),
                    device_s=device_s,
                )
            if self.integrity is not None and flags_dev is not None:
                # the flags readback + fault hooks stay OUTSIDE the
                # lock (np.asarray blocks on the device)
                flags_np = np.bitwise_or.reduce(
                    np.asarray(flags_dev), axis=0
                )
                faults.check("logit_corrupt")
                flags_np = faults.corrupt_array(
                    "logit_corrupt", flags_np
                )
            else:
                flags_np = None
            if self.integrity is not None:
                # sentinel scan BEFORE any append/stream — a HARD trip
                # discards this whole chunk (the entry is already
                # popped; containment clears the rest) so no token
                # sampled from corrupt logits ever reaches a client;
                # SOFT trips (entropy collapse) fail only the
                # attributed sequence, whose FAILED status then skips
                # it in the append loop below.  Under _readback_lock
                # like the append loop: the status/epoch snapshot and
                # the fail/residency-release must not interleave with a
                # cross-thread containment fold (watchdog, dp canary)
                # or a sequence could be checkpointed for replay AND
                # settled failed at once.
                with self._readback_lock:
                    live_rows = [
                        (s, s.slot)
                        for s, epoch in seqs
                        if s.status is SeqStatus.RUNNING
                        and s.preempt_count == epoch
                    ]
                    for _kind, seq, soft_exc in (
                        self.integrity.scan_decode(
                            sampled, flags_np, live_rows, chunk
                        )
                    ):
                        self.scheduler.fail_sequence(seq, soft_exc)
            metrics.observe_with_exemplar(
                metrics.ENGINE_STEP_TIME.labels(kind="decode"),
                block_s,
                trace_id=next(
                    (
                        s.trace.trace_id
                        for s, _ in seqs
                        if s.trace is not None and s.trace.trace_id
                    ),
                    None,
                ),
            )
            self.flight.record_tick(
                "decode",
                batch=len(seqs),
                chunk=chunk,
                step_s=round(block_s, 6),
                device_s=round(device_s, 6),
                readback_s=round(block_s - device_s, 6),
                kv_used=self.allocator.num_used,
                kv_free=self.allocator.num_free,
                queue_depth=len(self.scheduler.waiting),
            )
            # append under the readback lock (the blocking np.asarray
            # is above): see _admit_and_prefill — the epoch guard is
            # check-then-append, and containment's fold must not
            # interleave with it
            detok_t0 = time.perf_counter()
            delivered = 0
            with self._readback_lock:
                for seq, epoch in seqs:
                    if (
                        seq.status is not SeqStatus.RUNNING
                        or seq.preempt_count != epoch
                    ):
                        continue  # stopped or preempted since dispatch
                    slot = seq.slot
                    for k in range(chunk):
                        token = int(sampled[k, slot])
                        if lp_np is not None and seq.params.logprobs:
                            self._attach_logprob(seq, lp_np, k, slot)
                        seq.append_token(token)
                        self.total_decode_tokens += 1
                        delivered += 1
                        self._maybe_finish(seq, token)
                        if seq.status is not SeqStatus.RUNNING:
                            break
            self.perf.phase(
                "detok", time.perf_counter() - detok_t0
            )
            self.perf.note_tokens(delivered)
            self.total_steps += chunk
            if not drain:
                break

    # --------------------------------------------------------- speculative

    @engine_thread_only
    def _ngram_drafter(self, seq: Sequence, k: int) -> List[int]:
        from vgate_tpu.runtime.speculative import NgramIndex

        index = getattr(seq, "_ngram_index", None)
        if index is None or index.ngram != self.spec_ngram:
            index = NgramIndex(self.spec_ngram)
            seq._ngram_index = index  # incremental; dies with the seq
        return index.draft(seq.prompt_ids + seq.output_ids, k)

    @engine_thread_only
    def _tick_speculative(self) -> bool:
        """One speculative decode round (tpu.speculative_k > 0): draft up
        to k tokens per greedy sequence from its own history, verify all
        of them in ONE forward, and append the accepted run + the model's
        bonus token.  Per round each sequence advances by 1..k+1 tokens at
        the cost of a single dispatch; with zero drafts the round is
        exactly a decode step (runtime/speculative.py for the contract).

        Host-driven (no device-resident chaining, no chunk pipeline):
        acceptance counts are data-dependent, so positions feed back
        through the host each round.  That trade targets single-stream
        latency on local hardware; high-RTT links prefer chunked decode.
        """
        active = self._running_seqs()
        if not active:
            return False
        S = self.spec_k + 1
        if not self.scheduler.prepare_decode(active, horizon=S):
            return True  # preemption changed membership; retry next tick
        active = self._running_seqs()
        if not active:
            return True
        B = self.max_slots
        max_len = self.config.model.max_model_len
        if (
            self.draft_model is not None
            and self.draft_model.total_draft_calls == 0
        ):
            # the drafter's lazily-jitted scan compiles on its FIRST
            # call (inside the array-build loop below) — beat with the
            # compile grace or the watchdog would judge a multi-minute
            # Mosaic draft compile against step_stall_s and restart-loop
            # a healthy engine through the same compile until DEAD
            self._beat("draft", compiling=True)
        tokens = np.zeros((B, S), np.int32)
        positions0 = np.zeros((B,), np.int32)
        input_lens = np.ones((B,), np.int32)
        active_mask = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        steps = np.zeros((B,), np.int32)
        for seq in active:
            slot = seq.slot
            row = self._page_tables_np[slot]
            row[:] = 0
            row[: len(seq.pages)] = seq.pages
            tokens[slot, 0] = seq.output_ids[-1]
            positions0[slot] = seq.total_len - 1
            active_mask[slot] = True
            temps[slot] = seq.params.temperature
            top_ps[slot] = seq.params.top_p
            top_ks[slot] = seq.params.top_k
            if seq.params.seed is not None:
                seeds[slot] = seq.params.seed
            steps[slot] = seq.num_generated
            # acceptance+bonus never exceeds input_len, so capping the
            # input at the remaining budget/length bounds overshoot
            room = min(
                S,
                max(1, seq.params.max_tokens) - seq.num_generated,
                max_len - seq.total_len + 1,
            )
            if room > 1:
                # greedy AND sampled sequences draft: greedy rows verify
                # by argmax match, sampled rows by rejection sampling
                # (verify_and_sample), both distribution-exact
                draft = self.drafter(seq, room - 1)
                if draft:
                    tokens[slot, 1 : 1 + len(draft)] = draft
                    input_lens[slot] = 1 + len(draft)
        # rounds where little/nothing drafted (non-repetitive text — the
        # n-gram drafter found no match for greedy OR sampled rows) run a
        # narrower program variant — a no-draft round costs a plain
        # decode step, not a k+1-wide verify of nothing.  Widths are
        # powers of two so the variant count stays log2(S), mirroring
        # the decode-chunk ladder.
        S_round = 1 << (max(1, int(input_lens.max())) - 1).bit_length()
        S_round = max(1, min(S, S_round))
        if S_round < S:
            tokens = tokens[:, :S_round]
        # bucket the context window to the live maximum (next power of two
        # in pages): the verify attention gathers the whole passed table
        # width per layer, so slicing it keeps the gather O(context), not
        # O(max_model_len) — at the cost of log2(pages_per_seq) compiled
        # variants
        w_needed = max(len(seq.pages) for seq in active)
        width = self._page_tables_np.shape[1]
        if w_needed < width:
            width = min(width, 1 << (max(1, w_needed) - 1).bit_length())
            width = max(width, w_needed)
        want_pen = any(s.params.has_penalties for s in active)
        if want_pen:
            sig = tuple(
                (s.seq_id, s.slot, s.preempt_count) for s in active
            )
            if self._spec_pen is None or self._spec_pen["sig"] != sig:
                counts_j, freq_j, pres_j = self._penalty_arrays(
                    B, ((s.slot, s) for s in active)
                )
                self._spec_pen = {
                    "sig": sig,
                    "counts": counts_j,
                    "freq": freq_j,
                    "pres": pres_j,
                }
        else:
            self._spec_pen = None
        mt_sig = tuple((s.seq_id, s.slot) for s in active)
        if self._spec_mt is None or self._spec_mt["sig"] != mt_sig:
            mt, mt_ids = self._min_token_arrays(
                B, ((s.slot, s) for s in active)
            )
            lb, lb_vals = self._logit_bias_arrays(
                B, ((s.slot, s) for s in active)
            )
            self._spec_mt = {
                "sig": mt_sig, "mt": mt, "ids": mt_ids,
                "lb": lb, "lb_vals": lb_vals,
            }
        spec_mt = self._spec_mt["mt"]
        spec_mt_ids = self._spec_mt["ids"]
        spec_lb = self._spec_mt["lb"]
        spec_lb_vals = self._spec_mt["lb_vals"]
        faults.check("decode_step")
        # stale-wake epochs for the readback loop below (the verify
        # call + np.asarray block this thread; a stall declared there
        # may checkpoint + replay these sequences)
        spec_epochs = {s.seq_id: s.preempt_count for s in active}
        start = time.perf_counter()
        num_lp = (
            LOGPROBS_K
            if any(s.params.logprobs for s in active)
            else 0
        )
        all_greedy = self._all_greedy(active, num_lp)
        spec_key = (S_round, width, num_lp, all_greedy, want_pen)
        fresh = spec_key not in self._compiled_spec
        self._beat(
            "spec_verify",
            compiling=fresh,
            chunk=S_round,
            batch=len(active),
        )
        self._compiled_spec.add(spec_key)
        dispatch_t0 = time.perf_counter()
        (
            model_toks, accepted, lp_data, counts_out,
            self.k_pages, self.v_pages,
        ) = (
            _spec_verify_step(
                self.params,
                self.spec,
                jnp.asarray(tokens),
                jnp.asarray(positions0),
                jnp.asarray(input_lens),
                self.k_pages,
                self.v_pages,
                jnp.asarray(self._page_tables_np[:, :width]),
                jnp.asarray(active_mask),
                jnp.asarray(temps),
                jnp.asarray(top_ps),
                jnp.asarray(top_ks),
                self._base_key,
                jnp.asarray(self._step_counter, jnp.uint32),
                seeds=jnp.asarray(seeds),
                steps=jnp.asarray(steps),
                use_pallas=self.use_pallas,
                num_logprobs=num_lp,
                counts=(
                    self._spec_pen["counts"] if want_pen else None
                ),
                freq_pens=(
                    self._spec_pen["freq"] if want_pen else None
                ),
                pres_pens=(
                    self._spec_pen["pres"] if want_pen else None
                ),
                min_toks=spec_mt,
                stop_id_mat=spec_mt_ids,
                all_greedy=all_greedy,
                kv_carry=self._kv_carry,
                bias_ids=spec_lb,
                bias_vals=spec_lb_vals,
                mesh=self._mt_mesh,
            )
        )
        dispatch_s = time.perf_counter() - dispatch_t0
        self.perf.phase("dispatch", dispatch_s)
        if fresh:
            self.perf.record_compile(
                "spec_verify", spec_key, dispatch_s,
                trigger="spec_width",
            )
        if want_pen:
            self._spec_pen["counts"] = counts_out
        self._step_counter += 1
        # perf split of the existing sync (see _process_chunks)
        device_t0 = time.perf_counter()
        jax.block_until_ready((model_toks, accepted))
        device_s = time.perf_counter() - device_t0
        toks_np = np.asarray(model_toks)  # [B, S]
        acc_np = np.asarray(accepted)
        lp_np = None
        if lp_data is not None:
            # transpose to step-major so _attach_logprob's [step][slot]
            # indexing applies
            lp_np = (
                np.asarray(lp_data[0]).T,
                np.transpose(np.asarray(lp_data[1]), (1, 0, 2)),
                np.transpose(np.asarray(lp_data[2]), (1, 0, 2)),
            )
        spec_s = time.perf_counter() - start
        readback_s = time.perf_counter() - device_t0 - device_s
        self.perf.phase("device", device_s)
        self.perf.phase("readback", readback_s)
        if self.perf.enabled:
            self.perf.note_decode(
                steps=1,
                ctx_tokens=sum(s.total_len for s in active),
                device_s=device_s,
            )
        metrics.observe_with_exemplar(
            metrics.ENGINE_STEP_TIME.labels(kind="decode"),
            spec_s,
            trace_id=next(
                (
                    s.trace.trace_id
                    for s in active
                    if s.trace is not None and s.trace.trace_id
                ),
                None,
            ),
        )
        self.flight.record_tick(
            "spec_verify",
            batch=len(active),
            chunk=S_round,
            step_s=round(spec_s, 6),
            device_s=round(device_s, 6),
            readback_s=round(readback_s, 6),
            kv_used=self.allocator.num_used,
            kv_free=self.allocator.num_free,
            queue_depth=len(self.scheduler.waiting),
        )
        # append under the readback lock (device waits all happened
        # above): see _admit_and_prefill for the interleaving hazard
        detok_t0 = time.perf_counter()
        delivered = 0
        with self._readback_lock:
            for seq in active:
                # stale-wake guard (see _admit_and_prefill): status AND
                # the epoch captured at dispatch — a watchdog stall
                # during the blocking readback above may have
                # checkpointed + replayed this sequence already
                if (
                    seq.status is not SeqStatus.RUNNING
                    or seq.preempt_count != spec_epochs[seq.seq_id]
                ):
                    continue
                slot = seq.slot
                self.total_spec_drafted += int(input_lens[slot]) - 1
                self.total_spec_accepted += int(acc_np[slot])
                # model_toks[:, j] for j < accepted IS draft j+1;
                # position `accepted` holds the bonus token — one loop
                # covers both
                for j in range(int(acc_np[slot]) + 1):
                    token = int(toks_np[slot, j])
                    if lp_np is not None and seq.params.logprobs:
                        self._attach_logprob(seq, lp_np, j, slot)
                    seq.append_token(token)
                    self.total_decode_tokens += 1
                    delivered += 1
                    self._maybe_finish(seq, token)
                    if seq.status is not SeqStatus.RUNNING:
                        break
        self.perf.phase("detok", time.perf_counter() - detok_t0)
        self.perf.note_tokens(delivered)
        self.total_steps += 1
        return True

    def lp_entry(self, tid: int, lp: float, top) -> Dict[str, Any]:
        """One OpenAI-shape logprob entry for a delivered token."""
        return {
            "token": self.tokenizer.decode([tid]),
            "token_id": tid,
            "logprob": lp,
            "top_logprobs": [
                {
                    "token": self.tokenizer.decode([i]),
                    "token_id": i,
                    "logprob": l,
                }
                for i, l in top
            ],
        }

    def logprob_entries(self, seq: Sequence) -> List[Dict[str, Any]]:
        """OpenAI-shape logprob content for a finished sequence (one entry
        per generated token, aligned with ``generated_ids``)."""
        return [
            self.lp_entry(tid, lp, top)
            for tid, (lp, top) in zip(seq.generated_ids, seq.logprob_data)
        ]

    @engine_thread_only
    def _attach_logprob(self, seq: Sequence, lp_np, k, slot) -> None:
        """Record one delivered token's logprob data from a readback
        triple ``(lp [.., B], top_ids [.., B, K], top_lps [.., B, K])``
        (leading step axis optional — prefill readbacks have none)."""
        lp, tids, tlps = lp_np
        if lp.ndim == 2:  # [chunk, B]
            lp, tids, tlps = lp[k], tids[k], tlps[k]
        n = min(seq.params.top_logprobs, tids.shape[-1])
        seq.logprob_data.append(
            (
                float(lp[slot]),
                [
                    (int(tids[slot, j]), float(tlps[slot, j]))
                    for j in range(n)
                ],
            )
        )

    @engine_thread_only
    def _maybe_finish(self, seq: Sequence, token: int) -> None:
        reason = None
        # min_tokens gates STOP kinds only (device masking already
        # prevents stop tokens; this also holds back stop strings).  The
        # length finishes below must stay live: a floor above the budget
        # would otherwise leave the sequence RUNNING forever with zero
        # decode headroom.
        below_floor = seq.num_generated < seq.params.min_tokens
        if not below_floor:
            if token == self.tokenizer.eos_id or token in self._stop_ids:
                reason = "stop"
            elif (
                seq.params.stop_token_ids
                and token in seq.params.stop_token_ids
            ):
                reason = "stop"
            elif self._hit_stop_string(seq):
                reason = "stop"  # text_override truncated at the match
        if reason is None:
            if seq.num_generated >= max(1, seq.params.max_tokens):
                reason = "length"
            elif seq.total_len >= self.config.model.max_model_len:
                reason = "length"
        if reason is not None:
            self.scheduler.remove(seq)
            seq.finish(reason)

    @engine_thread_only
    def _hit_stop_string(self, seq: Sequence) -> bool:
        """Host-side stop-sequence detection at token readback (the
        reference delegates this to vLLM's ``SamplingParams.stop``,
        vgate/backends/vllm_backend.py:39-46).

        Cheap path first: decode only a tail window of tokens (a stop of L
        chars spans at most L tokens plus the just-appended one) and
        substring-match there; on a hit, decode the full generation once to
        find the earliest match and truncate ``text_override`` before it.
        Decode chunks may overshoot a stop; overshoot tokens remain in
        ``generated_ids`` but never reach the final text.
        """
        stops = seq.params.stop
        if not stops:
            return False
        longest = max(len(s) for s in stops)
        window = min(len(seq.generated_ids), longest + 8)
        tail = self.tokenizer.decode(seq.generated_ids[-window:])
        if not any(s in tail for s in stops):
            return False
        text = self.tokenizer.decode(seq.generated_ids)
        # min_tokens rule: matches ENDING inside the floor are ignored
        # (their stop checks were skipped while below the floor); a match
        # straddling the boundary still stops the sequence and truncates
        # at its start — the floor guarantees GENERATED tokens, not
        # post-truncation text length (vLLM semantics).  floor_chars has
        # the same +-few-chars BPE-boundary fuzz the tail-window check
        # tolerates (decoding a token prefix in isolation can render
        # replacement chars at a split multi-byte glyph).
        floor_chars = 0
        if seq.params.min_tokens > 0:
            floor_chars = len(
                self.tokenizer.decode(
                    seq.generated_ids[: seq.params.min_tokens]
                )
            )
        cuts = []
        for s in stops:
            idx = text.find(s, max(0, floor_chars - len(s) + 1))
            if idx != -1:
                cuts.append(idx)
        if not cuts:
            # tail decode produced chars the full decode doesn't (BPE
            # boundary artifact), or the only matches sit inside the
            # min_tokens floor — not a real stop
            return False
        seq.text_override = text[: min(cuts)]
        return True

    def final_text(self, seq: Sequence) -> str:
        """The request's final text: the stop-truncated override when a stop
        sequence matched, else the full decoded generation."""
        if seq.text_override is not None:
            return seq.text_override
        return self.tokenizer.decode(seq.generated_ids)

    # ------------------------------------------------------------- utilities

    def warmup(self, buckets: Optional[List[int]] = None) -> float:
        """Pre-compile the decode-chunk ladder and the given (default:
        smallest) prefill buckets so first requests don't pay XLA compile
        latency.  The first warmup sequence generates ``2*decode_chunk``
        tokens, which walks the power-of-two chunk descent (K, ..., 2, 1)
        that _pick_chunk produces near a budget boundary.  For the first
        bucket the batched-prefill ladder (B = batch_max, ..., 2, 1) is
        also compiled: each group is submitted as one burst so it admits
        as a single stacked program."""
        start = time.perf_counter()
        was_running = self._running
        if not was_running:
            self.start()
        ladder = SamplingParams(
            max_tokens=max(1, 2 * self.decode_chunk), temperature=0.0
        )
        # the decode-chunk/spec-verify programs split on all_greedy; a
        # second sampled ladder walk compiles those variants so the
        # first temperature>0 request doesn't pay them at serve time
        # (prefill programs don't split, so one bucket walk suffices)
        ladder_sampled = SamplingParams(
            max_tokens=max(1, 2 * self.decode_chunk), temperature=0.7
        )
        single = SamplingParams(max_tokens=1, temperature=0.0)
        buckets = buckets or [self.scheduler.prefill_buckets[0]]
        for i, bucket in enumerate(buckets):
            n = max(1, min(bucket - 1, 8))
            seq = self.submit_tokens([5] * n, ladder if i == 0 else single)
            seq.done_event.wait(timeout=600)
            if i == 0:
                seq = self.submit_tokens([5] * n, ladder_sampled)
                seq.done_event.wait(timeout=600)
                B = max(1, self.config.tpu.prefill_batch_max)
                while B >= 2:
                    group = [
                        self.submit_tokens([5] * n, single)
                        for _ in range(min(B, self.max_slots))
                    ]
                    for g in group:
                        g.done_event.wait(timeout=600)
                    B //= 2
        if self.scheduler.prefill_chunk > 0:
            # chunked prefill compiles suffix programs (one per pow2
            # context width) the bucket walk above never touches; one
            # max-length prompt hits every width so the first long
            # request doesn't pay serial compiles at serve time
            n_long = self.config.model.max_model_len - 2
            if n_long > self.scheduler.prefill_buckets[-1]:
                seq = self.submit_tokens([5] * n_long, single)
                seq.done_event.wait(timeout=600)
        if not was_running:
            self.stop()
        return time.perf_counter() - start

    def capture_profile(
        self, duration_s: float = 1.0, out_dir: Optional[str] = None
    ) -> Dict[str, Any]:
        """Capture a ``jax.profiler`` device trace while serving continues
        (SURVEY.md section 5.1: the reference has request-scoped OTel spans
        but no low-level profiler; on TPU the device timeline — kernel
        times, HBM traffic, infeed stalls — comes from the JAX profiler,
        viewable in TensorBoard/XProf)."""
        out_dir = out_dir or os.path.join(
            tempfile.gettempdir(),
            f"vgt_profile_{int(time.time())}",
        )
        duration_s = max(0.05, min(duration_s, 60.0))
        capture_start = time.time()
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(duration_s)
        finally:
            jax.profiler.stop_trace()
        # count only files this capture wrote (out_dir may be reused)
        n_files = sum(
            1
            for root, _, files in os.walk(out_dir)
            for f in files
            if os.path.getmtime(os.path.join(root, f)) >= capture_start - 1
        )
        result = {
            "trace_dir": out_dir,
            "duration_s": duration_s,
            "files": n_files,
        }
        # link the device-timeline capture to the attribution layer:
        # the flight ring shows WHEN the capture window sat relative to
        # recompiles/sheds, and /debug/perf reports the last capture so
        # operators can line up phase attribution with the XProf trace
        self.flight.record_tick("profile", **result)
        self.perf.note_profile(result)
        return result

    def perf_snapshot(self) -> Dict[str, Any]:
        """The /debug/perf payload (observability/perf.py): per-tick
        phase attribution window, compile ledger, live MFU/roofline
        gauges and the last profile capture."""
        return self.perf.snapshot()

    def set_spec_suspended(self, flag: bool) -> None:
        """Brownout hook (vgate_tpu/admission.py L3): suspend/resume
        speculative decoding without a rebuild.  Safe from any thread —
        the engine loop re-reads the flag every tick and folds any
        in-flight decode chunks before the first spec round."""
        self.spec_suspended = bool(flag)

    def set_prefix_insert_suspended(self, flag: bool) -> None:
        """Brownout hook (vgate_tpu/admission.py L4 "bypass cache
        writes"): stop inserting into the prefix tree, keep serving
        hits — under saturation new cache content mostly evicts warmer
        content, while existing hits still save prefill compute.  Safe
        from any thread (bool stores are atomic under the GIL); carried
        across supervisor rebuilds like spec_suspended."""
        self.prefix_insert_suspended = bool(flag)
        if self.radix_cache is not None:
            self.radix_cache.insert_suspended = bool(flag)
        if self.kv_swap is not None:
            # L4 also stops host-pool DEMOTIONS (a demotion is a cache
            # write) while promotions keep serving — existing warm
            # content saving prefill is exactly what overload needs.
            # Preemption swap-outs are NOT gated: parking client-owed
            # work beats recomputing it at any brownout level.
            self.kv_swap.demote_suspended = bool(flag)

    def pressure_signals(self) -> Dict[str, Any]:
        """Cheap cross-thread gauges for the gateway's admission and
        brownout controllers: plain int/len reads only (atomic enough
        under the GIL for control decisions — no locks, no device
        touches).  ``kv_free_ratio`` counts reclaimable cached pages as
        free (a warm prefix cache must not shed admissions);
        ``kv_truly_free_ratio`` excludes them — the gap between the two
        is the reclaimable cache."""
        total = max(1, self.allocator.num_allocatable)
        swap_block = (
            self.kv_swap.signal_block() if self.kv_swap is not None else {}
        )
        return {
            **swap_block,
            "kv_free_ratio": round(self.allocator.num_free / total, 4),
            "kv_truly_free_ratio": round(
                self.allocator.num_truly_free / total, 4
            ),
            "prefix_cached_ratio": round(
                self.allocator.num_cached / total, 4
            ),
            # capacity identity for admission (auto_token_budget scales
            # the token backlog limit with it) and attribution: int8 KV
            # roughly doubles both vs bf16 at the same HBM budget
            "kv_token_capacity": self.geometry.total_tokens,
            "kv_dtype": self.geometry.kv_dtype,
            "engine_queue_depth": len(self.scheduler.waiting),
            "running": len(self.scheduler.running),
        }

    def device_health(self) -> Dict[str, Any]:
        try:
            device = self.mesh.devices.flat[0]
            value = float(jnp.asarray([1.0]).sum())
            return {
                "alive": value == 1.0,
                "platform": device.platform,
                "device_kind": getattr(device, "device_kind", "unknown"),
                "num_devices": int(self.mesh.devices.size),
            }
        except Exception as exc:  # pragma: no cover
            return {"alive": False, "error": str(exc)}

    def get_stats(self) -> Dict[str, Any]:
        """Engine counters for /stats.  ``steps`` counts *dispatched decode
        steps* (chunk lengths summed, including overshoot steps discarded at
        readback); prefills are reported separately under ``prefills`` and
        per-request token deliveries under ``decode_tokens``."""
        return {
            "scheduler": self.scheduler.get_stats(),
            "steps": self.total_steps,
            "prefills": self.total_prefills,
            "decode_tokens": self.total_decode_tokens,
            "state_rebuilds": self.total_state_rebuilds,
            "flight": self.flight.get_stats(),
            "perf": self.perf.get_stats(),
            "kv_pages_total": self.allocator.num_allocatable,
            "kv_token_capacity": self.geometry.total_tokens,
            # KV storage attribution: drills and bench artifacts read
            # these so every recorded number names its KV config
            "kv_dtype": self.geometry.kv_dtype,
            "kv_page_bytes": self.geometry.page_bytes,
            "model": self.spec.name,
            "mesh": {
                axis: int(size) for axis, size in self.mesh.shape.items()
            },
            "load_time_s": round(self.load_time_s, 2),
            **(
                {"kv_swap": self.kv_swap.get_stats()}
                if self.kv_swap is not None
                else {}
            ),
            **(
                {"integrity": self.integrity.stats()}
                if self.integrity is not None
                else {}
            ),
            **(
                {
                    "speculative": {
                        "k": self.spec_k,
                        "drafter": (
                            f"draft-model:{self.draft_model.spec.name}"
                            if self.draft_model is not None
                            else f"ngram:{self.spec_ngram}"
                        ),
                        **(
                            {
                                "draft_calls":
                                    self.draft_model.total_draft_calls
                            }
                            if self.draft_model is not None
                            else {}
                        ),
                        "drafted": self.total_spec_drafted,
                        "accepted": self.total_spec_accepted,
                        "acceptance_rate": round(
                            self.total_spec_accepted
                            / max(1, self.total_spec_drafted),
                            3,
                        ),
                    }
                }
                if self.spec_k > 0
                else {}
            ),
        }
