"""Per-request sequence state tracked by the continuous-batching scheduler."""

from __future__ import annotations

import enum
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from vgate_tpu.backends.base import SamplingParams

_seq_counter = itertools.count()


class SeqStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    FAILED = "failed"


@dataclass
class Sequence:
    prompt_ids: List[int]
    params: SamplingParams
    seq_id: int = field(default_factory=lambda: next(_seq_counter))
    status: SeqStatus = SeqStatus.WAITING
    # tokens generated since the last (re-)prefill — the decode feed
    output_ids: List[int] = field(default_factory=list)
    # every token ever generated, surviving preemption/recompute — the result
    generated_ids: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    finish_reason: str = "stop"
    error: Optional[BaseException] = None
    # timing
    arrival_t: float = field(default_factory=time.perf_counter)
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    # delivery
    done_event: threading.Event = field(default_factory=threading.Event)
    stream_cb: Optional[Callable[[int], Any]] = None
    preempt_count: int = 0
    orig_prompt_len: int = 0
    # set when a stop string matched: the final text truncated at the match
    # (the raw generated_ids still contain the overshoot tokens)
    text_override: Optional[str] = None
    # per-delivered-token logprob data, aligned with generated_ids (only
    # filled when params.logprobs): (chosen_lp, [(token_id, lp), ...])
    logprob_data: List[tuple] = field(default_factory=list)
    # client-side cancellation (e.g. SSE disconnect): set from ANY
    # thread; the engine thread honors it at its next tick, finishing
    # the sequence with reason "abort" and freeing its slot/pages —
    # the capability vLLM exposes as abort_request, first-party here
    abort_requested: bool = False
    # why the abort was requested — labels the cancellation metric
    # (client_disconnect | drain)
    abort_reason: str = "client_disconnect"
    # absolute perf_counter deadline (arrival_t + params.timeout_s);
    # the engine sheds the sequence between decode ticks once passed
    deadline_t: Optional[float] = None
    # observability identity (observability/reqtrace.py): the gateway's
    # request id, and the per-request phase-span emitter — both optional
    # so direct engine callers (tests, bench drivers) pay nothing
    request_id: Optional[str] = None
    trace: Optional[Any] = None
    # settle observer, invoked exactly once from finish()/fail() — the
    # engine's flight recorder closes the request record here so every
    # settle path (scheduler sheds included) is covered by one hook
    on_settle: Optional[Callable[["Sequence"], Any]] = None
    _settle_notified: bool = False

    def __post_init__(self) -> None:
        if self.orig_prompt_len == 0:
            self.orig_prompt_len = len(self.prompt_ids)
        if self.deadline_t is None and self.params.timeout_s is not None:
            self.deadline_t = self.arrival_t + self.params.timeout_s

    def past_deadline(self, now: Optional[float] = None) -> bool:
        if self.deadline_t is None:
            return False
        return (now if now is not None else time.perf_counter()) >= (
            self.deadline_t
        )

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.generated_ids)

    @property
    def total_len(self) -> int:
        """Tokens whose KV is (or will be) resident."""
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def num_generated(self) -> int:
        """Generated tokens across preemptions (output_ids may have been
        folded into prompt_ids by reset_for_recompute)."""
        return len(self.generated_ids)

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def tpot(self) -> Optional[float]:
        if self.finish_t is None or self.first_token_t is None:
            return None
        n = max(1, self.num_output_tokens - 1)
        return (self.finish_t - self.first_token_t) / n

    def append_token(self, token: int) -> None:
        if self.first_token_t is None:
            self.first_token_t = time.perf_counter()
        self.output_ids.append(token)
        self.generated_ids.append(token)
        if self.stream_cb is not None:
            self.stream_cb(token)

    def request_abort(self, reason: str = "client_disconnect") -> None:
        """Ask the engine to drop this sequence (thread-safe, advisory:
        tokens already in flight may still append before the engine
        processes the abort).  ``reason`` labels the cancellation
        metric: "client_disconnect" (the default) or "drain"."""
        self.abort_reason = reason
        self.abort_requested = True

    def _notify_settle(self) -> None:
        if self._settle_notified or self.on_settle is None:
            return
        self._settle_notified = True
        try:
            self.on_settle(self)
        except Exception:
            pass  # observability must never break delivery

    def finish(self, reason: str) -> None:
        self.status = SeqStatus.FINISHED
        self.finish_reason = reason
        self.finish_t = time.perf_counter()
        self._notify_settle()
        self.done_event.set()

    def fail(self, exc: BaseException) -> None:
        self.status = SeqStatus.FAILED
        self.error = exc
        self.finish_t = time.perf_counter()
        self._notify_settle()
        self.done_event.set()

    def reset_for_recompute(self) -> None:
        """Preemption: drop residency, keep generated tokens in the prompt so
        decode resumes exactly where it stopped after re-prefill."""
        self.prompt_ids = self.prompt_ids + self.output_ids
        self.output_ids = []
        self.pages = []
        self.slot = None
        self.status = SeqStatus.WAITING
        self.preempt_count += 1
