"""Per-request sequence state tracked by the continuous-batching scheduler."""

from __future__ import annotations

import enum
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from vgate_tpu.backends.base import SamplingParams

_seq_counter = itertools.count()


class SeqStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    FAILED = "failed"


@dataclass
class Sequence:
    prompt_ids: List[int]
    params: SamplingParams
    seq_id: int = field(default_factory=lambda: next(_seq_counter))
    status: SeqStatus = SeqStatus.WAITING
    # tokens generated since the last (re-)prefill — the decode feed
    output_ids: List[int] = field(default_factory=list)
    # every token ever generated, surviving preemption/recompute — the result
    generated_ids: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    finish_reason: str = "stop"
    error: Optional[BaseException] = None
    # timing
    arrival_t: float = field(default_factory=time.perf_counter)
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    # delivery
    done_event: threading.Event = field(default_factory=threading.Event)
    stream_cb: Optional[Callable[[int], Any]] = None
    preempt_count: int = 0
    orig_prompt_len: int = 0
    # set when a stop string matched: the final text truncated at the match
    # (the raw generated_ids still contain the overshoot tokens)
    text_override: Optional[str] = None
    # per-delivered-token logprob data, aligned with generated_ids (only
    # filled when params.logprobs): (chosen_lp, [(token_id, lp), ...])
    logprob_data: List[tuple] = field(default_factory=list)
    # client-side cancellation (e.g. SSE disconnect): set from ANY
    # thread; the engine thread honors it at its next tick, finishing
    # the sequence with reason "abort" and freeing its slot/pages —
    # the capability vLLM exposes as abort_request, first-party here
    abort_requested: bool = False
    # why the abort was requested — labels the cancellation metric
    # (client_disconnect | drain)
    abort_reason: str = "client_disconnect"
    # absolute perf_counter deadline (arrival_t + params.timeout_s);
    # the engine sheds the sequence between decode ticks once passed
    deadline_t: Optional[float] = None
    # observability identity (observability/reqtrace.py): the gateway's
    # request id, and the per-request phase-span emitter — both optional
    # so direct engine callers (tests, bench drivers) pay nothing
    request_id: Optional[str] = None
    trace: Optional[Any] = None
    # settle observer, invoked exactly once from finish()/fail() — the
    # engine's flight recorder closes the request record here so every
    # settle path (scheduler sheds included) is covered by one hook
    on_settle: Optional[Callable[["Sequence"], Any]] = None
    _settle_notified: bool = False
    # engine restarts this sequence was checkpointed across and replayed
    # into the rebuilt core (crash, poison sweep, or watchdog stall).
    # recovery.max_resume_attempts caps it; >0 marks the final result
    # `resumed` so clients can see the latency blip's cause.
    resume_count: int = 0
    # PLANNED movements (replica drain, hot-replica rebalance, dp
    # scale-down) this sequence rode — the operational twin of
    # resume_count, counted separately because a migration is not a
    # failure: it never spends the crash-resume budget
    # (recovery.max_resume_attempts) and surfaces as `migrated`, not
    # `resumed`, on the final result.
    migrate_count: int = 0
    # KV storage format the generated prefix was sampled under, stamped
    # by fatal containment when the sequence is checkpointed (engine
    # geometry.kv_dtype — "bf16"/"f32"/"int8").  submit_existing on the
    # replay target refuses a mismatch: continuing an int8-sampled
    # prefix against a bf16 pool (or vice versa) would splice two
    # numerically different streams mid-generation.
    kv_dtype: Optional[str] = None
    # times this sequence's KV was parked in the host swap pool at
    # preemption (runtime/kv_swap.py) instead of being recomputed —
    # the operational twin of preempt_count for the swap tier.  The
    # live ticket itself rides on the private `_swap_ticket` attribute
    # (manager-owned; validity is epoch-guarded by preempt_count).
    swap_count: int = 0
    # Disaggregated prefill→decode handoff (pod.roles; runtime/
    # handoff.py).  handoff_requested is the submit-time wire flag: the
    # engine stages the sequence's KV for transfer once the first token
    # exists (then clears the flag).  handoff_count is bumped by the
    # GATEWAY when a decode worker accepts the transfer; >0 surfaces as
    # `disaggregated` on the final result.  The engine-side hold marker
    # rides on the private `_handoff_hold` attribute (scheduler-owned).
    handoff_requested: bool = False
    handoff_count: int = 0
    # integrity canary self-probe (vgate_tpu/integrity.py): ranks ahead
    # of client traffic at admission (a probe stuck behind a deep queue
    # can't verify anything in time) and is NEVER checkpointed/replayed
    # or counted as a poison suspect — a canary in flight across a
    # crash is simply failed; its keeper re-probes the rebuilt core.
    canary: bool = False

    def __post_init__(self) -> None:
        if self.orig_prompt_len == 0:
            self.orig_prompt_len = len(self.prompt_ids)
        if self.deadline_t is None and self.params.timeout_s is not None:
            self.deadline_t = self.arrival_t + self.params.timeout_s

    def past_deadline(self, now: Optional[float] = None) -> bool:
        if self.deadline_t is None:
            return False
        return (now if now is not None else time.perf_counter()) >= (
            self.deadline_t
        )

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.generated_ids)

    @property
    def total_len(self) -> int:
        """Tokens whose KV is (or will be) resident."""
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def num_generated(self) -> int:
        """Generated tokens across preemptions (output_ids may have been
        folded into prompt_ids by reset_for_recompute)."""
        return len(self.generated_ids)

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def tpot(self) -> Optional[float]:
        if self.finish_t is None or self.first_token_t is None:
            return None
        n = max(1, self.num_output_tokens - 1)
        return (self.finish_t - self.first_token_t) / n

    def append_token(self, token: int) -> None:
        if self.first_token_t is None:
            self.first_token_t = time.perf_counter()
        self.output_ids.append(token)
        self.generated_ids.append(token)
        if self.stream_cb is not None:
            self.stream_cb(token)

    def request_abort(self, reason: str = "client_disconnect") -> None:
        """Ask the engine to drop this sequence (thread-safe, advisory:
        tokens already in flight may still append before the engine
        processes the abort).  ``reason`` labels the cancellation
        metric: "client_disconnect" (the default) or "drain"."""
        self.abort_reason = reason
        self.abort_requested = True

    def _notify_settle(self) -> None:
        if self._settle_notified or self.on_settle is None:
            return
        self._settle_notified = True
        try:
            self.on_settle(self)
        except Exception:
            pass  # observability must never break delivery

    def finish(self, reason: str) -> None:
        self.status = SeqStatus.FINISHED
        self.finish_reason = reason
        self.finish_t = time.perf_counter()
        self._notify_settle()
        self.done_event.set()

    def fail(self, exc: BaseException) -> None:
        self.status = SeqStatus.FAILED
        self.error = exc
        self.finish_t = time.perf_counter()
        self._notify_settle()
        self.done_event.set()

    def reset_for_recompute(self) -> None:
        """Preemption: drop residency, keep generated tokens in the prompt so
        decode resumes exactly where it stopped after re-prefill."""
        self.prompt_ids = self.prompt_ids + self.output_ids
        self.output_ids = []
        self.pages = []
        self.slot = None
        self.status = SeqStatus.WAITING
        self.preempt_count += 1

    def reset_for_swap(self) -> None:
        """Preemption with the KV parked in the host swap pool
        (runtime/kv_swap.py): drop residency but keep the prompt/output
        split intact — re-admission scatters the saved pages back and
        decode resumes at the same position with ZERO recompute.  The
        preempt_count bump is still the staleness epoch: in-flight
        chunk readbacks discard this sequence's late tokens, and the
        swap ticket (stamped with the post-bump epoch) goes stale if
        anything else folds the sequence before re-admission."""
        self.pages = []
        self.slot = None
        self.status = SeqStatus.WAITING
        self.preempt_count += 1

    def checkpoint_summary(self) -> dict:
        """The loggable fields of :meth:`checkpoint` WITHOUT
        materializing the token-list copies — containment-path
        introspection (supervisor last_resume) runs exactly when the
        process may be dying of memory pressure, and only ever reads
        counts.  Must mirror SequenceCheckpoint.as_dict (pinned by
        tests/test_resume.py)."""
        return {
            "seq_id": self.seq_id,
            "request_id": self.request_id,
            "trace_id": getattr(self.trace, "trace_id", None),
            "prompt_tokens": self.orig_prompt_len,
            "generated_tokens": len(self.generated_ids),
            "resume_count": self.resume_count,
            "migrate_count": self.migrate_count,
            "swap_count": self.swap_count,
            "deadline_t": self.deadline_t,
            "kv_dtype": self.kv_dtype,
        }

    def resume_metrics(self) -> dict:
        """The `resumed`/`migrated` entries for a result's metrics dict
        (empty when the generation rode neither a restart nor a planned
        migration) — one definition for every result-assembly site
        (engine, supervisor, dp router, backend); the batcher lifts
        them to the response's `resumed`/`migrated` flags."""
        out: dict = {}
        if self.resume_count:
            out["resumed"] = float(self.resume_count)
        if self.migrate_count:
            out["migrated"] = float(self.migrate_count)
        if self.handoff_count:
            out["disaggregated"] = float(self.handoff_count)
        return out

    def checkpoint(self) -> "SequenceCheckpoint":
        """Snapshot this sequence's resumable state (engine crash/stall
        containment).  Pure data — safe to log, introspect via /stats,
        or rebuild a sequence from (:meth:`Sequence.from_checkpoint`)."""
        return SequenceCheckpoint(
            prompt_ids=list(self.prompt_ids[: self.orig_prompt_len]),
            generated_ids=list(self.generated_ids),
            params=self.params,
            seq_id=self.seq_id,
            arrival_t=self.arrival_t,
            deadline_t=self.deadline_t,
            first_token_t=self.first_token_t,
            preempt_count=self.preempt_count,
            resume_count=self.resume_count,
            migrate_count=self.migrate_count,
            swap_count=self.swap_count,
            request_id=self.request_id,
            trace_id=getattr(self.trace, "trace_id", None),
            kv_dtype=self.kv_dtype,
        )

    @classmethod
    def from_checkpoint(cls, cp: "SequenceCheckpoint") -> "Sequence":
        """Rebuild a WAITING prefill-continue sequence from a checkpoint:
        the partial generation folds into the prompt (exactly like
        preemption's recompute), so after re-prefill decode resumes at
        the next position.  Delivery plumbing (done_event, stream_cb,
        on_settle) is fresh — the live replay path mutates the original
        object via :meth:`prepare_resume` instead, so the client keeps
        its future; this constructor serves tests and any out-of-process
        resume."""
        seq = cls(
            prompt_ids=list(cp.prompt_ids) + list(cp.generated_ids),
            params=cp.params,
            seq_id=cp.seq_id,
            generated_ids=list(cp.generated_ids),
            arrival_t=cp.arrival_t,
            first_token_t=cp.first_token_t,
            orig_prompt_len=len(cp.prompt_ids),
            preempt_count=cp.preempt_count,
            resume_count=cp.resume_count + 1,
            migrate_count=cp.migrate_count,
            swap_count=cp.swap_count,
            request_id=cp.request_id,
            kv_dtype=cp.kv_dtype,
        )
        # absolute deadline survives verbatim: the replay runs on the
        # request's ORIGINAL budget, not a fresh one
        seq.deadline_t = cp.deadline_t
        return seq

    def _fold_for_replay(self) -> None:
        """Shared checkpoint fold behind :meth:`prepare_resume` (crash/
        stall containment) and :meth:`prepare_migrate` (planned
        movement): fold the generation into the prompt
        (prefill-continue) and return to WAITING so the replayer can
        re-submit this very object — every external reference
        (done_event waiter, stream_cb, cancel-token abort hooks,
        deadline) stays valid.  The preempt_count bump doubles as the
        staleness epoch: an engine thread with this sequence still in
        flight discards its late readbacks against it."""
        # a handoff hold does not survive a fold: the staged ticket is
        # invalidated by the epoch bump below, and a replayed sequence
        # still marked held would be skipped by admission forever
        if getattr(self, "_handoff_hold", False):
            self._handoff_hold = False
        self.handoff_requested = False
        if self.status is SeqStatus.RUNNING or self.output_ids:
            self.reset_for_recompute()
        else:
            # never admitted (or already folded by preemption): nothing
            # resident to fold — just make the queue state explicit
            self.pages = []
            self.slot = None
            self.status = SeqStatus.WAITING

    def prepare_resume(self) -> None:
        """Engine crash/stall checkpoint, live-object form (see
        :meth:`_fold_for_replay`); counts against
        recovery.max_resume_attempts and marks the result `resumed`."""
        self._fold_for_replay()
        self.resume_count += 1

    def prepare_migrate(self) -> None:
        """PLANNED checkpoint (replica drain / rebalance / scale-down),
        live-object form (see :meth:`_fold_for_replay`).  Deliberately
        does NOT touch resume_count: a migration is an operational
        choice, not a crash, so it must never spend the request's
        crash-resume budget — the result is marked `migrated` instead."""
        self._fold_for_replay()
        self.migrate_count += 1


@dataclass
class SequenceCheckpoint:
    """One in-flight sequence's resumable state, snapshotted by fatal
    containment (crash, poison sweep, watchdog stall) before the engine
    is torn down.  RNG continuation is implicit: sampling derives from
    ``(seed, step=num_generated)`` for seeded requests and the engine
    base key is config-derived, so a restored greedy or seeded sequence
    continues the identical token stream; unseeded temperature>0
    requests resume distribution-correct (not token-identical), exactly
    like a KV-pressure preemption."""

    prompt_ids: List[int]  # the ORIGINAL prompt (pre-fold)
    generated_ids: List[int]  # everything generated so far
    params: SamplingParams
    seq_id: int
    arrival_t: float
    deadline_t: Optional[float]  # absolute: the original budget
    first_token_t: Optional[float]
    preempt_count: int
    resume_count: int
    request_id: Optional[str]
    trace_id: Optional[str]
    # KV storage format the generation ran under (engine
    # geometry.kv_dtype); a replay target with a different format must
    # refuse the checkpoint instead of splicing numerics
    kv_dtype: Optional[str] = None
    # planned movements ridden so far (drain/rebalance/scale-down)
    migrate_count: int = 0
    # host-swap preemptions ridden so far (runtime/kv_swap.py); the
    # parked KV itself never travels in a checkpoint — containment
    # folds a swapped sequence back to the recompute path
    swap_count: int = 0

    def as_dict(self) -> dict:
        """Loggable summary (token *counts*, never token content — the
        prompt may be sensitive; observability.redact_prompts applies
        to previews elsewhere)."""
        return {
            "seq_id": self.seq_id,
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "prompt_tokens": len(self.prompt_ids),
            "generated_tokens": len(self.generated_ids),
            "resume_count": self.resume_count,
            "migrate_count": self.migrate_count,
            "swap_count": self.swap_count,
            "deadline_t": self.deadline_t,
            "kv_dtype": self.kv_dtype,
        }
