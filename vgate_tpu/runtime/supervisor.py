"""Supervised engine recovery + the serving health state machine.

The engine core treats any step-loop exception as terminal: ``_fatal``
is set, every owed future fails, and all later submissions raise until
the process restarts.  The reference V-Gate dodged this by delegating
crash handling to external vLLM/SGLang engines; an in-house TPU engine
must own it (ISSUE 1).  ``EngineSupervisor`` wraps one
:class:`~vgate_tpu.runtime.engine_core.EngineCore` and:

* watches for the fatal state (the core's ``on_fatal`` hook fires from
  the engine thread once the crash is contained);
* classifies the error — **transient** (restart), **poison** (a specific
  request keeps crashing the engine: quarantine it, then restart), or
  **unrecoverable** (straight to ``DEAD``);
* tears the core down and rebuilds it with capped exponential backoff
  and a sliding-window restart budget.  Weights are KEPT (the previous
  incarnation's already-quantized/sharded tree is passed back through
  ``EngineCore(params=..., params_ready=True)`` — no reload, no
  re-quantize); KV pages and scheduler state are rebuilt fresh;
* fails in-flight requests with the retryable
  :class:`~vgate_tpu.errors.EngineRecoveringError` (503 + Retry-After at
  the gateway) and rejects new submissions fast while ``RECOVERING``;
* quarantines suspected poison requests by prompt fingerprint so a
  client retry cannot re-crash the next incarnation.

Health state machine, surfaced through /health (readiness vs liveness
split) and /stats::

    SERVING ──crash──▶ RECOVERING ──restart ok──▶ DEGRADED ──probation──▶ SERVING
       ▲                   │
       └───────────────────┴──budget exhausted / unrecoverable──▶ DEAD

``DEGRADED`` is post-restart probation: the engine serves, but /health
reports the reduced confidence; one crash-free probation window promotes
it back to ``SERVING``.  ``DEAD`` fails the liveness probe so the
orchestrator recycles the pod.

dp == 1 engines only; ``ReplicatedEngine`` (tpu.dp > 1) keeps its own
replica failover and stays unsupervised.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

from vgate_tpu import faults, metrics
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import VGTConfig, get_config
from vgate_tpu.errors import (
    EngineDeadError,
    EngineRecoveringError,
    EngineStalledError,
    IntegrityError,
    MigrationRefusedError,
    PoisonRequestError,
    raise_for_state,
    state_is_alive,
    state_is_ready,
)
from vgate_tpu.analysis.annotations import requires_lock
from vgate_tpu.analysis.witness import named_lock
from vgate_tpu.integrity import CanaryKeeper
from vgate_tpu.logging_config import get_logger
from vgate_tpu.runtime.engine_core import (
    EngineCore,
    rebuild_core,
    replay_into,
)
from vgate_tpu.runtime.sequence import Sequence, SeqStatus

logger = get_logger(__name__)

# Threading contract (scripts/vgt_lint.py, thread-discipline): state
# shared between the watcher thread, canary probe threads, and
# serving-path callers mutates only under the supervisor RLock.
VGT_LOCK_GUARDS = {
    "_state": "_lock",
    "_pending_resume": "_lock",
    "_quarantine": "_lock",
    "_suspect_counts": "_lock",
    "_restart_times": "_lock",
}


class HealthState(enum.Enum):
    SERVING = "serving"
    DEGRADED = "degraded"
    RECOVERING = "recovering"
    DEAD = "dead"


def classify_heartbeat(
    heartbeat: Optional[Dict[str, Any]],
    now: float,
    step_stall_s: float,
    compile_grace_s: float,
) -> Optional[Dict[str, Any]]:
    """Hang-watchdog verdict for one engine heartbeat: ``None`` while
    healthy, else ``{"stalled_s", "limit_s", "phase", "compiling"}``.

    Compile-aware: a beat stamped ``compiling=True`` (first dispatch of
    a program variant — XLA/Mosaic can legitimately pause the loop for
    minutes) is judged against ``compile_grace_s`` instead of
    ``step_stall_s``.  Pure function of (beat, now) so tests drive it
    with fake clocks; ``step_stall_s <= 0`` disables the watchdog."""
    if step_stall_s <= 0 or not heartbeat:
        return None
    limit = (
        compile_grace_s
        if heartbeat.get("compiling")
        else step_stall_s
    )
    stalled_s = now - heartbeat.get("t", now)
    if stalled_s <= limit:
        return None
    return {
        "stalled_s": round(stalled_s, 3),
        "limit_s": limit,
        "phase": heartbeat.get("kind", "unknown"),
        "compiling": bool(heartbeat.get("compiling")),
    }


def restart_budget_remaining(
    restart_times: Seq[float], recovery: Any, now: Optional[float] = None
) -> int:
    """Restarts still available inside the sliding window — the ONE
    formula behind the `restarts_remaining` field in the supervisor's
    and the dp router's /health blocks (they must never diverge from
    the budget the repair loops actually enforce)."""
    now = time.monotonic() if now is None else now
    in_window = sum(
        1 for t in restart_times if now - t < recovery.restart_window_s
    )
    return max(0, recovery.max_restarts - in_window)


def classify_fatal(exc: BaseException) -> str:
    """transient | poison | unrecoverable | corrupt.  Injected faults
    carry their kind (faults.InjectedFault.fault_kind), and
    IntegrityError (sentinel trip / checksum mismatch / canary failure;
    fault_kind = "corrupt") routes to the reload-on-corrupt rebuild —
    a weights-kept restart would preserve the corruption.  Real errors
    default to transient — a restart is cheap relative to killing
    serving, and the restart budget bounds misclassification."""
    kind = getattr(exc, "fault_kind", None)
    if kind in faults.FAULT_KINDS:
        return kind
    if isinstance(exc, MemoryError):
        return "unrecoverable"
    return "transient"


class EngineSupervisor:
    """Owns the live EngineCore and the recovery loop.  Exposes the same
    serving surface the backend drives (submit/generate/stop/stats/...);
    everything not intercepted here delegates to the live core."""

    def __init__(
        self,
        config: Optional[VGTConfig] = None,
        devices: Optional[list] = None,
    ) -> None:
        self.config = config or get_config()
        self._recovery = self.config.recovery
        self._devices = devices
        self._lock = named_lock(
            "EngineSupervisor._lock", reentrant=True
        )
        self._state = HealthState.SERVING
        self._degraded_since: Optional[float] = None
        self._time_in_degraded = 0.0
        self._restart_times: List[float] = []
        self._quarantine: set = set()
        self._suspect_counts: Dict[str, int] = {}
        self._crash_event = threading.Event()
        self._stopping = False
        self._watcher: Optional[threading.Thread] = None
        self.total_crashes = 0
        self.total_restarts = 0
        self.total_stalls = 0
        # in-flight survival accounting (recovery.resume_in_flight):
        # sequences checkpointed at a crash/stall and replayed into the
        # rebuilt core vs given up on (quarantined / max attempts /
        # resubmit failure)
        self.total_resumed = 0
        self.total_lost = 0
        # checkpointed sequences awaiting the rebuilt core; failed with
        # a terminal error if the engine lands DEAD or stop() wins
        self._pending_resume: List[Sequence] = []
        # introspection record of the most recent checkpoint/replay
        # (/stats → engine.supervisor.last_resume): counts + per-seq
        # checkpoint summaries, never token content
        self.last_resume: Optional[Dict[str, Any]] = None
        self.transitions: List[tuple] = []
        self.last_fatal: Optional[str] = None
        # silent-corruption defense (vgate_tpu/integrity.py): canary
        # keeper (pinned greedy probe; first run records, later runs
        # verify), reload accounting, and the quarantined_corrupt mark
        # — True from a corrupt-classified fatal until the post-reload
        # canary passes (readiness stays red the whole time: the state
        # machine holds RECOVERING, so no traffic reaches the suspect
        # core).
        self._integrity_cfg = self.config.integrity
        self._canary: Optional[CanaryKeeper] = (
            CanaryKeeper(self._integrity_cfg)
            if self._integrity_cfg.enabled
            and self._integrity_cfg.canary_enabled
            else None
        )
        self.quarantined_corrupt = False
        self.total_corrupt_reloads = 0
        self.total_canary_failures = 0
        self.last_integrity: Optional[Dict[str, Any]] = None
        self._next_canary_t = (
            time.monotonic() + self._integrity_cfg.canary_interval_s
            if self._canary is not None
            and self._integrity_cfg.canary_interval_s > 0
            else None
        )
        # timer probes run OFF the watcher thread (one at a time): a
        # probe blocking on a wedged core must not suspend the stall
        # watchdog, whose whole job is noticing that wedge
        self._canary_probe: Optional[threading.Thread] = None
        # flight-recorder snapshot of the most recent crash (ticks +
        # in-flight requests at the moment of death) — logged on every
        # crash classification and surfaced via /stats engine.last_crash
        self.last_crash: Optional[Dict[str, Any]] = None
        # first build: construction failures (bad config, weight-load
        # faults) propagate — there is nothing to recover *to* yet
        self.core = EngineCore(self.config, devices=devices)
        self._attach(self.core)
        self._set_state_metric(self._state)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.core.start()
        if (
            self._canary is not None
            and self._integrity_cfg.canary_record_on_start
            and self._canary.expected is None
        ):
            # baseline the fingerprint against the KNOWN-GOOD boot
            # core (fresh from the checkpoint): every later gate then
            # VERIFIES rather than re-records — without this a reload
            # from a corrupt on-disk checkpoint would baseline garbage
            self._canary.check(self.core, context="boot")
        if self._watcher is None:
            self._watcher = threading.Thread(
                target=self._watch_loop, name="vgt-supervisor", daemon=True
            )
            self._watcher.start()

    def stop(self) -> None:
        self._stopping = True
        self._crash_event.set()
        if self._watcher is not None:
            self._watcher.join(timeout=30)
            self._watcher = None
        # checkpointed work that never reached a rebuilt core is still
        # owed an answer (core.stop() covers its own _checkpointed)
        self._fail_pending_resume(
            EngineRecoveringError(
                "engine stopped before the checkpointed request could "
                "be replayed"
            ),
            reason="shutdown",
        )
        self.core.stop()

    # ------------------------------------------------------------ the state

    @property
    def state(self) -> HealthState:
        """Current health state, with the lazy DEGRADED -> SERVING
        promotion: one crash-free probation window restores full
        confidence without a dedicated timer thread."""
        with self._lock:
            if (
                self._state is HealthState.DEGRADED
                and self._degraded_since is not None
                and time.monotonic() - self._degraded_since
                >= self._recovery.degraded_probation_s
            ):
                self._transition(HealthState.SERVING)
            return self._state

    def _transition(self, new: HealthState) -> None:
        with self._lock:
            old = self._state
            if old is new:
                return
            now = time.monotonic()
            if old is HealthState.DEGRADED and self._degraded_since is not None:
                dt = now - self._degraded_since
                self._time_in_degraded += dt
                metrics.TIME_IN_DEGRADED.inc(dt)
                self._degraded_since = None
            if new is HealthState.DEGRADED:
                self._degraded_since = now
            self._state = new
            self.transitions.append((old.value, new.value))
            metrics.STATE_TRANSITIONS.labels(
                from_state=old.value, to_state=new.value
            ).inc()
            self._set_state_metric(new)
        logger.warning(
            "engine health transition",
            extra={"extra_data": {"from": old.value, "to": new.value}},
        )

    @staticmethod
    def _set_state_metric(current: HealthState) -> None:
        for s in HealthState:
            metrics.HEALTH_STATE.labels(state=s.value).set(
                1.0 if s is current else 0.0
            )

    @property
    def retry_after_s(self) -> float:
        """Suggested client backoff: the next restart attempt's backoff
        (plus margin) while recovering, else the floor of 1s."""
        rec = self._recovery
        backoff = min(
            rec.backoff_cap_s,
            rec.backoff_base_s * (2 ** len(self._restart_times)),
        )
        return max(1.0, backoff)

    # ----------------------------------------------------------- recovery

    def _attach(self, core: EngineCore) -> None:
        core.on_fatal = self._on_fatal

    def _on_fatal(self, exc: BaseException) -> None:
        """Runs on the dying engine thread after the crash is contained
        (futures failed, slots cleared): flip to RECOVERING and hand off
        to the watcher thread."""
        with self._lock:
            self.total_crashes += 1
            self.last_fatal = f"{type(exc).__name__}: {exc}"
            if self._state is not HealthState.DEAD:
                self._transition(HealthState.RECOVERING)
        self._crash_event.set()

    def _watch_loop(self) -> None:
        while not self._stopping:
            fired = self._crash_event.wait(timeout=0.25)
            if self._stopping:
                return
            if not fired:
                # idle poll doubles as the hang watchdog: a wedged
                # engine (stuck decode step / Mosaic hang) never raises,
                # so nothing would ever set the crash event — the
                # monitor must declare the fault itself
                self._check_stall()
                # ... and as the slow-timer canary (integrity.
                # canary_interval_s): wrong answers never raise either
                self._maybe_canary()
                continue
            self._crash_event.clear()
            if self.core._fatal is not None:
                try:
                    self._handle_crash()
                except Exception:  # pragma: no cover - defensive
                    logger.error(
                        "supervisor crash handler failed", exc_info=True
                    )
                    self._fail_pending_resume(
                        EngineDeadError(
                            "supervisor crash handler failed; "
                            "in-flight work cannot be replayed"
                        ),
                        reason="resubmit_failed",
                    )
                    self._transition(HealthState.DEAD)

    def _check_stall(self) -> None:
        """Classify the live core's heartbeat; a stale beat becomes an
        EngineStalledError declared through the core's containment, so
        the existing crash path applies: stall → checkpoint → rebuild →
        replay."""
        rec = self._recovery
        core = self.core
        if (
            rec.step_stall_s <= 0
            or core._fatal is not None
            or not core._running
        ):
            return
        verdict = classify_heartbeat(
            getattr(core, "_heartbeat", None),
            time.monotonic(),
            rec.step_stall_s,
            rec.compile_grace_s,
        )
        if verdict is None:
            return
        exc = EngineStalledError(
            "engine heartbeat stale for "
            f"{verdict['stalled_s']:.1f}s (limit "
            f"{verdict['limit_s']:.1f}s) at phase "
            f"{verdict['phase']!r}; declaring the engine wedged",
            stalled_s=verdict["stalled_s"],
            phase=verdict["phase"],
        )
        logger.error(
            "engine stall detected by watchdog",
            extra={"extra_data": verdict},
        )
        if core.declare_stalled(exc):
            self.total_stalls += 1
            metrics.ENGINE_STALLS.inc()

    def _maybe_canary(self) -> None:
        """Slow-timer canary self-probe (integrity.canary_interval_s >
        0): a pinned greedy prompt whose output fingerprint must match
        the recorded one.  A mismatch is a silent-corruption fatal —
        declared through the core's containment (like the stall
        watchdog) so the standard path applies: checkpoint → reload →
        canary → replay.  The probe itself runs on its own thread (a
        probe blocked on a wedged core must not suspend the stall
        watchdog) and only on an IDLE engine: under live traffic the
        sentinels already watch every readback, and a probe queued
        behind a loaded engine would time out and read as corruption."""
        if self._next_canary_t is None or self._canary is None:
            return
        now = time.monotonic()
        if now < self._next_canary_t:
            return
        if self._canary_probe is not None and self._canary_probe.is_alive():
            return  # previous probe still in flight
        self._next_canary_t = now + self._integrity_cfg.canary_interval_s
        if self.state not in (HealthState.SERVING, HealthState.DEGRADED):
            return
        core = self.core
        if core._fatal is not None or not core._running:
            return
        try:
            if core.scheduler.has_work():
                return  # busy: re-probe at the next interval
        except Exception:  # pragma: no cover - mid-rebuild
            return
        self._canary_probe = threading.Thread(
            target=self._run_timer_canary,
            args=(core,),
            name="vgt-canary",
            daemon=True,
        )
        self._canary_probe.start()

    def _run_timer_canary(self, core: EngineCore) -> None:
        result = self._canary.check(core, context="timer")
        self.last_integrity = {"canary": result}
        if result["ok"]:
            return
        self.total_canary_failures += 1
        exc = IntegrityError(
            "slow-timer canary self-probe failed: "
            + str(result.get("error") or "fingerprint mismatch"),
            kind="canary",
            detail={
                k: v for k, v in result.items() if k != "ok"
            },
        )
        core.declare_stalled(exc)

    def _fail_pending_resume(
        self, exc: BaseException, reason: str
    ) -> None:
        with self._lock:
            pending, self._pending_resume = self._pending_resume, []
        for seq in pending:
            self.total_lost += 1
            metrics.LOST_SEQUENCES.labels(reason=reason).inc()
            seq.fail(exc)

    def _sleep(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while not self._stopping and time.monotonic() < deadline:
            time.sleep(min(0.05, deadline - time.monotonic()))

    def _update_quarantine(self, exc: BaseException, kind: str) -> None:
        with self._lock:
            self._update_quarantine_locked(exc, kind)

    @requires_lock("_lock")
    def _update_quarantine_locked(
        self, exc: BaseException, kind: str
    ) -> None:
        # (fingerprint, resume_count) pairs of the residents at death
        suspects = list(self.core._fatal_suspects)
        if kind == "poison":
            # the fault names its victim; fall back to every resident
            # request when it doesn't
            named = getattr(exc, "fingerprint", None)
            for fp in [named] if named else [s[0] for s in suspects]:
                if fp and fp not in self._quarantine:
                    self._quarantine.add(fp)
                    metrics.QUARANTINED_REQUESTS.inc()
                    logger.error(
                        "request quarantined as engine poison",
                        extra={"extra_data": {"fingerprint": fp}},
                    )
            return
        if kind == "corrupt":
            # checksum/canary corruption is the HARDWARE's fault, never
            # the residents': counting those toward a poison streak
            # would quarantine innocent traffic for a flipped bit.  But
            # a SENTINEL trip names the sequences whose logit rows went
            # bad — a prompt that deterministically overflows into NaN
            # logits would otherwise drive an unbounded reload loop
            # (sentinel → reload → client retries → sentinel ...), so
            # the ATTRIBUTED fingerprints run the same repeat-offender
            # streak as transient crashes below.
            attributed = {
                s.get("fingerprint")
                for s in getattr(exc, "sequences", ())
                if s.get("fingerprint")
            }
            if not attributed:
                return
            suspects = [
                (fp, rc) for fp, rc in suspects if fp in attributed
            ]
        elif kind != "transient":
            return
        # transient path: count repeat offenders — a request FRESHLY
        # SUBMITTED into `poison_threshold` consecutive crashes is
        # quarantined.  Only fresh submissions (resume_count == 0)
        # increment the streak: the signal is CLIENT persistence (keep
        # resubmitting the prompt that kills the engine), and with
        # resume_in_flight the engine's own replays put every innocent
        # bystander in flight across consecutive crashes by design —
        # counting those would quarantine all traffic after any two
        # rapid crashes.  A replayed sequence still KEEPS its streak
        # (presence in this crash, no reset); the engine's
        # max_resume_attempts bounds its replays, and the client's
        # retry after that typed 503 is exactly the fresh submission
        # that advances the streak.
        new_counts: Dict[str, int] = {}
        for fp, resume_count in suspects:
            prior = self._suspect_counts.get(fp, 0)
            count = prior + (1 if resume_count == 0 else 0)
            if count >= self._recovery.poison_threshold:
                if fp not in self._quarantine:
                    self._quarantine.add(fp)
                    metrics.QUARANTINED_REQUESTS.inc()
                    logger.error(
                        "repeat-offender request quarantined",
                        extra={
                            "extra_data": {
                                "fingerprint": fp, "crashes": count,
                            }
                        },
                    )
            elif count > 0:
                new_counts[fp] = count
        # requests NOT in this crash reset their streak (consecutive
        # involvement is the poison signal, not lifetime involvement)
        self._suspect_counts = new_counts

    def _handle_crash(self) -> None:
        exc = self.core._fatal
        assert exc is not None
        kind = classify_fatal(exc)
        metrics.ENGINE_CRASHES.labels(kind=kind).inc()
        logger.error(
            "engine crashed; supervisor recovering",
            extra={
                "extra_data": {
                    "kind": kind, "error": f"{type(exc).__name__}: {exc}",
                }
            },
        )
        # post-mortem: dump the dead core's flight recorder (its final
        # tick is the faulting dispatch) as one structured log record,
        # and keep it for /stats → engine.last_crash — the rings
        # themselves die with the core at rebuild
        flight = getattr(self.core, "flight", None)
        if flight is not None:
            # prefer the snapshot the dying engine thread took before
            # containment swept its residents; fall back to a fresh one
            # (still carries the ticks) for cores that died another way
            snapshot = (
                getattr(self.core, "_crash_snapshot", None)
                or flight.crash_snapshot(exc)
            )
            snapshot["classification"] = kind
            self.last_crash = snapshot
            logger.error(
                "engine crash flight-recorder snapshot",
                extra={"extra_data": {"flight": snapshot}},
            )
        # claim the checkpointed in-flight sequences BEFORE the rebuild
        # loop (the old core's stop() would otherwise fail them) and
        # record the snapshot for /stats — counts and token counts only
        with self._lock:
            self._pending_resume.extend(self.core.take_checkpointed())
            # containment may have given up on sequences itself
            # (max_resume_attempts): fold those into the lost total
            self.total_lost += self.core.take_resume_losses()
            if self._pending_resume:
                self.last_resume = {
                    "time": time.time(),
                    "cause": f"{type(exc).__name__}: {exc}",
                    "checkpointed": len(self._pending_resume),
                    "sequences": [
                        s.checkpoint_summary()
                        for s in self._pending_resume
                    ],
                }
        self._update_quarantine(exc, kind)
        if kind == "unrecoverable":
            self._fail_pending_resume(
                EngineDeadError(
                    "engine hit an unrecoverable fault; checkpointed "
                    "in-flight work cannot be replayed"
                ),
                reason="resubmit_failed",
            )
            self._transition(HealthState.DEAD)
            return
        # reload-on-corrupt: a corrupt-classified fatal (sentinel trip,
        # checksum mismatch, canary failure) must NOT keep the old tree
        # — the corruption would ride the weights-kept path into every
        # incarnation.  The replica is marked quarantined_corrupt until
        # its post-reload canary passes; the state machine already
        # holds RECOVERING (readiness red), so no traffic can land on
        # the suspect core meanwhile.
        # (integrity disabled ⇒ corrupt classification is inert and the
        # weights-kept path applies, preserving pre-integrity behavior)
        reload_weights = (
            kind == "corrupt" and self._integrity_cfg.enabled
        )
        if reload_weights:
            self.quarantined_corrupt = True
            metrics.CORRUPT_QUARANTINED.set(1)
            self.last_integrity = {
                "cause": f"{type(exc).__name__}: {exc}",
                "kind": getattr(exc, "integrity_kind", "unknown"),
                "sequences": list(getattr(exc, "sequences", ())),
                "detail": dict(getattr(exc, "detail", {})),
                "time": time.time(),
            }
        rec = self._recovery
        while not self._stopping:
            now = time.monotonic()
            with self._lock:
                self._restart_times = [
                    t for t in self._restart_times
                    if now - t < rec.restart_window_s
                ]
            if len(self._restart_times) >= rec.max_restarts:
                logger.error(
                    "restart budget exhausted; engine is DEAD",
                    extra={
                        "extra_data": {
                            "max_restarts": rec.max_restarts,
                            "window_s": rec.restart_window_s,
                        }
                    },
                )
                self._fail_pending_resume(
                    EngineDeadError(
                        "engine restart budget exhausted; checkpointed "
                        "in-flight work cannot be replayed"
                    ),
                    reason="resubmit_failed",
                )
                self._transition(HealthState.DEAD)
                return
            backoff = min(
                rec.backoff_cap_s,
                rec.backoff_base_s * (2 ** len(self._restart_times)),
            )
            self._sleep(backoff)
            if self._stopping:
                return
            with self._lock:
                self._restart_times.append(time.monotonic())
            try:
                # shared teardown/rebuild sequence (engine_core.
                # rebuild_core): stop, free the dead incarnation's
                # device KV pool before the new one sizes, weights
                # kept (checksum-verified first) or RELOADED for
                # corrupt fatals, brownout spec-suspension carried over
                new_core = rebuild_core(
                    self.core, self.config, self._devices,
                    reload_weights=reload_weights,
                )
            except IntegrityError:
                # the kept tree failed its rebuild-time checksum
                # verification: the crash itself was a symptom of the
                # corruption — escalate this recovery to a full reload
                logger.error(
                    "kept-weights rebuild failed checksum "
                    "verification; escalating to weight reload",
                    exc_info=True,
                )
                self.quarantined_corrupt = True
                metrics.CORRUPT_QUARANTINED.set(1)
                reload_weights = True
                continue  # burns budget via _restart_times; retry
            except Exception:
                logger.error(
                    "engine rebuild attempt failed", exc_info=True
                )
                continue  # burns budget via _restart_times; retry
            self._attach(new_core)
            self.core = new_core
            if self._stopping:
                # stop() raced the rebuild (its join timed out while we
                # were constructing): never start an engine nothing owns
                # (stop() fails the pending-resume sequences)
                new_core.stop()
                return
            if reload_weights:
                # counted per reload REBUILD (not per canary verdict)
                # so health integrity.corrupt_reloads tracks the
                # vgt_corrupt_reloads Prometheus counter exactly
                self.total_corrupt_reloads += 1
            if reload_weights and self._canary is not None:
                # the reloaded core must prove itself BEFORE any work
                # (replays included) lands on it: start, probe, and
                # only a matching canary fingerprint lifts the
                # quarantine.  A failing canary tears this incarnation
                # down and retries the reload — bounded by the same
                # restart budget as any other rebuild.
                new_core.start()
                result = self._canary.check(new_core, context="reload")
                self.last_integrity = dict(
                    self.last_integrity or {}, canary=result
                )
                if not result["ok"]:
                    self.total_canary_failures += 1
                    logger.error(
                        "post-reload canary FAILED; tearing the "
                        "incarnation down and retrying the reload",
                        extra={"extra_data": result},
                    )
                    new_core.stop()
                    continue
                self.quarantined_corrupt = False
                metrics.CORRUPT_QUARANTINED.set(0)
                self._replay(new_core)
            else:
                if reload_weights:
                    # canary disabled: trust the fresh load
                    self.quarantined_corrupt = False
                    metrics.CORRUPT_QUARANTINED.set(0)
                # replay checkpointed in-flight work into the rebuilt
                # core BEFORE it starts: the first tick then admits the
                # replays ahead of (racing) fresh client traffic
                self._replay(new_core)
                new_core.start()
            self.total_restarts += 1
            metrics.ENGINE_RESTARTS.inc()
            self._transition(HealthState.DEGRADED)
            logger.warning(
                "engine restarted",
                extra={
                    "extra_data": {
                        "restarts": self.total_restarts,
                        "backoff_s": backoff,
                        **(
                            {"weights_reloaded": True}
                            if reload_weights
                            else {}
                        ),
                    }
                },
            )
            return

    def _replay(self, core: Any) -> None:
        """Re-submit the checkpointed in-flight sequences into a rebuilt
        core as prefill-continues (prepare_resume already folded each
        partial generation into its prompt).  Quarantined fingerprints
        are excluded — a poison request must not ride the replay path
        back into the engine it keeps crashing; deadlines stay anchored
        (absolute deadline_t survives the checkpoint), so a blown
        budget sheds with the normal 504 + partials on the new core.
        ``core`` only needs submit_existing + flight, so tests drive
        this with fakes."""
        with self._lock:
            pending, self._pending_resume = self._pending_resume, []
        replayed = 0
        for seq in pending:
            outcome = replay_into(
                core, seq, self._quarantine,
                retry_after=self.retry_after_s,
            )
            if outcome == "replayed":
                replayed += 1
                self.total_resumed += 1
            else:
                self.total_lost += 1
        if self.last_resume is not None:
            self.last_resume["replayed"] = replayed
        if pending:
            logger.warning(
                "replayed checkpointed in-flight work into rebuilt "
                "engine",
                extra={
                    "extra_data": {
                        "checkpointed": len(pending),
                        "replayed": replayed,
                    }
                },
            )

    # ----------------------------------------------------------- submission

    def _gate(self, prompt_ids: List[int]) -> None:
        raise_for_state(
            self.state.value,
            retry_after=self.retry_after_s,
            detail=self.last_fatal,
        )
        if not self._quarantine:
            return  # steady state: skip the O(prompt) fingerprint
        fp = faults.fingerprint(prompt_ids)
        if fp in self._quarantine:
            raise PoisonRequestError(
                f"request {fp} is quarantined: it was in flight across "
                "repeated engine crashes (or was named by a poison "
                "fault) and will not be admitted again"
            )

    def evacuate(self, *args: Any, **kwargs: Any) -> None:
        """Refused, deliberately: a supervised dp=1 deployment has no
        in-process replica to replay the checkpoints into, and
        __getattr__ would otherwise delegate straight to
        EngineCore.evacuate — stranding live sequences (futures open,
        nothing replaying them) the moment an admin surface or script
        called it.  Use the SIGTERM graceful drain for single-replica
        rollouts; live migration needs tpu.dp > 1."""
        raise MigrationRefusedError(
            "dp=1 deployment has no migration target; use the SIGTERM "
            "graceful drain for rollouts (live migration requires "
            "tpu.dp > 1)"
        )

    def submit_tokens(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        self._gate(list(prompt_ids))
        try:
            return self.core.submit_tokens(
                prompt_ids, params, stream_cb, meta=meta
            )
        except EngineRecoveringError:
            raise
        except RuntimeError as exc:
            if self.core._fatal is not None:
                # crashed between the gate and the submit
                raise EngineRecoveringError(
                    "engine crashed during submission; retry shortly",
                    retry_after=self.retry_after_s,
                ) from exc
            raise

    def submit_prompt(
        self,
        prompt: str,
        params: SamplingParams,
        stream_cb: Optional[Callable[[int], Any]] = None,
        meta: Optional[Any] = None,
    ) -> Sequence:
        return self.submit_tokens(
            self.core.encode_prompt(prompt), params, stream_cb, meta=meta
        )

    def generate(
        self, prompts: Seq[str], params: Seq[SamplingParams]
    ) -> List[Dict[str, Any]]:
        """Blocking batch API (mirrors EngineCore.generate) routed through
        the supervisor's gate so quarantine/health checks apply."""
        seqs = [
            self.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        results = []
        for seq in seqs:
            seq.done_event.wait()
            if seq.status is SeqStatus.FAILED:
                raise seq.error  # type: ignore[misc]
            core = self.core
            text = core.final_text(seq)
            gen_time = (seq.finish_t or 0) - seq.arrival_t
            result = {
                "text": text,
                "token_ids": list(seq.generated_ids),
                "num_tokens": seq.num_output_tokens,
                "prompt_tokens": seq.orig_prompt_len,
                "finish_reason": seq.finish_reason,
                "metrics": {
                    "ttft": seq.ttft or 0.0,
                    "tpot": seq.tpot or 0.0,
                    "gen_time": gen_time,
                    **seq.resume_metrics(),
                },
            }
            if seq.params.logprobs:
                result["logprobs"] = core.logprob_entries(seq)
            results.append(result)
        return results

    # -------------------------------------------------------- introspection

    def health(self) -> Dict[str, Any]:
        """The health block /health and /stats surface: state machine
        position, restart accounting, quarantine size, queue depth."""
        state = self.state
        try:
            sched = self.core.scheduler.get_stats()
            queue_depth = sched["waiting"]
            running = sched["running"]
        except Exception:  # mid-rebuild: scheduler may not exist yet
            queue_depth = 0
            running = 0
        degraded_s = self._time_in_degraded
        if self._degraded_since is not None:
            degraded_s += time.monotonic() - self._degraded_since
        out = {
            "state": state.value,
            "alive": state_is_alive(state.value),
            "ready": state_is_ready(state.value),
            "crashes": self.total_crashes,
            "restarts": self.total_restarts,
            # satellite fix: operators could not see how close a
            # replica was to DEAD
            "restarts_remaining": restart_budget_remaining(
                self._restart_times, self._recovery
            ),
            "stalls": self.total_stalls,
            "resumed": self.total_resumed,
            "lost": self.total_lost,
            "quarantined": len(self._quarantine),
            "queue_depth": queue_depth,
            "running": running,
            "time_in_degraded_s": round(degraded_s, 3),
            "last_fatal": self.last_fatal,
            "transitions": list(self.transitions[-8:]),
        }
        if self._integrity_cfg.enabled:
            out["integrity"] = {
                "quarantined_corrupt": self.quarantined_corrupt,
                "corrupt_reloads": self.total_corrupt_reloads,
                "canary_failures": self.total_canary_failures,
                **(
                    {"canary": self._canary.stats()}
                    if self._canary is not None
                    else {}
                ),
                "last": self.last_integrity,
            }
        return out

    def device_health(self) -> Dict[str, Any]:
        if self.state is HealthState.DEAD:
            return {"alive": False, "state": "dead", "error": self.last_fatal}
        out = self.core.device_health()
        out["state"] = self.state.value
        return out

    def get_stats(self) -> Dict[str, Any]:
        try:
            stats = self.core.get_stats()
        except Exception:  # mid-rebuild
            stats = {}
        stats["supervisor"] = self.health()
        # always present (None until a crash happens) so operators can
        # discover the fields without inducing one; docs/operations.md
        stats["last_crash"] = self.last_crash
        stats["last_resume"] = self.last_resume
        armed = faults.snapshot()
        if armed:
            stats["faults_armed"] = armed
        return stats

    def __getattr__(self, name: str) -> Any:
        # serving surface not intercepted above (tokenizer, spec, mesh,
        # geometry, warmup, final_text, logprob_entries, ...) delegates
        # to the live core.  __getattr__ only fires for attributes not
        # found on the supervisor itself; guard against recursion while
        # __init__ is still building the first core.
        core = self.__dict__.get("core")
        if core is None:
            raise AttributeError(name)
        return getattr(core, name)
