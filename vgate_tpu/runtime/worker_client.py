"""Gateway-side client for one engine worker connection.

One :class:`WorkerClient` wraps one socket to one worker *incarnation*
(process + fencing epoch).  It owns a reader thread that demultiplexes
the two frame families the worker sends:

* **replies** (``op == "reply"``, correlated by ``id``) — completed
  synchronous calls; :meth:`call` blocks on them with a per-call
  deadline (``pod.call_timeout_s`` default), so a wedged worker costs a
  ``TimeoutError``, never a hung gateway thread.
* **notifications** (``tok`` / ``done`` / ``err`` / ``evacuated``) —
  handed to the PodEngine's dispatcher, which owns the fencing-epoch
  check (a frame from a replaced incarnation is *discarded and
  counted* there, not torn down here — the zombie's connection keeps
  draining so its late frames are observed rather than buffered).

Liveness is fail-fast: EOF, a frame-protocol violation, or any socket
error marks the client dead, fails every pending call with the typed
``WorkerLostError``, and fires ``on_lost`` exactly once — the
PodEngine's loss path (resubmit → respawn → canary gate) takes over.
The client never reconnects; a reconnect is a new incarnation with a
new epoch and therefore a new client.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Union

from vgate_tpu import metrics
from vgate_tpu.errors import WorkerLostError
from vgate_tpu.runtime import rpc
from vgate_tpu.runtime.worker import unwire_error

# Threading contract (scripts/vgt_lint.py, checker thread-discipline).
# Lock order: _lock (pending-call table) and _send_lock (socket writes)
# are both LEAVES and never nested — frames are encoded before either
# is taken, and reply delivery releases _lock before setting the event.
VGT_COMPONENTS: Dict[str, str] = {}
VGT_LOCK_GUARDS = {
    "_pending": "_lock",
}

Address = Union[str, Tuple[str, int]]


class _Pending:
    __slots__ = ("event", "reply")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.reply: Optional[Dict[str, Any]] = None


class WorkerClient:
    def __init__(
        self,
        address: Address,
        epoch: int,
        *,
        max_frame_bytes: int,
        connect_timeout_s: float,
        call_timeout_s: float,
        on_notify: Callable[[Dict[str, Any]], Any],
        on_lost: Callable[[Optional[BaseException]], Any],
        label: str = "worker",
    ) -> None:
        self.epoch = int(epoch)
        self.label = label
        self.max_frame_bytes = int(max_frame_bytes)
        self.call_timeout_s = float(call_timeout_s)
        self._on_notify = on_notify
        self._on_lost = on_lost
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._next_cid = 0
        self._dead: Optional[BaseException] = None
        self._lost_fired = False
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(max(0.1, float(connect_timeout_s)))
        self._sock.connect(address)
        self._sock.settimeout(None)
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"vgt-pod-read-{label}",
        )
        self._reader.start()

    # ------------------------------------------------------------- outbound

    def _send(self, frame: Dict[str, Any]) -> None:
        if self._dead is not None:
            raise WorkerLostError(
                f"{self.label} connection is down: {self._dead}"
            )
        frame["e"] = self.epoch
        try:
            with self._send_lock:
                sent = rpc.send_frame(
                    self._sock, frame, self.max_frame_bytes
                )
            metrics.RPC_BYTES.labels(direction="sent").observe(sent)
        except OSError as exc:
            self._mark_dead(exc)
            raise WorkerLostError(
                f"{self.label} send failed: {exc}"
            ) from exc

    def notify(self, op: str, **fields: Any) -> None:
        """Fire-and-forget frame (no reply expected): abort, brownout
        toggles.  Raises WorkerLostError only if the connection is
        already known dead."""
        self._send({"op": op, **fields})

    def call(
        self, op: str, timeout: Optional[float] = None, **fields: Any
    ) -> Dict[str, Any]:
        """Synchronous request/reply with a hard deadline.  Raises the
        worker's typed error (rebuilt via the errors taxonomy), a
        TimeoutError past the deadline, or WorkerLostError if the
        connection dies while waiting."""
        deadline = timeout if timeout is not None else self.call_timeout_s
        with self._lock:
            self._next_cid += 1
            cid = self._next_cid
            pending = _Pending()
            self._pending[cid] = pending
        t0 = time.perf_counter()
        try:
            # the wire carries the remaining budget so the worker can
            # bound its own work against the caller's deadline
            self._send(
                {"op": op, "id": cid, "deadline_s": deadline, **fields}
            )
            if not pending.event.wait(timeout=deadline):
                raise TimeoutError(
                    f"{self.label} RPC {op!r} timed out after "
                    f"{deadline:.1f}s"
                )
        finally:
            with self._lock:
                self._pending.pop(cid, None)
            # gateway-observed verb latency: success, typed error, and
            # timeout all count — a wedged verb must show in the tail
            metrics.RPC_CALL_SECONDS.labels(verb=op).observe(
                time.perf_counter() - t0
            )
        reply = pending.reply
        if reply is None:
            raise WorkerLostError(
                f"{self.label} connection lost during RPC {op!r}"
            )
        if not reply.get("ok"):
            raise unwire_error(reply.get("error") or {})
        return reply.get("data") or {}

    # -------------------------------------------------------------- inbound

    def _read_loop(self) -> None:
        exc: Optional[BaseException] = None
        recv_bytes = metrics.RPC_BYTES.labels(direction="received")
        try:
            while True:
                frame = rpc.recv_frame(
                    self._sock, self.max_frame_bytes,
                    size_cb=recv_bytes.observe,
                )
                if frame is None:
                    break  # clean EOF: worker exited
                if frame.get("op") == "reply":
                    self._deliver_reply(frame)
                else:
                    try:
                        self._on_notify(frame)
                    except Exception:  # noqa: BLE001 — reader must live
                        pass
        except (rpc.FrameError, OSError) as err:
            exc = err
        self._mark_dead(exc)

    def _deliver_reply(self, frame: Dict[str, Any]) -> None:
        with self._lock:
            pending = self._pending.get(frame.get("id"))
        if pending is None:
            return  # caller timed out and moved on
        pending.reply = frame
        pending.event.set()

    # ------------------------------------------------------------ lifecycle

    @property
    def dead(self) -> bool:
        return self._dead is not None

    def _mark_dead(self, exc: Optional[BaseException]) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = exc or ConnectionError("connection closed")
            pending = list(self._pending.values())
            self._pending.clear()
            fire = not self._lost_fired
            self._lost_fired = True
        for p in pending:
            p.event.set()  # reply stays None → WorkerLostError in call()
        try:
            self._sock.close()
        except OSError:
            pass
        if fire:
            try:
                self._on_lost(exc)
            except Exception:  # noqa: BLE001 — loss path must not raise
                pass

    def close(self) -> None:
        """Tear down without firing on_lost (deliberate shutdown)."""
        with self._lock:
            self._lost_fired = True
        self._mark_dead(ConnectionError("closed by gateway"))

    def join(self, timeout: float = 2.0) -> None:
        self._reader.join(timeout=timeout)
