"""Tokenization for the serving loop (CPU-side).

The reference passes raw strings to vLLM and never tokenizes
(main.py:215, SURVEY.md section 2.1 row 'Tokenization'); here tokenization
is first-party.  Two implementations behind one duck-typed interface:

* ``HFTokenizer`` — a local ``tokenizers``/``transformers`` tokenizer when a
  checkpoint/tokenizer path is configured;
* ``ByteTokenizer`` — a dependency-free UTF-8 byte fallback used in
  zero-egress environments (random-weight benchmarking, CI): byte ``b``
  maps to id ``OFFSET + b``, valid for any vocab >= 259.
"""

from __future__ import annotations

import os
from typing import List, Optional, Protocol

from vgate_tpu.logging_config import get_logger
from vgate_tpu.models.specs import ModelSpec

logger = get_logger(__name__)


class Tokenizer(Protocol):
    eos_id: int
    bos_id: int

    def encode(self, text: str) -> List[int]: ...

    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes shifted past a small reserved-special region."""

    OFFSET = 3  # 0=pad/eos-ish space, 1=bos, 2=unk

    def __init__(self, spec: ModelSpec) -> None:
        if spec.vocab_size < 256 + self.OFFSET:
            raise ValueError("vocab too small for byte tokenizer")
        self.eos_id = spec.eos_token_id % spec.vocab_size
        self.bos_id = spec.bos_token_id % spec.vocab_size

    def encode(self, text: str) -> List[int]:
        return [self.OFFSET + b for b in text.encode("utf-8")]

    def decode(self, ids: List[int]) -> str:
        data = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Wraps a local HF fast tokenizer."""

    def __init__(self, path: str, spec: ModelSpec) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        eos = self._tok.eos_token_id
        self.eos_id = eos if eos is not None else spec.eos_token_id
        bos = self._tok.bos_token_id
        self.bos_id = bos if bos is not None else spec.bos_token_id

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: List[dict]) -> Optional[str]:
        """Render chat messages with the model's own template when the
        tokenizer ships one (the gateway falls back to the reference's
        "Role: content" flattening otherwise, main.py:190-196)."""
        if not getattr(self._tok, "chat_template", None):
            return None
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True
        )


def get_tokenizer(spec: ModelSpec, tokenizer_path: Optional[str]) -> Tokenizer:
    if tokenizer_path and os.path.exists(tokenizer_path):
        try:
            return HFTokenizer(tokenizer_path, spec)
        except Exception:
            logger.warning(
                "failed to load HF tokenizer; falling back to bytes",
                exc_info=True,
            )
    return ByteTokenizer(spec)
