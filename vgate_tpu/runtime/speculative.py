"""Speculative decoding: prompt-lookup drafting + batched verification.

The reference has no speculative path (its decode is vLLM's, consumed
opaquely at vgate/backends/vllm_backend.py:51); this is a TPU-native
extra: drafts come from the sequence's own history (prompt-lookup /
n-gram matching — no draft model, no extra weights in HBM), and one
``spec_verify_forward`` pass (models/decoder.py) scores all drafts at
once over the paged KV cache.  Rejected drafts need no KV rollback: the
tokens past the accepted point sit at positions beyond the sequence's
length, which every later attention masks out and the next verify step
overwrites.

Distribution-exact for every request:

* **Greedy** (temperature 0): a draft token is accepted iff it equals
  the model's argmax at its position, so the output always follows the
  verify program's own greedy trajectory — drafts can accelerate it
  but never steer it.  The standard program-variant caveat applies (as
  it does to chunked decode): the verify pass and the single-step
  decode pass are different compiled programs, so an ulp-level logit
  tie can in principle break differently between them; the CPU suite
  pins token-identical output against the plain engine in practice
  (tests/test_speculative.py).
* **Sampled** (temperature > 0): standard rejection-sampling
  verification (ops/sampling.py verify_and_sample) — accept draft t
  with probability p(t) under the row's masked sampling distribution,
  resample from the residual on rejection — so every emitted token is
  exactly p-distributed whatever the drafter proposed (the scheme the
  reference's vLLM backend applies on GPU, consumed opaquely at
  vgate/backends/vllm_backend.py:51; here first-party).  A seeded
  sampled request remains run-to-run reproducible (acceptance and
  resample noise derive from (seed, step)), but its trajectory differs
  from the non-speculative engine's — equality holds in distribution,
  not token-for-token (tests/test_speculative.py pins both).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp


class NgramIndex:
    """Incremental prompt-lookup index for one sequence.

    Maps every ``ngram``-window of the history to its most recent start
    position, extended by only the windows added since the last call —
    so a draft costs O(new tokens), not a rescan of the whole history
    (the sequence's identity survives preemption: recompute folds
    outputs into the prompt but the concatenated token content is
    unchanged, so ``n_indexed`` stays valid).
    """

    def __init__(self, ngram: int = 2) -> None:
        self.ngram = max(1, ngram)
        self.pos: dict = {}
        self.n_indexed = 0  # windows with start < n_indexed are indexed

    def draft(self, ids: Sequence[int], k: int) -> List[int]:
        """Propose up to ``k`` continuation tokens by prompt lookup.

        Finds the most recent earlier occurrence of the final ``ngram``
        tokens and returns what followed it.  Returns [] when the
        history is too short or the n-gram never recurred — speculation
        then degrades to a plain decode step, never to a wrong result
        (drafts are verified, not trusted).
        """
        g = self.ngram
        n = len(ids)
        # index every complete window that ends before the final key
        # window (start <= n - g - 1); later occurrences overwrite
        # earlier ones, so lookups see the most recent repetition
        while self.n_indexed <= n - g - 1:
            i = self.n_indexed
            self.pos[tuple(ids[i : i + g])] = i
            self.n_indexed += 1
        if k <= 0 or n < g + 1:
            return []
        start = self.pos.get(tuple(ids[-g:]))
        if start is None:
            return []
        return list(ids[start + g : start + g + k])


def ngram_draft(
    ids: Sequence[int], k: int, ngram: int = 2
) -> List[int]:
    """One-shot prompt lookup (see NgramIndex for the incremental form
    the engine uses)."""
    return NgramIndex(ngram).draft(ids, k)


def count_accepted(
    model_toks: jnp.ndarray,  # [B, S] the model's token at each position
    tokens: jnp.ndarray,  # [B, S] input: [current, draft_1, ..., draft_{S-1}]
    input_lens: jnp.ndarray,  # [B] 1 + number of real drafts per row
) -> jnp.ndarray:
    """Leading-match acceptance count per row (jit-safe, [B] int32).

    Draft ``tokens[:, j]`` (j >= 1) is accepted iff it equals the model's
    choice at the previous position ``model_toks[:, j-1]`` and every
    earlier draft was accepted; the first mismatch stops the run (the
    model's token there becomes the bonus token).  Rows with
    ``input_lens == 1`` (no draft) always return 0.
    """
    S = tokens.shape[1]
    idx = jnp.arange(1, S)
    ok = (model_toks[:, :-1] == tokens[:, 1:]) & (
        idx[None, :] < input_lens[:, None]
    )
    # cumprod turns the boolean run into 1,1,...,1,0,0 — its sum is the
    # length of the accepted prefix
    return jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)


# ---------------------------------------------------- draft-model drafting


def _draft_scan(
    params, window, n_valid, k_pages, v_pages, page_tables, *, spec, k_max
):
    """``k_max`` greedy draft steps as ONE device program.

    Each step runs the drafter's full windowed prefill pass
    (models/decoder.py prefill_forward over the [W]-token window) and
    appends its argmax; once the window is full it shifts left.  The KV
    pool is a scratch the pass overwrites every step — the drafter
    manages no cache, it recomputes the (small, fixed) window.  RoPE
    positions are window-relative, not sequence-absolute: acceptable
    for a DRAFTER, whose only job is proposing likely continuations
    (the target's verify pass uses true absolute positions).
    """
    from vgate_tpu.models.decoder import prefill_forward

    W = window.shape[0]

    def step(carry, _):
        win, n, kp, vp = carry
        logits, kp, vp = prefill_forward(
            params, spec, win[None], n[None], kp, vp, page_tables
        )
        t = jnp.argmax(logits[0]).astype(jnp.int32)
        full = n >= W
        win = jnp.where(
            full,
            jnp.concatenate([win[1:], t[None]]),
            jax.lax.dynamic_update_index_in_dim(
                win, t, jnp.minimum(n, W - 1), 0
            ),
        )
        return (win, jnp.minimum(n + 1, W), kp, vp), t

    (_, _, _, _), toks = jax.lax.scan(
        step, (window, n_valid, k_pages, v_pages), None, length=k_max
    )
    return toks


class DraftModelDrafter:
    """Greedy draft-model drafting (the step beyond prompt-lookup).

    A second, small registered model proposes up to ``k_max`` tokens per
    round from a fixed ``window``-token suffix of the sequence.  One
    jitted ``lax.scan`` dispatches all steps (one device round-trip per
    draft call); the drafter holds a tiny scratch KV pool and recomputes
    the window each step instead of managing a paged cache.

    Correctness does not depend on the drafter: the engine's verify
    round (engine_core._tick_speculative + ops/sampling.verify_and_sample)
    accepts exactly the distribution-correct prefix of ANY proposal, so
    a weak or mismatched drafter only lowers the acceptance rate.  The
    cost model: a draft round re-reads the drafter's weights k_max
    times, so the drafter should be several times smaller than the
    target (e.g. Qwen2.5-0.5B drafting for 1.5B/7B — same tokenizer
    family; drafted ids outside the target vocab are dropped).

    Known limit: the engine's drafter seam is per-sequence, so a round
    with B active sequences dispatches B sequential draft scans before
    the one batched verify — draft latency scales with B.  Acceptable
    because speculation's home turf is single-stream (B~1) latency;
    batching the seam into one [B, W] scan is the optimization to reach
    for if multi-stream speculative serving ever becomes a target.

    Plain (single-device) meshes only — the engine falls back to n-gram
    drafting on model-parallel meshes (engine_core.__init__).
    """

    def __init__(
        self,
        model_id: str,
        k_max: int,
        dtype=jnp.bfloat16,
        window: int = 128,
        checkpoint_path: Optional[str] = None,
        target_vocab: Optional[int] = None,
        device=None,
        target_has_checkpoint: bool = False,
    ) -> None:
        from vgate_tpu.logging_config import get_logger
        from vgate_tpu.models.specs import spec_for_model_id
        from vgate_tpu.runtime.weights import load_or_init_params
        from vgate_tpu.utils.math import round_up

        if checkpoint_path is None and target_has_checkpoint:
            # ADVICE.md round-5 finding: model.draft_model_id with
            # draft_checkpoint_path unset next to a REAL target
            # checkpoint means the drafter runs on random init — its
            # proposals are noise, acceptance lands near 0%, and every
            # verify round is pure overhead over plain decode.  Loud by
            # design: this config is always a mistake in serving (only
            # synthetic benchmarks exercise random/random pairs, and
            # there the target is random too, so this never fires).
            get_logger(__name__).warning(
                "draft model %r has NO checkpoint "
                "(model.draft_checkpoint_path is unset) while the "
                "target model loads real weights: the randomly "
                "initialized drafter will be rejected at ~every "
                "position (~0%% acceptance) and speculative decoding "
                "becomes a pure slowdown — set "
                "model.draft_checkpoint_path or clear "
                "model.draft_model_id",
                model_id,
            )

        self.spec = spec_for_model_id(model_id)
        self.k_max = max(1, int(k_max))
        ps = 8  # internal scratch-pool page size
        self.window = round_up(max(ps, int(window)), ps)
        self.target_vocab = int(target_vocab or self.spec.vocab_size)
        params = load_or_init_params(self.spec, checkpoint_path, dtype)
        if device is not None:
            params = jax.device_put(params, device)
        self.params = params
        n_pages = 1 + self.window // ps
        kv_shape = (
            self.spec.num_layers, self.spec.num_kv_heads, n_pages, ps,
            self.spec.head_dim,
        )
        self._kv_dtype = dtype
        self._k_scratch = jnp.zeros(kv_shape, dtype)
        self._v_scratch = jnp.zeros(kv_shape, dtype)
        self._page_tables = jnp.arange(
            1, 1 + self.window // ps, dtype=jnp.int32
        )[None, :]
        self._fn = jax.jit(
            functools.partial(
                _draft_scan, spec=self.spec, k_max=self.k_max
            )
        )
        self.total_draft_calls = 0

    def draft_for(self, seq, k: int) -> List[int]:
        """The engine drafter seam (Callable[[Sequence, int], List[int]])."""
        k = min(int(k), self.k_max)
        if k <= 0:
            return []
        ids = (seq.prompt_ids + seq.output_ids)[-self.window:]
        win = np.zeros((self.window,), np.int32)
        win[: len(ids)] = ids
        toks = np.asarray(
            self._fn(
                self.params,
                jnp.asarray(win),
                jnp.asarray(len(ids), jnp.int32),
                self._k_scratch,
                self._v_scratch,
                self._page_tables,
            )
        )
        self.total_draft_calls += 1
        out: List[int] = []
        for t in toks[:k].tolist():
            if not 0 <= int(t) < self.target_vocab:
                break  # drafter/target vocab mismatch: stop proposing
            out.append(int(t))
        return out
