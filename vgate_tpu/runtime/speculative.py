"""Speculative decoding: prompt-lookup drafting + batched verification.

The reference has no speculative path (its decode is vLLM's, consumed
opaquely at vgate/backends/vllm_backend.py:51); this is a TPU-native
extra: drafts come from the sequence's own history (prompt-lookup /
n-gram matching — no draft model, no extra weights in HBM), and one
``spec_verify_forward`` pass (models/decoder.py) scores all drafts at
once over the paged KV cache.  Rejected drafts need no KV rollback: the
tokens past the accepted point sit at positions beyond the sequence's
length, which every later attention masks out and the next verify step
overwrites.

Distribution-exact for every request:

* **Greedy** (temperature 0): a draft token is accepted iff it equals
  the model's argmax at its position, so the output always follows the
  verify program's own greedy trajectory — drafts can accelerate it
  but never steer it.  The standard program-variant caveat applies (as
  it does to chunked decode): the verify pass and the single-step
  decode pass are different compiled programs, so an ulp-level logit
  tie can in principle break differently between them; the CPU suite
  pins token-identical output against the plain engine in practice
  (tests/test_speculative.py).
* **Sampled** (temperature > 0): standard rejection-sampling
  verification (ops/sampling.py verify_and_sample) — accept draft t
  with probability p(t) under the row's masked sampling distribution,
  resample from the residual on rejection — so every emitted token is
  exactly p-distributed whatever the drafter proposed (the scheme the
  reference's vLLM backend applies on GPU, consumed opaquely at
  vgate/backends/vllm_backend.py:51; here first-party).  A seeded
  sampled request remains run-to-run reproducible (acceptance and
  resample noise derive from (seed, step)), but its trajectory differs
  from the non-speculative engine's — equality holds in distribution,
  not token-for-token (tests/test_speculative.py pins both).
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp


class NgramIndex:
    """Incremental prompt-lookup index for one sequence.

    Maps every ``ngram``-window of the history to its most recent start
    position, extended by only the windows added since the last call —
    so a draft costs O(new tokens), not a rescan of the whole history
    (the sequence's identity survives preemption: recompute folds
    outputs into the prompt but the concatenated token content is
    unchanged, so ``n_indexed`` stays valid).
    """

    def __init__(self, ngram: int = 2) -> None:
        self.ngram = max(1, ngram)
        self.pos: dict = {}
        self.n_indexed = 0  # windows with start < n_indexed are indexed

    def draft(self, ids: Sequence[int], k: int) -> List[int]:
        """Propose up to ``k`` continuation tokens by prompt lookup.

        Finds the most recent earlier occurrence of the final ``ngram``
        tokens and returns what followed it.  Returns [] when the
        history is too short or the n-gram never recurred — speculation
        then degrades to a plain decode step, never to a wrong result
        (drafts are verified, not trusted).
        """
        g = self.ngram
        n = len(ids)
        # index every complete window that ends before the final key
        # window (start <= n - g - 1); later occurrences overwrite
        # earlier ones, so lookups see the most recent repetition
        while self.n_indexed <= n - g - 1:
            i = self.n_indexed
            self.pos[tuple(ids[i : i + g])] = i
            self.n_indexed += 1
        if k <= 0 or n < g + 1:
            return []
        start = self.pos.get(tuple(ids[-g:]))
        if start is None:
            return []
        return list(ids[start + g : start + g + k])


def ngram_draft(
    ids: Sequence[int], k: int, ngram: int = 2
) -> List[int]:
    """One-shot prompt lookup (see NgramIndex for the incremental form
    the engine uses)."""
    return NgramIndex(ngram).draft(ids, k)


def count_accepted(
    model_toks: jnp.ndarray,  # [B, S] the model's token at each position
    tokens: jnp.ndarray,  # [B, S] input: [current, draft_1, ..., draft_{S-1}]
    input_lens: jnp.ndarray,  # [B] 1 + number of real drafts per row
) -> jnp.ndarray:
    """Leading-match acceptance count per row (jit-safe, [B] int32).

    Draft ``tokens[:, j]`` (j >= 1) is accepted iff it equals the model's
    choice at the previous position ``model_toks[:, j-1]`` and every
    earlier draft was accepted; the first mismatch stops the run (the
    model's token there becomes the bonus token).  Rows with
    ``input_lens == 1`` (no draft) always return 0.
    """
    S = tokens.shape[1]
    idx = jnp.arange(1, S)
    ok = (model_toks[:, :-1] == tokens[:, 1:]) & (
        idx[None, :] < input_lens[:, None]
    )
    # cumprod turns the boolean run into 1,1,...,1,0,0 — its sum is the
    # length of the accepted prefix
    return jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
