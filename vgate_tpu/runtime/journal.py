"""Durable request journal + idempotency keys (gateway survivability).

The gateway's half of the crash-survivability story (the workers' half
is orphan mode, ``pod.orphan_grace_s``): every accepted non-streaming
request that carries an ``Idempotency-Key`` header is appended to an
append-only JSONL journal *before* dispatch and settled with its result
body on completion.  A gateway that crashes mid-request therefore
leaves a durable record of what it had promised; its successor replays
the journal at startup and

* a client retry whose generation already completed (typically on an
  orphaned worker the successor adopted) returns the **identical**
  result body with zero recompute — ``vgt_journal_replays{outcome=
  "served"}``;
* an accepted-but-unsettled record re-submits through the normal
  admission path (``outcome="resubmitted"``), so the work is not lost
  even when the client never retries;
* a key that is still in flight on the live gateway gets a typed 409
  (:class:`~vgate_tpu.errors.DuplicateRequestError`,
  ``outcome="duplicate"``) — two generations must never race under one
  key;
* a record that cannot be replayed (malformed snapshot, truncated
  tail) is counted (``outcome="failed"``) and skipped, never a crash.

Durability discipline: one JSON object per line, ``fsync`` after every
append (``gateway.journal_fsync``), and a loader that tolerates exactly
one torn record at the tail — the only partial write a crashed
``append → fsync`` sequence can leave.  A torn record anywhere else is
corruption and fails loudly.  Compaction (triggered past
``gateway.journal_max_bytes``) rewrites the file keeping only live
records: pending ones, and settled ones younger than
``gateway.journal_retention_s`` (still replayable to a retrying
client).

Wall-clock timestamps (``time.time``) are used deliberately — records
must stay meaningful across process restarts, which excludes
``perf_counter``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from vgate_tpu import metrics
from vgate_tpu.analysis.annotations import requires_lock
from vgate_tpu.errors import DuplicateRequestError
from vgate_tpu.logging_config import get_logger

logger = get_logger(__name__)

# Threading contract (scripts/vgt_lint.py, checker thread-discipline):
# _lock is a LEAF — held across the in-memory table AND the file append
# (ordering of journal lines must match ordering of state transitions),
# but nothing else is ever acquired under it.
VGT_COMPONENTS: Dict[str, str] = {}
VGT_LOCK_GUARDS = {
    "_records": "_lock",
}

# record states
PENDING = "pending"
SETTLED = "settled"
FAILED = "failed"


class JournalRecord:
    __slots__ = (
        "key", "state", "request_id", "endpoint", "snapshot",
        "result", "accepted_t", "settled_t", "inherited",
    )

    def __init__(
        self,
        key: str,
        request_id: str,
        endpoint: str,
        snapshot: Dict[str, Any],
        accepted_t: float,
    ) -> None:
        self.key = key
        self.state = PENDING
        self.request_id = request_id
        self.endpoint = endpoint
        self.snapshot = snapshot
        self.result: Optional[Dict[str, Any]] = None
        self.accepted_t = accepted_t
        self.settled_t: Optional[float] = None
        # loaded from a PREDECESSOR's journal (vs accepted this
        # lifetime).  A retry hitting an inherited pending key waits
        # for the startup replay to settle it — the original attempt
        # died with the old gateway, so 409 "wait for the original"
        # would dead-end the client.
        self.inherited = False

    def as_dict(self) -> Dict[str, Any]:
        return {
            "key": self.key,
            "state": self.state,
            "request_id": self.request_id,
            "endpoint": self.endpoint,
            "accepted_t": self.accepted_t,
            "settled_t": self.settled_t,
            "inherited": self.inherited,
        }


class RequestJournal:
    """Append-only fsync'd JSONL journal of idempotent requests.

    ``path=None`` runs fully in memory: idempotency still works within
    one gateway lifetime (duplicate 409s, settled replays), it just
    does not survive a restart — the mode tests and journal-less
    deployments get by default.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        fsync: bool = True,
        max_bytes: int = 16 * 1024 * 1024,
        retention_s: float = 3600.0,
    ) -> None:
        self.path = path or None
        self.fsync = bool(fsync)
        self.max_bytes = int(max_bytes)
        self.retention_s = float(retention_s)
        self._lock = threading.Lock()
        self._records: Dict[str, JournalRecord] = {}
        self._fh = None
        self._bytes = 0
        self._torn_tail = False
        if self.path:
            # nothing shares the journal yet, but _load/_apply assert
            # _lock discipline (they also run under compaction) — hold
            # it for real rather than special-casing construction
            with self._lock:
                self._load()
                self._open_for_append()
            self._set_bytes_gauge()

    # ------------------------------------------------------------- loading

    @requires_lock("_lock")
    def _load(self) -> None:
        """Rebuild the in-memory table from the journal file.  Tolerant
        of exactly one torn record at the tail (a crash mid-append);
        torn records elsewhere indicate corruption and raise."""
        if not self.path or not os.path.exists(self.path):
            return
        torn_at: Optional[int] = None
        with open(self.path, "rb") as fh:
            lines = fh.read().split(b"\n")
        # a trailing newline yields one empty final element; drop it
        if lines and lines[-1] == b"":
            lines.pop()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                op = json.loads(line.decode("utf-8"))
                if not isinstance(op, dict):
                    raise ValueError("journal line is not an object")
            except (ValueError, UnicodeDecodeError) as exc:
                if i == len(lines) - 1:
                    # the one legal torn record: a crash between the
                    # append and its newline/fsync
                    torn_at = i
                    logger.warning(
                        "journal: dropping torn trailing record "
                        "(crash mid-append): %s", exc,
                    )
                    break
                raise RuntimeError(
                    f"journal {self.path} corrupt at line {i + 1}: {exc}"
                ) from exc
            self._apply(op)
        self._torn_tail = torn_at is not None
        if self._torn_tail:
            # rewrite without the torn tail so the next append starts
            # at a clean record boundary
            self._compact_locked()

    @requires_lock("_lock")
    def _apply(self, op: Dict[str, Any]) -> None:
        kind = op.get("op")
        key = op.get("key")
        if not isinstance(key, str) or not key:
            raise RuntimeError(f"journal record missing key: {op!r}")
        if kind == "accept":
            rec = JournalRecord(
                key,
                str(op.get("request_id") or ""),
                str(op.get("endpoint") or ""),
                dict(op.get("snapshot") or {}),
                float(op.get("t") or 0.0),
            )
            rec.inherited = True  # _apply only runs from _load
            self._records[key] = rec
        elif kind == "settle":
            rec = self._records.get(key)
            if rec is not None:
                rec.state = SETTLED
                rec.result = op.get("result")
                rec.settled_t = float(op.get("t") or 0.0)
        elif kind == "fail":
            rec = self._records.get(key)
            if rec is not None:
                rec.state = FAILED
                rec.settled_t = float(op.get("t") or 0.0)
        else:
            raise RuntimeError(f"journal record with unknown op: {kind!r}")

    # ------------------------------------------------------------ appending

    def _open_for_append(self) -> None:
        if not self.path:
            return
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(self.path, "ab")
        self._bytes = self._fh.tell()

    @requires_lock("_lock")
    def _append_locked(self, op: Dict[str, Any]) -> None:
        if self._fh is None:
            return
        data = json.dumps(op, separators=(",", ":")).encode("utf-8")
        self._fh.write(data + b"\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        self._bytes += len(data) + 1
        if self._bytes > self.max_bytes:
            self._compact_locked()
        self._set_bytes_gauge()

    def _set_bytes_gauge(self) -> None:
        try:
            metrics.JOURNAL_BYTES.set(self._bytes)
        except Exception:  # noqa: BLE001 — telemetry never fails an append
            pass

    # ----------------------------------------------------------- compaction

    def _live_records(self) -> List[JournalRecord]:
        now = time.time()
        live = []
        for rec in self._records.values():
            if rec.state == PENDING:
                live.append(rec)
            elif rec.state == SETTLED:
                if (now - (rec.settled_t or now)) < self.retention_s:
                    live.append(rec)
            # FAILED records are never replayable; drop at compaction
        return live

    @requires_lock("_lock")
    def _compact_locked(self) -> None:
        if not self.path:
            return
        live = self._live_records()
        tmp = self.path + ".compact"
        with open(tmp, "wb") as out:
            for rec in sorted(live, key=lambda r: r.accepted_t):
                out.write(json.dumps({
                    "op": "accept", "key": rec.key,
                    "request_id": rec.request_id,
                    "endpoint": rec.endpoint,
                    "snapshot": rec.snapshot, "t": rec.accepted_t,
                }, separators=(",", ":")).encode("utf-8") + b"\n")
                if rec.state == SETTLED:
                    out.write(json.dumps({
                        "op": "settle", "key": rec.key,
                        "result": rec.result, "t": rec.settled_t,
                    }, separators=(",", ":")).encode("utf-8") + b"\n")
            out.flush()
            os.fsync(out.fileno())
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        # drop compacted-away records from memory too, so the table
        # cannot grow without bound across a long gateway lifetime
        keep = {rec.key for rec in live}
        for key in [k for k in self._records if k not in keep]:
            del self._records[key]
        self._open_for_append()
        self._set_bytes_gauge()

    # -------------------------------------------------------------- the API

    def accept(
        self,
        key: str,
        request_id: str,
        endpoint: str,
        snapshot: Dict[str, Any],
    ) -> None:
        now = time.time()
        with self._lock:
            rec = JournalRecord(key, request_id, endpoint, snapshot, now)
            self._records[key] = rec
            self._append_locked({
                "op": "accept", "key": key, "request_id": request_id,
                "endpoint": endpoint, "snapshot": snapshot, "t": now,
            })

    def settle(self, key: str, result: Dict[str, Any]) -> None:
        now = time.time()
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.state = SETTLED
            rec.result = result
            rec.settled_t = now
            self._append_locked({
                "op": "settle", "key": key, "result": result, "t": now,
            })

    def fail(self, key: str) -> None:
        """The request errored terminally — the key is released (a
        retry with it runs fresh rather than replaying a failure)."""
        now = time.time()
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.state = FAILED
            rec.settled_t = now
            self._append_locked({"op": "fail", "key": key, "t": now})

    def lookup(self, key: str) -> Optional[JournalRecord]:
        with self._lock:
            return self._records.get(key)

    def begin(
        self, key: str, request_id: str, endpoint: str,
        snapshot: Dict[str, Any],
    ) -> Tuple[str, Optional[Dict[str, Any]]]:
        """Admission decision for one keyed request.  Returns
        ``("replay", result)`` when the key settled (serve the stored
        body, zero recompute), ``("await", None)`` when the key is
        pending but INHERITED from a predecessor (the caller should
        wait for the startup replay to settle it), raises
        :class:`DuplicateRequestError` when it is pending from this
        lifetime, and returns ``("fresh", None)`` after journaling the
        accept."""
        now = time.time()
        with self._lock:
            rec = self._records.get(key)
            if rec is not None:
                if rec.state == SETTLED and rec.result is not None:
                    if (
                        rec.settled_t is not None
                        and (now - rec.settled_t) >= self.retention_s
                    ):
                        # past retention: the stored body may already be
                        # compacted away on disk — treat as fresh
                        pass
                    else:
                        return ("replay", rec.result)
                elif rec.state == PENDING:
                    if rec.inherited:
                        return ("await", None)
                    raise DuplicateRequestError(
                        f"Idempotency-Key {key!r} is already in flight; "
                        "wait for the original attempt",
                    )
                # FAILED (or expired-settled) falls through to fresh
            rec = JournalRecord(key, request_id, endpoint, snapshot, now)
            self._records[key] = rec
            self._append_locked({
                "op": "accept", "key": key, "request_id": request_id,
                "endpoint": endpoint, "snapshot": snapshot, "t": now,
            })
        return ("fresh", None)

    def pending(self) -> List[JournalRecord]:
        """Accepted-but-unsettled records (startup replay candidates:
        the previous gateway died between accept and settle)."""
        with self._lock:
            return [
                r for r in self._records.values() if r.state == PENDING
            ]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            by_state: Dict[str, int] = {}
            for rec in self._records.values():
                by_state[rec.state] = by_state.get(rec.state, 0) + 1
            return {
                "path": self.path,
                "bytes": self._bytes,
                "records": len(self._records),
                "by_state": by_state,
                "torn_tail_recovered": self._torn_tail,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
