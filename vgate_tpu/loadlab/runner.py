"""Sweep orchestration: scenario -> arrivals -> driver -> grader ->
stamped JSONL artifact.

For each QPS cell the runner: builds the plan, snapshots the server's
``vgt_*`` histograms, optionally schedules the chaos arm, drives the
cell open-loop, re-snapshots the histograms, grades the samples, and
appends one artifact line.  The artifact carries BOTH latency views per
cell — the client-observed distributions and the server's own
TTFT/TPOT histogram deltas — so metric skew between what the server
claims and what clients experience is visible in one file (the smoke
drill asserts the two agree on an unloaded cell).

``launch_server`` boots ``python main.py`` as a subprocess with the
scenario's ``server_env`` — the path bench.py's scenario mode and the
drills share.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import re
import subprocess
import sys
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional

import aiohttp

from . import slo, workload
from .driver import drive_cell, run_serial
from .scenario import Scenario

_REPO_DIR = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# server histograms mirrored into each cell line (name -> artifact key)
_HISTOGRAMS = {
    "vgt_time_to_first_token_seconds": "ttft",
    "vgt_time_per_output_token_seconds": "tpot",
}


def git_sha() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=_REPO_DIR, capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None
    except Exception:  # noqa: BLE001 — stamping must never fail a run
        return None


# -- prometheus text scraping --------------------------------------------

def parse_histograms(text: str) -> Dict[str, Dict[str, Any]]:
    """Extract {name: {count, sum, buckets: {le: cum_count}}} for the
    mirrored histograms from a /metrics exposition."""
    out: Dict[str, Dict[str, Any]] = {
        name: {"count": 0.0, "sum": 0.0, "buckets": {}}
        for name in _HISTOGRAMS
    }
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^(\w+)(?:\{([^}]*)\})?\s+([0-9eE+.\-]+|NaN)", line)
        if not m:
            continue
        metric, labels, value = m.group(1), m.group(2) or "", m.group(3)
        try:
            val = float(value)
        except ValueError:
            continue
        for name, acc in out.items():
            if metric == f"{name}_count":
                acc["count"] = val
            elif metric == f"{name}_sum":
                acc["sum"] = val
            elif metric == f"{name}_bucket":
                le = re.search(r'le="([^"]+)"', labels)
                if le:
                    acc["buckets"][le.group(1)] = val
    return out


def hist_delta(
    before: Dict[str, Any], after: Dict[str, Any]
) -> Dict[str, Any]:
    """Per-cell histogram delta: observation count, mean, and a bucket
    p99 estimate (upper-bound interpolation on the cumulative bucket
    counts — coarse, but honest about its granularity)."""
    dcount = after["count"] - before["count"]
    dsum = after["sum"] - before["sum"]
    result: Dict[str, Any] = {
        "count": int(dcount),
        "mean_ms": round(dsum / dcount * 1000, 1) if dcount > 0 else None,
    }
    if dcount > 0:
        deltas = []
        for le, cum in after["buckets"].items():
            if le == "+Inf":
                continue
            d = cum - before["buckets"].get(le, 0.0)
            deltas.append((float(le), d))
        deltas.sort()
        target = 0.99 * dcount
        p99 = None
        for le, cum_d in deltas:
            if cum_d >= target:
                p99 = le * 1000
                break
        result["p99_ms_le"] = round(p99, 1) if p99 is not None else None
    return result


async def _scrape(base_url: str) -> Optional[Dict[str, Dict[str, Any]]]:
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{base_url}/metrics",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                if resp.status != 200:
                    return None
                return parse_histograms(await resp.text())
    except Exception:  # noqa: BLE001 — the server view is best-effort;
        # the client view is the ground truth the lab exists to record
        return None


async def _fetch_perf(base_url: str) -> Optional[Dict[str, Any]]:
    """One /debug/perf scrape, or None when the server has no
    attribution surface (pre-perf servers, disabled recorder)."""
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{base_url}/debug/perf",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                if resp.status != 200:
                    return None
                body = await resp.json()
                return body if body.get("enabled") else None
    except Exception:  # noqa: BLE001 — perf attribution is an extra
        # evidence column, never a reason to fail the measurement
        return None


def _perf_totals(snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The monotone ``totals`` block from a /debug/perf payload —
    top-level on dp=1, under the merged aggregate on dp>1 (both shapes
    carry it top-level; the replicas list is ignored here)."""
    return snap.get("totals")


def perf_delta(
    before: Optional[Dict[str, Any]],
    after: Optional[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Per-cell perf-attribution delta from two /debug/perf scrapes:
    where the server's engine time went during THIS cell (phase
    seconds, recompiles, host-overhead ratio) plus the end-of-cell
    rolling-window gauges — so every sweep artifact carries a "where
    did the time go" row next to its tok/s number."""
    if before is None or after is None:
        return None
    b, a = _perf_totals(before), _perf_totals(after)
    if b is None or a is None:
        return None
    phases = {
        name: round(
            a["phase_seconds"].get(name, 0.0)
            - b["phase_seconds"].get(name, 0.0),
            6,
        )
        for name in a.get("phase_seconds", {})
    }
    wall = round(a["wall_s"] - b["wall_s"], 6)
    recompiles = {
        prog: a["compiles"].get(prog, 0) - b["compiles"].get(prog, 0)
        for prog in set(a.get("compiles", {})) | set(b.get("compiles", {}))
    }
    window = after.get("window") or {}
    out = {
        "ticks": a["ticks"] - b["ticks"],
        "tokens": a["tokens"] - b["tokens"],
        "wall_s": wall,
        "phase_seconds": phases,
        "host_overhead_ratio": (
            round(phases.get("host", 0.0) / wall, 4) if wall > 0 else None
        ),
        "recompiles": {k: v for k, v in recompiles.items() if v},
        "compile_seconds": round(
            a["compile_seconds"] - b["compile_seconds"], 6
        ),
        # end-of-cell rolling-window gauges (the live view the server's
        # vgt_decode_mfu / vgt_host_overhead_ratio metrics export)
        "window": {
            key: window.get(key)
            for key in (
                "tokens_per_s", "mfu", "hbm_roofline_pct",
                "host_overhead_ratio",
            )
        },
    }
    # pod-mode servers stamp topology + handoff outcome counters onto
    # the merged snapshot; land the per-cell handoff outcome DELTAS so
    # a disaggregated sweep row shows how many KV transfers (and how
    # many monolithic fallbacks) this cell's tok/s actually paid for
    pod_after = after.get("pod")
    if pod_after is not None:
        ho_b = (before.get("pod") or {}).get("handoffs") or {}
        ho_a = pod_after.get("handoffs") or {}
        out["pod"] = {
            "workers": pod_after.get("workers"),
            "workers_alive": pod_after.get("workers_alive"),
            "handoffs": {
                key: ho_a.get(key, 0) - ho_b.get(key, 0)
                for key in set(ho_a) | set(ho_b)
            },
        }
    return out


async def _fetch_stats(base_url: str) -> Dict[str, Any]:
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{base_url}/stats",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                if resp.status != 200:
                    return {}
                return await resp.json()
    except Exception:  # noqa: BLE001
        return {}


# -- chaos arm ------------------------------------------------------------

async def _chaos_task(
    base_url: str, spec, result: Dict[str, Any]
) -> None:
    """Arm the scenario's fault spec mid-cell via /debug/faults (the
    server opts in with VGT_FAULTS_HTTP=1)."""
    await asyncio.sleep(max(0.0, spec.at_s))
    try:
        async with aiohttp.ClientSession() as session:
            async with session.post(
                f"{base_url}/debug/faults",
                json={"faults": spec.faults},
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                body = await resp.json()
                result["armed"] = resp.status == 200 and bool(
                    body.get("armed")
                )
                result["status"] = resp.status
                result["detail"] = body
    except Exception as exc:  # noqa: BLE001 — chaos is an optional arm;
        # failure to arm is recorded, not fatal to the measurement
        result["armed"] = False
        result["error"] = repr(exc)


async def _chaos_disarm(base_url: str) -> None:
    with contextlib.suppress(Exception):
        async with aiohttp.ClientSession() as session:
            await session.delete(
                f"{base_url}/debug/faults",
                timeout=aiohttp.ClientTimeout(total=10),
            )


# -- the sweep ------------------------------------------------------------

async def run_scenario_async(
    scenario: Scenario,
    base_url: str,
    *,
    out_path: Optional[str] = None,
    platform: Optional[str] = None,
    device: Optional[str] = None,
    cells: Optional[List[float]] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """Run the full QPS sweep; returns {lines, summary, out_path}."""
    say = progress or (lambda s: print(s, file=sys.stderr, flush=True))
    base_url = base_url.rstrip("/")
    stats = await _fetch_stats(base_url)
    cfg = stats.get("config") or {}
    import hashlib

    meta: Dict[str, Any] = {
        "kind": "meta",
        "schema": slo.SCHEMA,
        "scenario": scenario.name,
        "scenario_hash": scenario.content_hash(),
        "seed": scenario.seed,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": platform
        or os.environ.get("VGT_LOADLAB_PLATFORM")
        or (os.environ.get("JAX_PLATFORMS") or "unknown").split(",")[0]
        or "unknown",
        "device": device or os.environ.get("VGT_LOADLAB_DEVICE")
        or "unknown",
        "git_sha": git_sha(),
        "config_fingerprint": hashlib.sha256(
            json.dumps(cfg, sort_keys=True).encode()
        ).hexdigest()[:16] if cfg else None,
        "server_config": cfg or None,
        "server_model": (stats.get("engine") or {}).get("model"),
        "base_url": base_url,
        "arrival": scenario.arrival.to_dict(),
        "duration_s": scenario.duration_s,
        "slos": {t: s.to_dict() for t, s in scenario.slos.items()},
    }
    lines: List[Dict[str, Any]] = [meta]

    if scenario.warmup_requests > 0:
        say(f"loadlab: warmup x{scenario.warmup_requests}")
        await run_serial(
            base_url,
            workload.warmup_requests(scenario, scenario.warmup_requests),
            timeout_s=scenario.request_timeout_s,
        )

    sweep = list(cells) if cells is not None else list(scenario.qps_cells)
    cell_lines: List[Dict[str, Any]] = []
    for idx, qps in enumerate(sweep):
        plan = workload.build_plan(scenario, idx, qps)
        say(
            f"loadlab: cell {idx + 1}/{len(sweep)} qps={qps:g} "
            f"({len(plan)} arrivals over {scenario.duration_s:g}s)"
        )
        before = await _scrape(base_url)
        perf_before = await _fetch_perf(base_url)
        chaos_result: Dict[str, Any] = {}
        extra = []
        armed_here = scenario.chaos is not None and (
            scenario.chaos.cell_index is None
            or scenario.chaos.cell_index == idx
        ) and scenario.chaos.faults
        if armed_here:
            extra.append(
                _chaos_task(base_url, scenario.chaos, chaos_result)
            )
        samples = await drive_cell(
            base_url, plan,
            timeout_s=scenario.request_timeout_s,
            extra_tasks=extra,
        )
        if armed_here and scenario.chaos.disarm_at_end:
            await _chaos_disarm(base_url)
        # let stragglers' histogram observations land before the
        # post-cell scrape (the driver already awaited every sample)
        after = await _scrape(base_url)
        perf_after = await _fetch_perf(base_url)
        line = slo.grade_cell(
            samples, scenario.slos,
            qps=qps, duration_s=scenario.duration_s,
        )
        if before is not None and after is not None:
            line["server"] = {
                key: hist_delta(before[name], after[name])
                for name, key in _HISTOGRAMS.items()
            }
        else:
            line["server"] = None
        # the attribution delta lands next to the two TTFT views: every
        # future perf PR's sweep carries a "where did the time go" row,
        # not just a tok/s number
        line["perf"] = perf_delta(perf_before, perf_after)
        if armed_here:
            line["chaos"] = {
                "faults": scenario.chaos.faults,
                "at_s": scenario.chaos.at_s,
                **chaos_result,
            }
        cell_lines.append(line)
        lines.append(line)
        say(json.dumps(line))

    summary = slo.summarize(cell_lines)
    lines.append(summary)
    say(json.dumps(summary))
    if out_path:
        slo.write_artifact(out_path, lines)
        say(f"loadlab: artifact -> {out_path}")
    return {"lines": lines, "summary": summary, "out_path": out_path}


def run_scenario(scenario: Scenario, base_url: str, **kwargs: Any):
    """Sync wrapper (scripts / bench.py)."""
    return asyncio.run(run_scenario_async(scenario, base_url, **kwargs))


# -- local server launch --------------------------------------------------

def scenario_server_env(scenario: Scenario) -> Dict[str, str]:
    """The scenario's server_env as DEFAULTS: any variable the operator
    already exported wins (r6_session.sh re-points the same scenario at
    a 7B model / int8 KV by exporting over it)."""
    return {
        k: str(v)
        for k, v in scenario.server_env.items()
        if k not in os.environ
    }


@contextlib.contextmanager
def launch_server(
    env_overrides: Dict[str, str],
    port: int = 8790,
    ready_timeout_s: float = 300.0,
):
    """Boot ``python main.py`` on ``port`` with ``env_overrides`` and
    yield its base URL once /health/ready answers; always tears the
    process down.  The scenario's ``server_env`` plus the caller's env
    decide platform/model — the lab itself never imports jax."""
    env = dict(os.environ)
    env.update(env_overrides)
    env["VGT_SERVER__PORT"] = str(port)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO_DIR, "main.py")],
        env=env, cwd=_REPO_DIR,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    base = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + ready_timeout_s
        last_err: Optional[str] = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"server exited rc={proc.returncode} before ready"
                )
            try:
                with urllib.request.urlopen(
                    f"{base}/health/ready", timeout=2
                ) as resp:
                    if resp.status == 200:
                        break
            except Exception as exc:  # noqa: BLE001 — poll until deadline
                last_err = repr(exc)
            time.sleep(0.3)
        else:
            raise TimeoutError(
                f"server on :{port} never became ready "
                f"({ready_timeout_s:.0f}s); last error: {last_err}"
            )
        yield base
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
