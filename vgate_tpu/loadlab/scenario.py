"""Scenario definitions: YAML + dataclasses composing traffic mixes,
arrival shapes, QPS sweeps, per-tier SLOs, and an optional chaos arm.

A scenario is the unit of comparison: two artifact files produced from
the same scenario (same name + same content hash) are comparable
cell-for-cell by ``python -m vgate_tpu.loadlab.compare``.  Bundled
scenarios live in ``vgate_tpu/loadlab/scenarios/*.yaml`` and are
addressable by bare name (``smoke_mixed``); anything else is a path.

Shapes map onto levers the engine already has:

* ``chat`` / ``multi_turn_chat`` — shared system prefixes + growing
  per-user transcripts exercise the PR-6 radix prefix cache,
* ``rag`` — common corpus preambles ahead of unique questions, same
  radix lever at a coarser grain,
* ``long_context`` — chunked-prefill pressure,
* ``embeddings`` — the non-generative path (admission + batcher only).

Tier mixes (interactive/standard/batch) exercise PR-4 admission and
priority scheduling; the chaos arm replays the PR 1-9 fault drills
under measured load via the ``/debug/faults`` surface.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

from vgate_tpu.admission import TIERS

from . import arrivals

SHAPES = ("chat", "multi_turn_chat", "rag", "long_context", "embeddings")

_SCENARIO_DIR = os.path.join(os.path.dirname(__file__), "scenarios")


@dataclass
class SLOSpec:
    """Per-request bounds a sample must meet to count toward goodput.

    All bounds are milliseconds; ``None`` means "not graded on this
    axis".  A request must ALSO have completed without error — a typed
    503/429/504 or an SSE error event can never be "good" no matter how
    fast it failed.
    """

    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    e2e_ms: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            k: v for k, v in dataclasses.asdict(self).items()
            if v is not None
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLOSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown SLO fields: {sorted(unknown)}")
        return cls(**d)


@dataclass
class TrafficMix:
    """One weighted slice of the offered traffic."""

    shape: str = "chat"
    weight: float = 1.0
    tier: str = "standard"
    # prompt/output sizing in tokenizer-agnostic "units" (~words).  On
    # the byte-tokenizer tiny-dense smoke model a unit is several
    # tokens; on real models roughly 1.3 tokens.  Sizing is relative —
    # scenarios compare against themselves, not across tokenizers.
    prompt_units: int = 48
    max_tokens: int = 16
    stream: bool = True
    # shared-prefix levers (chat/multi_turn_chat/rag): how many units
    # of prefix are shared, and across how large a cohort
    shared_prefix_units: int = 0
    group_size: int = 4
    # multi_turn_chat: transcript turns per simulated user
    turns: int = 3
    # rag: size of the shared corpus-passage pool
    num_docs: int = 8

    def __post_init__(self) -> None:
        if self.shape not in SHAPES:
            raise ValueError(
                f"unknown shape {self.shape!r}; valid: {SHAPES}"
            )
        if self.tier not in TIERS:
            raise ValueError(
                f"unknown tier {self.tier!r}; valid: {tuple(TIERS)}"
            )
        if self.weight <= 0:
            raise ValueError("mix weight must be > 0")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrafficMix":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown mix fields: {sorted(unknown)}")
        return cls(**d)


@dataclass
class ChaosSpec:
    """Arm ``VGT_FAULTS``-style fault points mid-cell through the
    server's ``/debug/faults`` surface (requires the server to run with
    ``VGT_FAULTS_HTTP=1``).  ``cell_index`` limits arming to one sweep
    cell (None = every cell); ``at_s`` is the offset into that cell."""

    faults: str = ""
    at_s: float = 2.0
    cell_index: Optional[int] = None
    disarm_at_end: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown chaos fields: {sorted(unknown)}")
        return cls(**d)


@dataclass
class ArrivalSpec:
    process: str = "poisson"
    # bursty-only knobs (ignored by poisson/constant)
    on_s: float = 2.0
    off_s: float = 4.0
    burst_mult: float = 3.0

    def __post_init__(self) -> None:
        if self.process not in arrivals.PROCESSES:
            raise ValueError(
                f"unknown arrival process {self.process!r}; "
                f"valid: {arrivals.PROCESSES}"
            )

    def generate(
        self, rate_qps: float, duration_s: float, seed: int
    ) -> List[float]:
        kwargs: Dict[str, float] = {}
        if self.process == "bursty":
            kwargs = {
                "on_s": self.on_s,
                "off_s": self.off_s,
                "burst_mult": self.burst_mult,
            }
        return arrivals.generate(
            self.process, rate_qps, duration_s, seed, **kwargs
        )

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ArrivalSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown arrival fields: {sorted(unknown)}")
        return cls(**d)


@dataclass
class Scenario:
    name: str = "unnamed"
    seed: int = 20260803
    # per-cell wall clock; the sweep runs every cell in qps_cells
    duration_s: float = 15.0
    qps_cells: List[float] = field(default_factory=lambda: [2.0])
    arrival: ArrivalSpec = field(default_factory=ArrivalSpec)
    mixes: List[TrafficMix] = field(default_factory=lambda: [TrafficMix()])
    slos: Dict[str, SLOSpec] = field(default_factory=dict)
    # per-request client timeout; a request past it is a typed
    # ``client_timeout`` sample, never an unhandled error
    request_timeout_s: float = 60.0
    # serial, un-measured requests fired before cell 0 (route warmup +
    # first-dispatch compiles must not skew the first cell's tail)
    warmup_requests: int = 3
    # env overrides for --launch mode (scripts boot the server with
    # these on top of the caller's environment)
    server_env: Dict[str, str] = field(default_factory=dict)
    chaos: Optional[ChaosSpec] = None

    def __post_init__(self) -> None:
        if not self.qps_cells:
            raise ValueError("scenario needs at least one qps cell")
        if not self.mixes:
            raise ValueError("scenario needs at least one traffic mix")
        for tier in self.slos:
            if tier not in TIERS:
                raise ValueError(
                    f"SLO for unknown tier {tier!r}; valid: {tuple(TIERS)}"
                )

    # -- (de)serialization ------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "qps_cells": list(self.qps_cells),
            "arrival": self.arrival.to_dict(),
            "mixes": [m.to_dict() for m in self.mixes],
            "slos": {t: s.to_dict() for t, s in self.slos.items()},
            "request_timeout_s": self.request_timeout_s,
            "warmup_requests": self.warmup_requests,
            "server_env": dict(self.server_env),
        }
        if self.chaos is not None:
            d["chaos"] = self.chaos.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Scenario":
        d = dict(d)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown scenario fields: {sorted(unknown)}")
        if "arrival" in d:
            d["arrival"] = ArrivalSpec.from_dict(d["arrival"])
        if "mixes" in d:
            d["mixes"] = [TrafficMix.from_dict(m) for m in d["mixes"]]
        if "slos" in d:
            d["slos"] = {
                t: SLOSpec.from_dict(s) for t, s in d["slos"].items()
            }
        if d.get("chaos") is not None:
            d["chaos"] = ChaosSpec.from_dict(d["chaos"])
        return cls(**d)

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    def content_hash(self) -> str:
        """Stable hash of everything that affects the offered load —
        compare refuses cross-scenario diffs on it.  server_env is
        included, but only the YAML's view of it: env-EXPORTED server
        overrides bypass this hash by design (r6_session re-points one
        scenario at other models), which is why compare.py additionally
        gates on the artifact's config_fingerprint (hashed from the
        live server's /stats config block)."""
        canon = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(canon.encode()).hexdigest()[:16]


def bundled_scenarios() -> List[str]:
    """Names of the scenarios shipped in the package."""
    if not os.path.isdir(_SCENARIO_DIR):
        return []
    return sorted(
        os.path.splitext(f)[0]
        for f in os.listdir(_SCENARIO_DIR)
        if f.endswith(".yaml")
    )


def load_scenario(name_or_path: str) -> Scenario:
    """Load a scenario by bundled name or filesystem path."""
    path = name_or_path
    if not os.path.exists(path):
        bundled = os.path.join(_SCENARIO_DIR, f"{name_or_path}.yaml")
        if os.path.exists(bundled):
            path = bundled
        else:
            raise FileNotFoundError(
                f"no scenario file {name_or_path!r} and no bundled "
                f"scenario of that name (bundled: {bundled_scenarios()})"
            )
    with open(path) as f:
        data = yaml.safe_load(f)
    if not isinstance(data, dict):
        raise ValueError(f"scenario file {path} is not a YAML mapping")
    return Scenario.from_dict(data)
