"""Goodput/tail regression gate:

    python -m vgate_tpu.loadlab.compare old.jsonl new.jsonl

Exits nonzero when the new artifact regresses against the old one
beyond thresholds, so perf PRs can gate on a recorded baseline:

* per-tier goodput in any matching QPS cell drops more than
  ``--max-goodput-drop`` (absolute fraction, default 0.05),
* TTFT p99 in any matching cell/tier rises more than
  ``--max-tail-rise`` (relative, default 0.25) AND by more than an
  absolute floor (``--tail-floor-ms``, default 50 — sub-floor jitter on
  fast cells is noise, not regression),
* a summary knee moved DOWN a cell: ``max_goodput_qps`` (highest cell
  sustaining goodput >= target) or ``knee_qps`` (peak delivered
  good-QPS).

Cells match on offered QPS; tiers with fewer than ``--min-samples``
requests on either side are skipped (tail statistics on a handful of
requests gate nothing).  ``--cells`` restricts the per-cell gates to
the listed QPS values when only one regime is under test (e.g.
``--cells 14`` gates the overload cell; the summary knee gates are
then skipped — a partial view cannot see a knee move).  Artifacts from different scenarios (name or
content hash) refuse to compare unless ``--allow-cross-scenario``, and
different server-config fingerprints refuse unless
``--allow-config-change`` (the scenario hash cannot see env-exported
server overrides; the fingerprint can).

Exit codes: 0 clean, 1 regression(s), 2 usage/load error.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional

from .slo import load_artifact


def _cells_by_qps(art: Dict[str, Any]) -> Dict[float, Dict[str, Any]]:
    return {c["qps"]: c for c in art.get("cells", [])}


def _tier_p99(tier_row: Dict[str, Any]) -> Optional[float]:
    return (tier_row.get("ttft_ms") or {}).get("p99")


def compare(
    old: Dict[str, Any],
    new: Dict[str, Any],
    *,
    max_goodput_drop: float = 0.05,
    max_tail_rise: float = 0.25,
    tail_floor_ms: float = 50.0,
    min_samples: int = 8,
    cells: Optional[List[float]] = None,
) -> List[Dict[str, Any]]:
    """Returns the regression list (empty = gate passes).  ``cells``
    restricts the per-cell gates (goodput/tail) to the listed QPS
    values — for gates that target one regime (e.g. the overload
    cell), where a quiet cell's handful of samples would only add
    noise; the summary knee gates are skipped under a filter, since a
    partial view cannot see a knee move."""
    regressions: List[Dict[str, Any]] = []
    old_cells = _cells_by_qps(old)
    new_cells = _cells_by_qps(new)
    gated = set(old_cells) & set(new_cells)
    if cells is not None:
        gated &= set(cells)
    for qps in sorted(gated):
        o_cell, n_cell = old_cells[qps], new_cells[qps]
        if not o_cell.get("valid", True) or not n_cell.get("valid", True):
            continue  # a lag-invalidated cell gates nothing
        o_tiers = o_cell.get("tiers") or {}
        n_tiers = n_cell.get("tiers") or {}
        for tier in sorted(set(o_tiers) & set(n_tiers)):
            o_t, n_t = o_tiers[tier], n_tiers[tier]
            if (
                o_t.get("n", 0) < min_samples
                or n_t.get("n", 0) < min_samples
            ):
                continue
            o_g, n_g = o_t.get("goodput"), n_t.get("goodput")
            if (
                o_g is not None and n_g is not None
                and o_g - n_g > max_goodput_drop
            ):
                regressions.append({
                    "kind": "goodput_drop",
                    "qps": qps,
                    "tier": tier,
                    "old": o_g,
                    "new": n_g,
                    "threshold": max_goodput_drop,
                    "msg": (
                        f"goodput regression: {tier}@{qps:g}qps "
                        f"{o_g:.3f} -> {n_g:.3f} "
                        f"(drop {o_g - n_g:.3f} > {max_goodput_drop})"
                    ),
                })
            o_p99, n_p99 = _tier_p99(o_t), _tier_p99(n_t)
            # the tail gate needs real TTFT samples, not offered
            # requests: a mostly-shed tier can have n=45 offered but a
            # p99 computed over 2 completions — noise, not signal
            o_tn = (o_t.get("ttft_ms") or {}).get("n", 0)
            n_tn = (n_t.get("ttft_ms") or {}).get("n", 0)
            if (
                o_p99 is not None and n_p99 is not None
                and o_tn >= min_samples and n_tn >= min_samples
                and n_p99 - o_p99 > tail_floor_ms
                and o_p99 > 0
                and (n_p99 - o_p99) / o_p99 > max_tail_rise
            ):
                regressions.append({
                    "kind": "tail_rise",
                    "qps": qps,
                    "tier": tier,
                    "old": o_p99,
                    "new": n_p99,
                    "threshold": max_tail_rise,
                    "msg": (
                        f"TTFT p99 regression: {tier}@{qps:g}qps "
                        f"{o_p99:.0f}ms -> {n_p99:.0f}ms "
                        f"(+{(n_p99 - o_p99) / o_p99 * 100:.0f}% > "
                        f"{max_tail_rise * 100:.0f}%)"
                    ),
                })
    o_sum = old.get("summary") or {}
    n_sum = new.get("summary") or {}
    # summary gates are only comparable when both sweeps offered the
    # same cells and no cell was lag-invalidated — a partial or
    # corrupted rerun must not read as a knee move
    summaries_comparable = (
        cells is None
        and o_sum.get("cells") == n_sum.get("cells")
        and not o_sum.get("invalid_cells")
        and not n_sum.get("invalid_cells")
    )
    for key, label in (
        ("max_goodput_qps", "max-goodput-QPS"),
        ("knee_qps", "delivered-goodput knee"),
    ):
        o_knee, n_knee = o_sum.get(key), n_sum.get(key)
        if (
            summaries_comparable
            and o_knee is not None
            and (n_knee is None or n_knee < o_knee)
        ):
            regressions.append({
                "kind": "knee_drop",
                "metric": key,
                "old": o_knee,
                "new": n_knee,
                "msg": (
                    f"{label} moved down: {o_knee:g} -> "
                    f"{n_knee if n_knee is not None else 'none'}"
                ),
            })
    return regressions


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vgate_tpu.loadlab.compare",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("old", help="baseline artifact (jsonl)")
    parser.add_argument("new", help="candidate artifact (jsonl)")
    parser.add_argument("--max-goodput-drop", type=float, default=0.05)
    parser.add_argument("--max-tail-rise", type=float, default=0.25)
    parser.add_argument("--tail-floor-ms", type=float, default=50.0)
    parser.add_argument("--min-samples", type=int, default=8)
    parser.add_argument(
        "--cells", type=float, nargs="+", default=None,
        help="gate only these QPS cells (e.g. --cells 14 gates the "
             "overload cell of a 2-cell sweep; summary knee gates are "
             "skipped under a filter)",
    )
    parser.add_argument(
        "--allow-cross-scenario", action="store_true",
        help="compare artifacts even when scenario name/hash differ "
             "(implies --allow-config-change)",
    )
    parser.add_argument(
        "--allow-config-change", action="store_true",
        help="compare artifacts whose server config fingerprints "
             "differ (e.g. gating an intentional config-default flip)",
    )
    args = parser.parse_args(argv)
    try:
        old = load_artifact(args.old)
        new = load_artifact(args.new)
    except (OSError, ValueError) as exc:
        print(f"compare: cannot load artifacts: {exc}", file=sys.stderr)
        return 2
    o_meta, n_meta = old["meta"], new["meta"]
    if not args.allow_cross_scenario:
        if o_meta.get("scenario") != n_meta.get("scenario") or (
            o_meta.get("scenario_hash") != n_meta.get("scenario_hash")
        ):
            print(
                "compare: artifacts are from different scenarios "
                f"({o_meta.get('scenario')}/{o_meta.get('scenario_hash')}"
                f" vs {n_meta.get('scenario')}/"
                f"{n_meta.get('scenario_hash')}); pass "
                "--allow-cross-scenario to override",
                file=sys.stderr,
            )
            return 2
    # the scenario hash only covers the YAML; env-exported overrides
    # (r6_session re-points one scenario at 7B / int8 KV) change the
    # SERVER, which the config fingerprint (hashed /stats config block)
    # catches — a different config is a different experiment
    o_fp = o_meta.get("config_fingerprint")
    n_fp = n_meta.get("config_fingerprint")
    if (
        o_fp and n_fp and o_fp != n_fp
        and not args.allow_config_change
        and not args.allow_cross_scenario
    ):
        print(
            "compare: artifacts were measured against differently-"
            f"configured servers (config_fingerprint {o_fp} vs {n_fp});"
            " pass --allow-config-change if the config change is the "
            "thing under test",
            file=sys.stderr,
        )
        return 2
    if o_meta.get("platform") != n_meta.get("platform"):
        print(
            f"compare: WARNING platform changed "
            f"{o_meta.get('platform')} -> {n_meta.get('platform')} — "
            "latency comparisons across platforms are not meaningful",
            file=sys.stderr,
        )
    if args.cells:
        # a filter that matches nothing would silently disable every
        # gate and exit 0 — a typo'd QPS or a scenario whose cells
        # drifted from the recorded baseline must fail loudly, not
        # vacuously pass
        common = {c["qps"] for c in old.get("cells", [])} & {
            c["qps"] for c in new.get("cells", [])
        }
        missing = [q for q in args.cells if q not in common]
        if missing:
            print(
                f"compare: --cells {missing} match no cell present in "
                f"both artifacts (common cells: {sorted(common)})",
                file=sys.stderr,
            )
            return 2
    regressions = compare(
        old, new,
        max_goodput_drop=args.max_goodput_drop,
        max_tail_rise=args.max_tail_rise,
        tail_floor_ms=args.tail_floor_ms,
        min_samples=args.min_samples,
        cells=args.cells,
    )
    if regressions:
        print(f"FAIL: {len(regressions)} regression(s)")
        for r in regressions:
            print(f"  - {r['msg']}")
        return 1
    print(
        f"PASS: no goodput/tail regressions "
        f"({len(old.get('cells', []))} baseline cells vs "
        f"{len(new.get('cells', []))} candidate cells)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
