"""SLO grading: samples -> per-tier goodput per QPS cell, knee
detection, and the machine-readable JSONL artifact the compare tool
gates on.

Goodput semantics: a request is GOOD when it completed cleanly AND met
every bound in its tier's SLOSpec (ttft/tpot/e2e).  The denominator is
every request offered to that tier in the cell — errors, sheds and
timeouts all count against goodput.  A tier with no SLO grades on
clean completion alone (availability goodput).

Artifact layout (one JSON object per line):

    {"kind": "meta", "schema": "vgate.loadlab/v1", ...}   # stamp
    {"kind": "cell", "qps": 2.0, "tiers": {...}, ...}     # per cell
    {"kind": "summary", "max_goodput_qps": ..., ...}      # knee et al

The schema field list is pinned by tests/test_loadlab.py — additive
evolution only (compare must keep reading old artifacts).
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .driver import Sample
from .scenario import SLOSpec

SCHEMA = "vgate.loadlab/v1"

# pinned by test_loadlab.py::test_artifact_schema_stability — widen,
# never narrow or rename
META_REQUIRED = (
    "kind", "schema", "scenario", "scenario_hash", "seed", "ts",
    "platform", "device", "git_sha", "config_fingerprint", "base_url",
    "slos",
)
CELL_REQUIRED = (
    "kind", "qps", "offered", "completed", "duration_s", "tiers",
    "overall", "unhandled_errors", "send_lag_p99_s", "valid", "perf",
)
SUMMARY_REQUIRED = (
    "kind", "max_goodput_qps", "knee_qps", "per_tier_max_goodput_qps",
    "unhandled_errors", "cells",
)

# a cell "sustains" its offered QPS when goodput clears this; the knee
# summary reports the highest such cell
GOODPUT_TARGET = 0.9

# failure kinds that mean the LAB (not the server) misbehaved; drills
# assert the artifact reports zero of these
UNHANDLED_KINDS = ("driver_error", "transport", "cancelled")


def percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile on an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def dist_ms(values_s: Iterable[Optional[float]]) -> Dict[str, Any]:
    """{p50,p95,p99,mean,max,n} in milliseconds over the non-None
    values."""
    vals = sorted(v for v in values_s if v is not None)
    if not vals:
        return {"n": 0}
    return {
        "n": len(vals),
        "mean": round(sum(vals) / len(vals) * 1000, 1),
        "p50": round(percentile(vals, 0.50) * 1000, 1),
        "p95": round(percentile(vals, 0.95) * 1000, 1),
        "p99": round(percentile(vals, 0.99) * 1000, 1),
        "max": round(vals[-1] * 1000, 1),
    }


def meets_slo(sample: Sample, spec: Optional[SLOSpec]) -> bool:
    if not sample.ok:
        return False
    if spec is None:
        return True
    if spec.ttft_ms is not None and (
        sample.ttft_s is None or sample.ttft_s * 1000 > spec.ttft_ms
    ):
        return False
    if spec.tpot_ms is not None and (
        sample.tpot_s is not None and sample.tpot_s * 1000 > spec.tpot_ms
    ):
        return False
    if spec.e2e_ms is not None and (
        sample.e2e_s is None or sample.e2e_s * 1000 > spec.e2e_ms
    ):
        return False
    return True


def grade_cell(
    samples: List[Sample],
    slos: Dict[str, SLOSpec],
    *,
    qps: float,
    duration_s: float,
) -> Dict[str, Any]:
    """One artifact ``cell`` line (minus the server-side block the
    runner merges in)."""
    tiers: Dict[str, Dict[str, Any]] = {}
    by_tier: Dict[str, List[Sample]] = {}
    for s in samples:
        by_tier.setdefault(s.tier, []).append(s)
    for tier, rows in sorted(by_tier.items()):
        spec = slos.get(tier)
        good = sum(1 for s in rows if meets_slo(s, spec))
        errors: Dict[str, int] = {}
        for s in rows:
            if s.kind != "ok":
                errors[s.kind] = errors.get(s.kind, 0) + 1
        tiers[tier] = {
            "n": len(rows),
            "ok": sum(1 for s in rows if s.ok),
            "slo_met": good,
            "goodput": round(good / len(rows), 4) if rows else None,
            "ttft_ms": dist_ms(s.ttft_s for s in rows if s.ok),
            "tpot_ms": dist_ms(s.tpot_s for s in rows if s.ok),
            "e2e_ms": dist_ms(s.e2e_s for s in rows if s.ok),
            "errors": errors,
            "slo": slos[tier].to_dict() if tier in slos else None,
        }
    n = len(samples)
    good_all = sum(
        1 for s in samples if meets_slo(s, slos.get(s.tier))
    )
    unhandled = sum(1 for s in samples if s.kind in UNHANDLED_KINDS)
    lag = sorted(s.send_lag_s for s in samples)
    lag_p99 = percentile(lag, 0.99) or 0.0
    from .driver import SEND_LAG_BOUND_S

    return {
        "kind": "cell",
        "qps": qps,
        "duration_s": duration_s,
        "offered": n,
        "completed": sum(1 for s in samples if s.ok),
        "tiers": tiers,
        "overall": {
            "goodput": round(good_all / n, 4) if n else None,
            "ok": sum(1 for s in samples if s.ok),
            "good_qps": round(good_all / duration_s, 3)
            if duration_s > 0 else None,
        },
        "unhandled_errors": unhandled,
        "send_lag_p99_s": round(lag_p99, 4),
        # a cell where the measuring host itself lagged is stamped
        # invalid rather than silently reported (client-side clipping
        # corrupts tails in the flattering direction)
        "valid": lag_p99 <= SEND_LAG_BOUND_S,
        # server-side perf attribution for the cell ("where did the
        # time go"): the runner overwrites this with the /debug/perf
        # delta; None when the server has no attribution surface
        "perf": None,
    }


# -- knee detection -------------------------------------------------------

def max_goodput_qps(
    cells: List[Tuple[float, Optional[float]]],
    target: float = GOODPUT_TARGET,
) -> Optional[float]:
    """Highest offered QPS whose goodput clears ``target`` (None when no
    cell does).  This is the headline "max goodput QPS" number."""
    ok = [q for q, g in cells if g is not None and g >= target]
    return max(ok) if ok else None


def knee_qps(
    cells: List[Tuple[float, Optional[float]]]
) -> Optional[float]:
    """The saturation knee: the offered QPS after which DELIVERED good
    throughput (qps x goodput) stops improving.  Scanning in offered-QPS
    order, returns the cell with peak delivered goodput — past the knee,
    offering more traffic returns less good work."""
    best_q: Optional[float] = None
    best_delivered = -1.0
    for q, g in sorted(cells):
        if g is None:
            continue
        delivered = q * g
        if delivered > best_delivered:
            best_delivered = delivered
            best_q = q
    return best_q


def summarize(
    cell_lines: List[Dict[str, Any]]
) -> Dict[str, Any]:
    """The artifact ``summary`` line from its cell lines."""
    # lag-invalidated cells carry untrustworthy goodput — they appear
    # in `cells`/`invalid_cells` but never feed the knee numbers
    valid_cells = [c for c in cell_lines if c.get("valid", True)]
    overall = [
        (c["qps"], (c.get("overall") or {}).get("goodput"))
        for c in valid_cells
    ]
    per_tier: Dict[str, List[Tuple[float, Optional[float]]]] = {}
    for c in valid_cells:
        for tier, t in (c.get("tiers") or {}).items():
            per_tier.setdefault(tier, []).append(
                (c["qps"], t.get("goodput"))
            )
    return {
        "kind": "summary",
        "cells": [c["qps"] for c in cell_lines],
        "max_goodput_qps": max_goodput_qps(overall),
        "knee_qps": knee_qps(overall),
        "per_tier_max_goodput_qps": {
            tier: max_goodput_qps(rows)
            for tier, rows in sorted(per_tier.items())
        },
        "goodput_target": GOODPUT_TARGET,
        "unhandled_errors": sum(
            c.get("unhandled_errors", 0) for c in cell_lines
        ),
        "invalid_cells": [
            c["qps"] for c in cell_lines if not c.get("valid", True)
        ],
    }


# -- artifact io ----------------------------------------------------------

def write_artifact(path: str, lines: List[Dict[str, Any]]) -> None:
    with open(path, "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")


def load_artifact(path: str) -> Dict[str, Any]:
    """Parse an artifact back into {meta, cells, summary}; raises on a
    file that is not a loadlab artifact."""
    meta: Optional[Dict[str, Any]] = None
    cells: List[Dict[str, Any]] = []
    summary: Optional[Dict[str, Any]] = None
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            row = json.loads(raw)
            kind = row.get("kind")
            if kind == "meta":
                meta = row
            elif kind == "cell":
                cells.append(row)
            elif kind == "summary":
                summary = row
    if meta is None or meta.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: not a {SCHEMA} artifact (missing/foreign meta line)"
        )
    return {"meta": meta, "cells": cells, "summary": summary}


def validate_lines(lines: List[Dict[str, Any]]) -> List[str]:
    """Schema self-check: list of missing-key complaints (empty = ok)."""
    problems: List[str] = []
    required = {
        "meta": META_REQUIRED, "cell": CELL_REQUIRED,
        "summary": SUMMARY_REQUIRED,
    }
    for i, line in enumerate(lines):
        kind = line.get("kind")
        for key in required.get(kind, ()):
            if key not in line:
                problems.append(f"line {i} ({kind}): missing {key!r}")
    return problems
