"""SLO-graded workload lab: open-loop load generation, scenario traffic
suite, and goodput regression gating (ISSUE 11).

The lab drives the REAL HTTP server (never the engine directly — the
gateway, admission, batcher and SSE path are part of what is measured)
with pre-computed open-loop arrival schedules, grades what the client
observed against per-tier SLOs, and writes a stamped JSONL artifact
that ``python -m vgate_tpu.loadlab.compare`` gates perf PRs on.

Entry points:

    python -m vgate_tpu.loadlab run --scenario smoke_mixed \
        --base-url http://127.0.0.1:8000 --out new.jsonl
    python -m vgate_tpu.loadlab run --scenario smoke_mixed --launch
    python -m vgate_tpu.loadlab.compare old.jsonl new.jsonl

This package is deliberately jax-free: it must run from any client
host, including one with a wedged TPU grant.
"""

from .scenario import (  # noqa: F401
    ArrivalSpec,
    ChaosSpec,
    Scenario,
    SLOSpec,
    TrafficMix,
    bundled_scenarios,
    load_scenario,
)

__all__ = [
    "ArrivalSpec",
    "ChaosSpec",
    "Scenario",
    "SLOSpec",
    "TrafficMix",
    "bundled_scenarios",
    "load_scenario",
]
