"""Open-loop arrival processes for the workload lab.

Every generator returns **absolute arrival offsets** (seconds from the
cell's t0), computed up front from a seeded RNG.  The driver sleeps
until each offset and fires — it never waits for a previous response —
so arrivals cannot back off when the server slows down.  That is the
open-loop property this whole subsystem exists for: a closed-loop
driver (fire, await, fire) self-throttles under overload and reports a
flattering, meaningless latency curve exactly when the measurement
matters most (the comparative vLLM/TGI serving study in PAPERS.md
grades on open-loop tail latency for the same reason).

Determinism: same (process, rate, duration, seed) -> identical
timestamps, so two artifact runs compare cell-for-cell.
"""

from __future__ import annotations

import random
from typing import List

PROCESSES = ("poisson", "constant", "bursty")


def poisson(rate_qps: float, duration_s: float, seed: int) -> List[float]:
    """Homogeneous Poisson process: exponential inter-arrivals at
    ``rate_qps``, truncated at ``duration_s``."""
    if rate_qps <= 0 or duration_s <= 0:
        return []
    rng = random.Random(seed)
    out: List[float] = []
    t = rng.expovariate(rate_qps)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(rate_qps)
    return out


def constant(rate_qps: float, duration_s: float, seed: int = 0) -> List[float]:
    """Evenly spaced arrivals (the metronome arm: isolates queueing
    effects from arrival burstiness).  ``seed`` accepted for signature
    parity; the process is deterministic by construction."""
    if rate_qps <= 0 or duration_s <= 0:
        return []
    gap = 1.0 / rate_qps
    n = int(duration_s * rate_qps)
    return [i * gap for i in range(n) if i * gap < duration_s]


def bursty(
    rate_qps: float,
    duration_s: float,
    seed: int,
    on_s: float = 2.0,
    off_s: float = 4.0,
    burst_mult: float = 3.0,
) -> List[float]:
    """On/off-modulated Poisson (flash-crowd shape): alternating windows
    of ``on_s`` seconds at ``rate_qps * burst_mult`` and ``off_s``
    seconds at a compensating lower rate, chosen so the long-run mean
    stays ``rate_qps`` (an overload curve swept with bursty arrivals
    must be comparable to the Poisson sweep at the same offered QPS).

    ``burst_mult`` is clamped so the off-window rate never goes
    negative: burst_mult <= (on_s + off_s) / on_s.
    """
    if rate_qps <= 0 or duration_s <= 0:
        return []
    if on_s <= 0 or off_s < 0:
        raise ValueError("bursty arrivals need on_s > 0 and off_s >= 0")
    cycle = on_s + off_s
    burst_mult = min(burst_mult, cycle / on_s)
    rate_on = rate_qps * burst_mult
    rate_off = (
        (rate_qps * cycle - rate_on * on_s) / off_s if off_s > 0 else 0.0
    )
    rng = random.Random(seed)
    out: List[float] = []
    window_start = 0.0
    while window_start < duration_s:
        for width, rate in ((on_s, rate_on), (off_s, rate_off)):
            if width <= 0 or rate <= 0:
                window_start += width
                continue
            end = min(window_start + width, duration_s)
            t = window_start + rng.expovariate(rate)
            while t < end:
                out.append(t)
                t += rng.expovariate(rate)
            window_start = window_start + width
            if window_start >= duration_s:
                break
    return out


def generate(
    process: str,
    rate_qps: float,
    duration_s: float,
    seed: int,
    **kwargs: float,
) -> List[float]:
    """Dispatch by process name (the scenario YAML's ``arrival.process``
    field).  Unknown names raise so a typo'd scenario fails at load, not
    after a 30-minute sweep."""
    if process == "poisson":
        return poisson(rate_qps, duration_s, seed)
    if process == "constant":
        return constant(rate_qps, duration_s, seed)
    if process == "bursty":
        return bursty(rate_qps, duration_s, seed, **kwargs)
    raise ValueError(
        f"unknown arrival process {process!r}; valid: {PROCESSES}"
    )
