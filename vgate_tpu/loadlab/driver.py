"""Async open-loop driver: fires a pre-planned arrival schedule at a
live vgate-tpu server and measures what a CLIENT observes.

Measured per request (client truth — not the server's self-report):

* **TTFT** — first SSE chunk carrying non-empty delta content (for
  streams) or the full response (non-streaming), from the moment the
  request was DUE to be sent.  Late sends (event-loop lag) are folded
  into latency, not silently excused: an overloaded client host shows
  up as `send_lag` in the sample, and the lab refuses the cell when lag
  grows past a bound rather than report corrupted numbers.
* **TPOT** — mean inter-chunk gap after the first content chunk.
* **e2e** — due-time to last byte.
* **error taxonomy** — every failure is a typed `kind`
  (http_503_overloaded / http_503_recovering / http_429 /
  http_504_partial / sse_timeout_error / client_timeout / transport
  ...).  `driver_error` means the lab itself broke — drills assert it
  never happens.

Open-loop discipline: every arrival is its own task sleeping until its
ABSOLUTE due time; nothing awaits a previous response.  Server slowness
changes completions, never offered load.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp

from .workload import PlannedRequest

# sends this late mean the measuring host (not the server) saturated —
# cells with a worse p99 send lag are stamped invalid by the runner
SEND_LAG_BOUND_S = 0.25


@dataclass
class Sample:
    tier: str
    shape: str
    offset_s: float
    kind: str = "ok"  # typed outcome; "ok" only for clean completions
    ok: bool = False
    status: Optional[int] = None
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    e2e_s: Optional[float] = None
    tokens: int = 0
    send_lag_s: float = 0.0
    stream: bool = False
    error: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "tier": self.tier, "shape": self.shape, "kind": self.kind,
            "ok": self.ok, "status": self.status,
            "ttft_s": self.ttft_s, "tpot_s": self.tpot_s,
            "e2e_s": self.e2e_s, "tokens": self.tokens,
            "send_lag_s": round(self.send_lag_s, 4),
            "stream": self.stream,
        }
        if self.error:
            d["error"] = self.error[:300]
        return d


def classify_http_error(status: int, payload: Any) -> str:
    """Map an HTTP failure to its typed kind using the server's own
    machine-readable `reason` taxonomy (PR-4: every RetryableError 503
    carries one)."""
    err = payload.get("error", {}) if isinstance(payload, dict) else {}
    if status == 503:
        reason = err.get("reason")
        return f"http_503_{reason}" if reason else "http_503"
    if status == 429:
        return "http_429"
    if status == 504:
        meta = err.get("metadata") or {}
        partial = (
            meta.get("partial_tokens") or err.get("partial_tokens")
        )
        return "http_504_partial" if partial else "http_504"
    return f"http_{status}"


async def _consume_sse(
    resp: aiohttp.ClientResponse, sample: Sample, due_t: float,
    loop: asyncio.AbstractEventLoop,
) -> None:
    """Walk the SSE stream, stamping first/last content-chunk times."""
    first_t: Optional[float] = None
    last_t: Optional[float] = None
    n_chunks = 0
    error_event: Optional[str] = None
    done_seen = False
    async for raw in resp.content:
        line = raw.strip()
        if not line.startswith(b"data: "):
            continue
        payload = line[len(b"data: "):]
        if payload == b"[DONE]":
            done_seen = True
            break
        try:
            event = json.loads(payload)
        except ValueError:
            continue
        if "error" in event:
            error_event = event["error"].get("type") or "error"
            continue
        choices = event.get("choices") or []
        delta = choices[0].get("delta", {}) if choices else {}
        if delta.get("content"):
            now = loop.time()
            if first_t is None:
                first_t = now
            last_t = now
            n_chunks += 1
        usage = event.get("usage")
        if usage and usage.get("completion_tokens"):
            sample.extra["completion_tokens"] = usage["completion_tokens"]
    end_t = loop.time()
    sample.e2e_s = end_t - due_t
    # chunk count is a floor for tokens (stop-holdback merges tokens
    # into one delta); prefer the server-reported usage when present
    sample.tokens = sample.extra.get("completion_tokens", n_chunks)
    if first_t is not None:
        sample.ttft_s = first_t - due_t
        if last_t is not None and n_chunks > 1:
            sample.tpot_s = (last_t - first_t) / (n_chunks - 1)
    if error_event is not None:
        sample.kind = f"sse_{error_event}"
        sample.error = error_event
    elif not done_seen:
        sample.kind = "sse_truncated"
    elif first_t is None:
        sample.kind = "sse_empty"
    else:
        sample.kind = "ok"
        sample.ok = True


async def _fire(
    session: aiohttp.ClientSession,
    base_url: str,
    req: PlannedRequest,
    t0: float,
    timeout_s: float,
    samples: List[Sample],
) -> None:
    loop = asyncio.get_running_loop()
    due_t = t0 + req.offset_s
    delay = due_t - loop.time()
    if delay > 0:
        await asyncio.sleep(delay)
    sample = Sample(
        tier=req.tier, shape=req.shape, offset_s=req.offset_s,
        stream=req.stream,
        send_lag_s=max(0.0, loop.time() - due_t),
    )
    samples.append(sample)
    try:
        async with session.post(
            base_url + req.endpoint,
            json=req.body,
            timeout=aiohttp.ClientTimeout(total=timeout_s),
        ) as resp:
            sample.status = resp.status
            ctype = resp.headers.get("Content-Type", "")
            if resp.status == 200 and "text/event-stream" in ctype:
                await _consume_sse(resp, sample, due_t, loop)
            else:
                try:
                    payload = await resp.json()
                except Exception:
                    payload = None
                sample.e2e_s = loop.time() - due_t
                if resp.status == 200:
                    sample.kind = "ok"
                    sample.ok = True
                    # non-streaming: first byte IS the full body
                    sample.ttft_s = sample.e2e_s
                    usage = (
                        payload.get("usage", {})
                        if isinstance(payload, dict) else {}
                    )
                    sample.tokens = usage.get("completion_tokens", 0)
                else:
                    sample.kind = classify_http_error(resp.status, payload)
                    sample.error = json.dumps(payload)[:300] if payload \
                        else None
    # both spellings: on py3.10 asyncio.TimeoutError is not the builtin
    except (TimeoutError, asyncio.TimeoutError):
        sample.e2e_s = loop.time() - due_t
        sample.kind = "client_timeout"
    except aiohttp.ClientError as exc:
        sample.e2e_s = loop.time() - due_t
        sample.kind = "transport"
        sample.error = repr(exc)
    except asyncio.CancelledError:
        sample.kind = "cancelled"
        raise
    except Exception as exc:  # noqa: BLE001 — the lab must never lose a
        # sample: an unclassified failure is a typed driver_error the
        # drills assert to be zero
        sample.e2e_s = loop.time() - due_t
        sample.kind = "driver_error"
        sample.error = repr(exc)


async def drive_cell(
    base_url: str,
    plan: List[PlannedRequest],
    *,
    timeout_s: float = 60.0,
    headers: Optional[Dict[str, str]] = None,
    extra_tasks: Optional[List[Any]] = None,
) -> List[Sample]:
    """Fire one cell's plan open-loop; returns every sample (len ==
    len(plan) — no request is ever dropped).  ``extra_tasks`` are
    awaitables run alongside the load (chaos arming, watchers); their
    failures are re-raised after the cell completes."""
    samples: List[Sample] = []
    connector = aiohttp.TCPConnector(limit=0)  # open loop: no conn cap
    loop = asyncio.get_running_loop()
    async with aiohttp.ClientSession(
        connector=connector, headers=headers
    ) as session:
        t0 = loop.time()
        tasks = [
            asyncio.ensure_future(
                _fire(session, base_url, req, t0, timeout_s, samples)
            )
            for req in plan
        ]
        side = [
            asyncio.ensure_future(t) for t in (extra_tasks or [])
        ]
        await asyncio.gather(*tasks)
        for s in side:
            if not s.done():
                s.cancel()
        side_results = await asyncio.gather(*side, return_exceptions=True)
    for r in side_results:
        if isinstance(r, Exception) and not isinstance(
            r, asyncio.CancelledError
        ):
            raise r
    return samples


async def run_serial(
    base_url: str,
    plan: List[PlannedRequest],
    *,
    timeout_s: float = 60.0,
) -> List[Sample]:
    """Serial (closed-loop, unmeasured) pass — used only for warmup."""
    samples: List[Sample] = []
    loop = asyncio.get_running_loop()
    async with aiohttp.ClientSession() as session:
        for req in plan:
            await _fire(
                session, base_url, req, loop.time() - req.offset_s,
                timeout_s, samples,
            )
    return samples
