"""Deterministic request synthesis: scenario mixes -> concrete HTTP
request plans.

Everything derives from the scenario seed + cell index + arrival index,
so the same scenario offers byte-identical traffic on every run (the
compare tool depends on it) while still exercising prefix sharing:
requests in the same cohort share system/corpus preambles verbatim, and
multi-turn users re-send their own growing transcript — the shapes the
PR-6 radix cache keys on.

Open-loop note: multi-turn transcripts are PRE-generated (the
"assistant" turns are synthesized filler, not the server's live
answers).  A closed-loop chat replay would condition turn N+1's send
time on turn N's completion — exactly the feedback loop this lab
refuses.  Prompt-side prefix reuse (the dominant term) is preserved;
generated-token reuse is measured separately by
benchmarks/bench_prefix.py.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, List

from .scenario import Scenario, TrafficMix

# tokenizer-agnostic filler vocabulary: wide enough that prefixes only
# collide when the generator MEANS them to collide
_WORDS = [
    "latency", "tensor", "batch", "page", "prefill", "decode", "cache",
    "shard", "router", "replica", "kernel", "systolic", "bandwidth",
    "queue", "token", "stream", "admission", "tier", "goodput", "knee",
    "roofline", "mesh", "pallas", "vector", "scalar", "matrix", "fused",
    "paged", "radix", "prefix", "chunk", "bucket", "slot", "grant",
]


def _words(rng: random.Random, n: int) -> str:
    return " ".join(rng.choice(_WORDS) for _ in range(max(1, n)))


@dataclass
class PlannedRequest:
    """One concrete request the driver will fire at ``offset_s``."""

    offset_s: float
    endpoint: str  # /v1/chat/completions | /v1/embeddings
    body: Dict[str, Any]
    tier: str
    shape: str
    stream: bool
    index: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)


def _chat_body(
    mix: TrafficMix, messages: List[Dict[str, str]]
) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        "messages": messages,
        "max_tokens": mix.max_tokens,
        "temperature": 0.0,
        "priority": mix.tier,
    }
    if mix.stream:
        body["stream"] = True
        body["stream_options"] = {"include_usage": True}
    return body


def _build_one(
    mix: TrafficMix, rng: random.Random, state: Dict[str, Any]
) -> Dict[str, Any]:
    """Endpoint + body for one request of ``mix``.  ``state`` carries
    per-mix cohort structures (shared prefixes, user transcripts)."""
    if mix.shape == "embeddings":
        return {
            "endpoint": "/v1/embeddings",
            "body": {
                "input": _words(rng, mix.prompt_units),
                "priority": mix.tier,
            },
            "stream": False,
        }

    if mix.shape == "rag":
        # shared corpus passages: every request opens with one of
        # num_docs verbatim preambles (the radix tree indexes each the
        # first time it is seen), then asks a unique question
        docs = state.setdefault("docs", {})
        doc_id = rng.randrange(max(1, mix.num_docs))
        if doc_id not in docs:
            doc_rng = random.Random(0x5A6 + doc_id)
            docs[doc_id] = _words(
                doc_rng, max(8, mix.shared_prefix_units or 32)
            )
        question = _words(rng, max(4, mix.prompt_units // 4))
        messages = [
            {
                "role": "system",
                "content": f"Answer from the passage. Passage {doc_id}: "
                           f"{docs[doc_id]}",
            },
            {"role": "user", "content": f"Question: {question}"},
        ]
        return {
            "endpoint": "/v1/chat/completions",
            "body": _chat_body(mix, messages),
            "stream": mix.stream,
        }

    if mix.shape == "multi_turn_chat":
        # cohort of group_size users sharing one system prompt; each
        # request advances one user's transcript by a turn and re-sends
        # the whole history (the growing-prefix shape)
        users = state.setdefault("users", {})
        system = state.setdefault(
            "system",
            "You are a concise serving-systems assistant. "
            + _words(random.Random(7), max(0, mix.shared_prefix_units)),
        )
        uid = rng.randrange(max(1, mix.group_size))
        history = users.setdefault(uid, [])
        if len(history) >= 2 * mix.turns:
            history.clear()  # user starts a fresh conversation
        history.append(
            {"role": "user",
             "content": _words(rng, max(4, mix.prompt_units // 2))}
        )
        messages = (
            [{"role": "system", "content": system}] + list(history)
        )
        # synthesize the assistant's reply into the transcript so the
        # NEXT turn re-sends it (prefix growth without closing the loop)
        history.append(
            {"role": "assistant",
             "content": _words(rng, max(4, mix.max_tokens // 2))}
        )
        return {
            "endpoint": "/v1/chat/completions",
            "body": _chat_body(mix, messages),
            "stream": mix.stream,
        }

    # chat / long_context: single turn, optional shared system prefix
    messages = []
    if mix.shared_prefix_units > 0:
        system = state.setdefault(
            "system",
            "You are a helpful assistant. "
            + _words(random.Random(11), mix.shared_prefix_units),
        )
        messages.append({"role": "system", "content": system})
    messages.append(
        {"role": "user", "content": _words(rng, mix.prompt_units)}
    )
    return {
        "endpoint": "/v1/chat/completions",
        "body": _chat_body(mix, messages),
        "stream": mix.stream,
    }


def build_plan(
    scenario: Scenario, cell_index: int, qps: float
) -> List[PlannedRequest]:
    """Arrivals + mix assignment + request synthesis for one sweep cell.

    The arrival process and the mix/content RNGs are seeded
    independently (seed, cell, purpose) so changing the traffic mix
    never perturbs the arrival timestamps and vice versa.
    """
    offsets = scenario.arrival.generate(
        qps, scenario.duration_s, seed=scenario.seed * 1009 + cell_index
    )
    mix_rng = random.Random(scenario.seed * 9176 + cell_index)
    weights = [m.weight for m in scenario.mixes]
    states: List[Dict[str, Any]] = [{} for _ in scenario.mixes]
    plan: List[PlannedRequest] = []
    for i, offset in enumerate(offsets):
        (mix_i,) = mix_rng.choices(range(len(scenario.mixes)), weights)
        mix = scenario.mixes[mix_i]
        built = _build_one(mix, mix_rng, states[mix_i])
        plan.append(
            PlannedRequest(
                offset_s=offset,
                endpoint=built["endpoint"],
                body=built["body"],
                tier=mix.tier,
                shape=mix.shape,
                stream=built["stream"],
                index=i,
            )
        )
    return plan


def warmup_requests(scenario: Scenario, n: int) -> List[PlannedRequest]:
    """Small serial pre-cell requests (not measured, not graded)."""
    rng = random.Random(scenario.seed + 77)
    out = []
    for i in range(n):
        out.append(
            PlannedRequest(
                offset_s=0.0,
                endpoint="/v1/chat/completions",
                body={
                    "messages": [
                        {"role": "user",
                         "content": f"warmup {i} " + _words(rng, 6)}
                    ],
                    "max_tokens": 4,
                    "temperature": 0.0,
                },
                tier="standard",
                shape="chat",
                stream=False,
                index=-1 - i,
            )
        )
    return out
