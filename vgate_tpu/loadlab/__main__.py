"""CLI: ``python -m vgate_tpu.loadlab run|list|compare ...``."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import compare as compare_mod
from .runner import launch_server, run_scenario, scenario_server_env
from .scenario import bundled_scenarios, load_scenario


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m vgate_tpu.loadlab")
    sub = parser.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser(
        "run", help="run a scenario sweep against a live server"
    )
    run_p.add_argument(
        "--scenario", required=True,
        help="bundled scenario name or YAML path",
    )
    run_p.add_argument(
        "--base-url", default=None,
        help="server to drive (mutually exclusive with --launch)",
    )
    run_p.add_argument(
        "--launch", action="store_true",
        help="boot python main.py with the scenario's server_env",
    )
    run_p.add_argument("--port", type=int, default=8790)
    run_p.add_argument("--out", default=None, help="artifact path (jsonl)")
    run_p.add_argument(
        "--cells", default=None,
        help="override qps cells, comma-separated (e.g. 1,2,4)",
    )
    run_p.add_argument("--platform", default=None)
    run_p.add_argument("--device", default=None)
    run_p.add_argument(
        "--duration", type=float, default=None,
        help="override per-cell duration_s",
    )

    sub.add_parser("list", help="list bundled scenarios")

    cmp_p = sub.add_parser(
        "compare", help="gate a new artifact against a baseline"
    )
    cmp_p.add_argument("old")
    cmp_p.add_argument("new")

    args = parser.parse_args(argv)

    if args.cmd == "list":
        for name in bundled_scenarios():
            print(name)
        return 0

    if args.cmd == "compare":
        return compare_mod.main([args.old, args.new])

    scenario = load_scenario(args.scenario)
    if args.duration is not None:
        scenario.duration_s = args.duration
    cells = (
        [float(c) for c in args.cells.split(",")] if args.cells else None
    )
    kwargs = dict(
        out_path=args.out,
        platform=args.platform,
        device=args.device,
        cells=cells,
    )
    if args.launch:
        if args.base_url:
            parser.error("--launch and --base-url are mutually exclusive")
        with launch_server(
            scenario_server_env(scenario), port=args.port
        ) as base:
            result = run_scenario(scenario, base, **kwargs)
    elif args.base_url:
        result = run_scenario(scenario, args.base_url, **kwargs)
    else:
        parser.error("one of --base-url or --launch is required")
    summary = result["summary"]
    return 0 if summary.get("unhandled_errors", 0) == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
