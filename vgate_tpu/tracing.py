"""Request tracing on the OpenTelemetry API with graceful degradation.

The reference defers all OTel SDK imports so the module loads without the SDK
installed (vgate/tracing.py:24-26, 97-108); we keep that contract.  In this
environment only the OTel *API* is present, so when the SDK (or the OTLP
exporter) is missing, ``init_tracing`` silently leaves the API's built-in
no-op tracer in place — every span call site stays unconditional.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)

_initialized = False
_provider: Any = None
# test/dev tracer-provider override (observability.memtrace installs an
# in-memory recorder here): consulted by every get_tracer() call so it
# takes effect even for tracers bound at module import time
_override_provider: Any = None

try:  # The OTel API is a light dependency; tolerate even its absence.
    from opentelemetry import trace as _otel_trace
except ImportError:  # pragma: no cover
    _otel_trace = None

try:
    from opentelemetry import context as _otel_context
except ImportError:  # pragma: no cover
    _otel_context = None


class _NoopSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attribute(self, *a, **k):
        pass

    def set_attributes(self, *a, **k):
        pass

    def record_exception(self, *a, **k):
        pass

    def set_status(self, *a, **k):
        pass

    def add_event(self, *a, **k):
        pass

    def is_recording(self):
        return False

    def end(self, *a, **k):
        pass


class _NoopTracer:
    def start_as_current_span(self, *a, **k):
        return _NoopSpan()

    def start_span(self, *a, **k):
        return _NoopSpan()


def init_tracing(config=None) -> bool:
    """Initialise the tracer provider if the SDK is available and tracing is
    enabled (reference: vgate/tracing.py:38-94).  Returns True when a real
    provider was installed."""
    global _initialized, _provider
    if config is None:
        from vgate_tpu.config import get_config

        config = get_config()
    if _initialized:
        return _provider is not None
    _initialized = True
    if not config.tracing.enabled or _otel_trace is None:
        return False
    try:
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.sdk.trace.sampling import TraceIdRatioBased
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
    except ImportError:
        logger.warning(
            "tracing.enabled=true but the OpenTelemetry SDK is not "
            "installed; spans will be no-ops"
        )
        return False

    resource = Resource.create({"service.name": config.tracing.service_name})
    provider = TracerProvider(
        resource=resource,
        sampler=TraceIdRatioBased(config.tracing.sample_rate),
    )
    provider.add_span_processor(
        BatchSpanProcessor(OTLPSpanExporter(endpoint=config.tracing.endpoint))
    )
    _otel_trace.set_tracer_provider(provider)
    _provider = provider
    return True


class _ProxyTracer:
    """Late-binding tracer: resolves the live tracer at each span call so
    a provider installed AFTER module import (init_tracing, or the
    in-memory recorder observability.memtrace puts in
    ``set_tracer_provider_override``) is honored by tracers that were
    bound at import time (``tracer = get_tracer(__name__)``)."""

    __slots__ = ("_name",)

    def __init__(self, name: str) -> None:
        self._name = name

    def _resolve(self):
        if _override_provider is not None:
            return _override_provider.get_tracer(self._name)
        return _otel_trace.get_tracer(self._name)

    def start_as_current_span(self, *a, **k):
        return self._resolve().start_as_current_span(*a, **k)

    def start_span(self, *a, **k):
        return self._resolve().start_span(*a, **k)


def get_tracer(name: str):
    """Tracer accessor; returns a no-op tracer when OTel is absent
    (reference: vgate/tracing.py:97-108)."""
    if _otel_trace is None:
        return _NoopTracer()
    return _ProxyTracer(name)


def set_tracer_provider_override(provider) -> None:
    """Install (or with None, remove) a process-local tracer provider
    that wins over the OTel global.  Exists so tests and dev tooling can
    record spans without the OTel SDK (observability/memtrace.py); not a
    serving configuration surface."""
    global _override_provider
    _override_provider = provider


def capture_context() -> Optional[Any]:
    """Snapshot the current OTel context (the active span rides in it)
    for cross-thread propagation — the batcher captures it per request
    and the engine thread parents its phase spans on it.  None when the
    OTel API is absent."""
    if _otel_context is None:
        return None
    return _otel_context.get_current()


def context_trace_id(ctx: Any) -> Optional[str]:
    """Hex trace id of the span carried by a captured context (exemplar
    attachment off the request thread), or None."""
    if ctx is None or _otel_trace is None:
        return None
    span = _otel_trace.get_current_span(ctx)
    sc = span.get_span_context()
    if sc is None or not sc.is_valid:
        return None
    return format(sc.trace_id, "032x")


def context_span_id(ctx: Any) -> Optional[str]:
    if ctx is None or _otel_trace is None:
        return None
    span = _otel_trace.get_current_span(ctx)
    sc = span.get_span_context()
    if sc is None or not sc.is_valid:
        return None
    return format(sc.span_id, "016x")


def context_to_traceparent(ctx: Any) -> Optional[str]:
    """Encode a captured context's active span as a W3C ``traceparent``
    header value (``00-<trace>-<span>-<flags>``) for the gateway →
    worker RPC plane.  The pod frame protocol is JSON, not HTTP, so the
    value rides as a plain frame field; the W3C wire format keeps it
    interoperable with anything downstream that speaks trace context.
    None when OTel is absent or the context carries no valid span."""
    if ctx is None or _otel_trace is None:
        return None
    span = _otel_trace.get_current_span(ctx)
    sc = span.get_span_context()
    if sc is None or not sc.is_valid:
        return None
    return (
        f"00-{sc.trace_id:032x}-{sc.span_id:016x}-"
        f"{int(sc.trace_flags):02x}"
    )


def context_from_traceparent(header: Optional[str]) -> Optional[Any]:
    """Decode a W3C ``traceparent`` value into an OTel context carrying
    a remote ``NonRecordingSpan`` — the worker-side half of
    :func:`context_to_traceparent`.  Spans started under the returned
    context parent onto the gateway's span, so one trace spans all pod
    processes.  Returns None (spans stay local roots) on any malformed
    input: a worker must never fail a submit over a bad trace header."""
    if not header or _otel_trace is None:
        return None
    try:
        parts = header.split("-")
        if len(parts) != 4 or parts[0] != "00":
            return None
        trace_id = int(parts[1], 16)
        span_id = int(parts[2], 16)
        flags = int(parts[3], 16)
        if len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        sc = _otel_trace.SpanContext(
            trace_id=trace_id,
            span_id=span_id,
            is_remote=True,
            trace_flags=_otel_trace.TraceFlags(flags),
        )
        if not sc.is_valid:
            return None
        return _otel_trace.set_span_in_context(
            _otel_trace.NonRecordingSpan(sc)
        )
    except (ValueError, AttributeError):
        return None


def get_current_trace_id() -> Optional[str]:
    """Hex trace id of the active span for logs/exemplars
    (reference: vgate/tracing.py:123-136)."""
    if _otel_trace is None:
        return None
    span = _otel_trace.get_current_span()
    ctx = span.get_span_context()
    if ctx is None or not ctx.is_valid:
        return None
    return format(ctx.trace_id, "032x")


def get_current_span_id() -> Optional[str]:
    if _otel_trace is None:
        return None
    span = _otel_trace.get_current_span()
    ctx = span.get_span_context()
    if ctx is None or not ctx.is_valid:
        return None
    return format(ctx.span_id, "016x")


def shutdown_tracing() -> None:
    global _initialized, _provider
    if _provider is not None:
        try:
            _provider.shutdown()
        except Exception:  # pragma: no cover
            pass
    _provider = None
    _initialized = False


def reset_tracing() -> None:
    """Test hook mirroring the reference's autouse reset fixture
    (tests/conftest.py:242-249 in the reference)."""
    shutdown_tracing()
    set_tracer_provider_override(None)
