"""Structured logging: JSON and ANSI console formatters with trace-id
injection (reference: vgate/logging_config.py:46-108).

Every log record gets ``trace_id``/``span_id`` from the active OTel span when
one exists, and an ``extra_data`` dict passed via ``extra={"extra_data": ...}``
is merged into the JSON payload (the reference's convention, e.g.
vgate/batcher.py:95-101).
"""

from __future__ import annotations

import contextlib
import json
import logging
import sys
import threading
from datetime import datetime, timezone
from typing import Any, Dict, Optional

from vgate_tpu.tracing import get_current_span_id, get_current_trace_id

# Thread-local request binding: the engine thread has no active OTel
# span (spans are emitted with explicit timestamps, never attached to
# its context), so sequence-scoped log records would lose their
# request/trace identity.  The engine binds the owning request around
# per-sequence work; both formatters fall back to it when the span
# lookup yields nothing.
_bound = threading.local()


def bind_request_fields(
    request_id: Optional[str], trace_id: Optional[str]
):
    """Set the calling thread's bound request identity; returns the
    previous binding (pass it back to restore).  Hot-path friendly: two
    attribute writes, no allocation when both ids are None."""
    prev = getattr(_bound, "fields", None)
    if request_id is None and trace_id is None:
        _bound.fields = None
    else:
        fields = {}
        if request_id:
            fields["request_id"] = request_id
        if trace_id:
            fields["trace_id"] = trace_id
        _bound.fields = fields or None
    return prev


def restore_request_fields(prev) -> None:
    _bound.fields = prev


@contextlib.contextmanager
def bound_request(
    request_id: Optional[str] = None, trace_id: Optional[str] = None
):
    prev = bind_request_fields(request_id, trace_id)
    try:
        yield
    finally:
        restore_request_fields(prev)


def _bound_fields() -> Optional[Dict[str, str]]:
    return getattr(_bound, "fields", None)

_ANSI = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[35m",
}
_RESET = "\033[0m"


class JSONFormatter(logging.Formatter):
    """One JSON object per line (reference: vgate/logging_config.py:46-75)."""

    def format(self, record: logging.LogRecord) -> str:
        payload: Dict[str, Any] = {
            "timestamp": datetime.fromtimestamp(
                record.created, tz=timezone.utc
            ).isoformat(),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = get_current_trace_id()
        if trace_id:
            payload["trace_id"] = trace_id
            span_id = get_current_span_id()
            if span_id:
                payload["span_id"] = span_id
        else:
            bound = _bound_fields()
            if bound:
                payload.update(bound)
        extra = getattr(record, "extra_data", None)
        if isinstance(extra, dict):
            payload.update(extra)
        if record.exc_info:
            payload["exception"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


class ConsoleFormatter(logging.Formatter):
    """Human-readable colored lines (reference: vgate/logging_config.py:78-108)."""

    def format(self, record: logging.LogRecord) -> str:
        color = _ANSI.get(record.levelname, "")
        ts = datetime.fromtimestamp(record.created).strftime("%H:%M:%S.%f")[:-3]
        parts = [
            f"{ts} {color}{record.levelname:<8}{_RESET} "
            f"{record.name}: {record.getMessage()}"
        ]
        trace_id = get_current_trace_id()
        if trace_id:
            parts.append(f" [trace={trace_id[:8]}]")
        else:
            bound = _bound_fields()
            if bound:
                if "trace_id" in bound:
                    parts.append(f" [trace={bound['trace_id'][:8]}]")
                if "request_id" in bound:
                    parts.append(f" [req={bound['request_id']}]")
        extra = getattr(record, "extra_data", None)
        if isinstance(extra, dict) and extra:
            parts.append(" " + json.dumps(extra, default=str))
        if record.exc_info:
            parts.append("\n" + self.formatException(record.exc_info))
        return "".join(parts)


def setup_logging(config=None) -> None:
    """Install the configured formatter on the root logger
    (reference: vgate/logging_config.py:111-149)."""
    if config is None:
        from vgate_tpu.config import get_config

        config = get_config()
    root = logging.getLogger()
    root.setLevel(getattr(logging, config.logging.level.upper(), logging.INFO))
    for handler in list(root.handlers):
        root.removeHandler(handler)
    handler = logging.StreamHandler(sys.stderr)
    if config.logging.format == "json":
        handler.setFormatter(JSONFormatter())
    else:
        handler.setFormatter(ConsoleFormatter())
    root.addHandler(handler)
    # Quiet noisy third-party loggers.
    for noisy in ("aiohttp.access", "urllib3", "jax._src"):
        logging.getLogger(noisy).setLevel(logging.WARNING)


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)


class LogContext:
    """Context helper binding fields onto every log call
    (reference: vgate/logging_config.py:165-196)."""

    def __init__(self, logger: logging.Logger, **fields: Any) -> None:
        self._logger = logger
        self._fields = fields

    def _log(self, level: int, msg: str, **extra: Any) -> None:
        merged = {**self._fields, **extra}
        self._logger.log(level, msg, extra={"extra_data": merged})

    def debug(self, msg: str, **extra: Any) -> None:
        self._log(logging.DEBUG, msg, **extra)

    def info(self, msg: str, **extra: Any) -> None:
        self._log(logging.INFO, msg, **extra)

    def warning(self, msg: str, **extra: Any) -> None:
        self._log(logging.WARNING, msg, **extra)

    def error(self, msg: str, **extra: Any) -> None:
        self._log(logging.ERROR, msg, **extra)
