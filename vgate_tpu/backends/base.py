"""Backend protocol + the dry-run backend.

The reference pins the engine seam to four methods — ``load_model``,
``create_sampling_params``, ``generate``, ``shutdown`` — with outputs
normalized to ``{text, token_ids, num_tokens, metrics}``
(vgate/backends/base.py:21-34, vgate/backends/vllm_backend.py:53-69).  We
keep that seam and strengthen it in two ways the TPU engine needs:

* ``SamplingParams`` is an explicit per-request dataclass, and ``generate``
  accepts one per prompt — fixing the reference quirk where the whole batch
  inherits the first request's temperature/top_p (vgate/batcher.py:271).
* Backends may implement ``generate_async`` for engines with their own
  continuous-batching scheduler; callers fall back to running the sync
  ``generate`` in a thread pool otherwise.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Protocol, Sequence, runtime_checkable


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls, honored per sequence inside a batch."""

    max_tokens: int = 256
    # suppress eos/stop tokens on device until this many tokens exist
    min_tokens: int = 0
    temperature: float = 0.7
    top_p: float = 0.95
    top_k: int = 0  # 0 disables top-k
    stop: Optional[List[str]] = None
    # extra token ids that end generation with finish_reason "stop"
    # (beyond the model's eos) — the id-level sibling of `stop` strings
    stop_token_ids: Optional[List[int]] = None
    seed: Optional[int] = None
    # OpenAI-style logprobs: return the chosen token's log-probability
    # (raw-logit log-softmax) and, when top_logprobs > 0, the top
    # alternatives per position (clamped to the engine's LOGPROBS_K).
    logprobs: bool = False
    top_logprobs: int = 0
    # OpenAI penalties over generated tokens (-2..2): frequency scales
    # with the count, presence is a flat once-seen offset.
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # OpenAI logit_bias: {token_id: additive bias in [-100, 100]},
    # applied to the logits before sampling at every position.
    logit_bias: Optional[Dict[int, float]] = None
    # End-to-end request deadline in seconds, measured from engine
    # arrival.  The engine sheds the sequence between decode ticks once
    # it passes (DeadlineExceededError with partial-tokens metadata →
    # 504 at the gateway).  None = only server.request_timeout_s
    # applies.  NOT part of the result-cache identity: a completed
    # result is the same whatever budget produced it.
    timeout_s: Optional[float] = None
    # Priority-tier rank (vgate_tpu/admission.py: 0 = interactive,
    # 1 = standard, 2 = batch).  The engine scheduler admits
    # lower-rank sequences first and preempts higher-rank ones first
    # under KV pressure.  Like timeout_s, NOT part of the cache key.
    priority: int = 1

    @property
    def has_penalties(self) -> bool:
        return bool(self.frequency_penalty or self.presence_penalty)


@dataclass
class GenerationResult:
    """Normalized backend output (reference: vllm_backend.py:64-69)."""

    text: str
    token_ids: List[int] = field(default_factory=list)
    num_tokens: int = 0
    prompt_tokens: int = 0
    metrics: Dict[str, float] = field(default_factory=dict)
    finish_reason: str = "stop"
    # per-token logprob entries (OpenAI shape) when requested, else None
    logprobs: Optional[List[Dict[str, Any]]] = None

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "text": self.text,
            "token_ids": self.token_ids,
            "num_tokens": self.num_tokens,
            "prompt_tokens": self.prompt_tokens,
            "metrics": self.metrics,
            "finish_reason": self.finish_reason,
        }
        if self.logprobs is not None:
            out["logprobs"] = self.logprobs
        return out


@runtime_checkable
class InferenceBackend(Protocol):
    """The 4-method engine seam (reference: vgate/backends/base.py:21-34)."""

    def load_model(self, model_config: Any) -> None: ...

    def create_sampling_params(self, **kwargs: Any) -> SamplingParams: ...

    def generate(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
    ) -> List[GenerationResult]: ...

    def shutdown(self) -> None: ...


class DryRunBackend:
    """Echo backend for CI / CPU containers / gateway tests
    (reference: DryRunBackend at vgate/backends/base.py:37-62)."""

    def __init__(self) -> None:
        self.model_id = "dry-run"
        self.calls = 0

    def load_model(self, config: Any) -> None:
        model_cfg = getattr(config, "model", config)
        self.model_id = getattr(model_cfg, "model_id", "dry-run")

    def create_sampling_params(self, **kwargs: Any) -> SamplingParams:
        return SamplingParams(**kwargs)

    def generate(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
    ) -> List[GenerationResult]:
        # same named fault point the jax backend probes: lets chaos/drain
        # drills inject latency or failures into dry-run serving too
        # (scripts/drain_check.sh arms backend_generate:delay)
        from vgate_tpu import faults

        faults.check("backend_generate")
        self.calls += 1
        start = time.perf_counter()
        results = []
        for prompt in prompts:
            text = f"[dry-run] echo: {prompt[:80]}"
            elapsed = time.perf_counter() - start
            results.append(
                GenerationResult(
                    text=text,
                    token_ids=list(range(8)),
                    num_tokens=8,
                    prompt_tokens=max(1, len(prompt.split())),
                    metrics={
                        "ttft": elapsed,
                        "gen_time": elapsed,
                        "tpot": elapsed / 8,
                    },
                )
            )
        return results

    def embed(self, inputs: Sequence[str]) -> List[List[float]]:
        """Deterministic fake embeddings (reference mock: engine.py:93-111)."""
        return [[(i % 100) * 0.01 for i in range(768)] for _ in inputs]

    def shutdown(self) -> None:
        pass
