"""The in-house TPU inference backend (`engine_type: jax_tpu`).

Implements the reference's 4-method backend seam
(vgate/backends/base.py:21-34) — but where vLLM/SGLang adapters delegate to
external GPU engines (vllm_backend.py:48-70), this backend owns the whole
stack: JAX model runner, paged KV cache, continuous-batching scheduler and
device-side sampling (runtime/engine_core.py).  Additional capabilities the
gateway exploits when present: ``generate_async`` (sequences join the running
engine between decode steps), ``stream_async`` (per-token SSE), ``embed``
(real encoder embeddings) and ``device_health``.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import os
import threading
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from vgate_tpu import faults
from vgate_tpu.backends.base import GenerationResult, SamplingParams
from vgate_tpu.config import get_config
from vgate_tpu.errors import state_is_alive, state_is_ready
from vgate_tpu.logging_config import get_logger
from vgate_tpu.models.specs import ModelSpec, spec_for_model_id
from vgate_tpu.runtime.engine_core import EngineCore
from vgate_tpu.runtime.sequence import SeqStatus
from vgate_tpu.utils.math import bucket_for, round_up
from vgate_tpu.analysis.witness import named_lock

logger = get_logger(__name__)


class Embedder:
    """Encoder-model wrapper for /v1/embeddings."""

    BUCKETS = (32, 128, 512)

    def __init__(self, model_id: str, checkpoint_path: Optional[str], dtype):
        from vgate_tpu.models.encoder import (
            encode_forward,
            init_encoder_params,
        )
        from vgate_tpu.runtime.tokenizer import get_tokenizer

        self.spec = spec_for_model_id(model_id)
        if not self.spec.is_encoder:
            raise ValueError(f"{model_id} is not an encoder model")
        self.tokenizer = get_tokenizer(self.spec, checkpoint_path)
        if checkpoint_path and os.path.isdir(checkpoint_path):
            from vgate_tpu.models.encoder import (
                encoder_params_from_safetensors,
            )

            self.params = encoder_params_from_safetensors(
                self.spec, checkpoint_path, dtype
            )
        else:
            # zero-egress fallback: architecturally real, semantically
            # meaningless vectors (logged so operators can't mistake them
            # for bge embeddings)
            logger.warning(
                "no embedding checkpoint found; using random-init weights",
                extra={"extra_data": {"model": model_id,
                                      "path": checkpoint_path}},
            )
            self.params = init_encoder_params(
                self.spec, jax.random.PRNGKey(0), dtype
            )
        self._forward = jax.jit(
            functools.partial(encode_forward, spec=self.spec)
        )
        self._lock = named_lock("Embedder._lock")

    def embed(self, inputs: Sequence[str]) -> List[List[float]]:
        max_len = self.spec.max_position_embeddings
        ids = [self.tokenizer.encode(t)[: max_len - 2] for t in inputs]
        longest = max(1, max(len(i) for i in ids))
        S = bucket_for(
            min(longest + 2, max_len),
            [b for b in self.BUCKETS if b <= max_len] + [max_len],
        )
        B = max(1, min(64, 1 << (len(ids) - 1).bit_length()))
        out: List[List[float]] = []
        with self._lock:
            for chunk_start in range(0, len(ids), B):
                chunk = ids[chunk_start : chunk_start + B]
                tokens = np.zeros((B, S), np.int32)
                mask = np.zeros((B, S), np.int32)
                for row, seq_ids in enumerate(chunk):
                    full = (
                        [self.tokenizer.bos_id] + seq_ids + [self.tokenizer.eos_id]
                    )
                    tokens[row, : len(full)] = full
                    mask[row, : len(full)] = 1
                vecs = self._forward(
                    self.params,
                    tokens=jnp.asarray(tokens),
                    mask=jnp.asarray(mask),
                )
                out.extend(
                    np.asarray(vecs[: len(chunk)], np.float32).tolist()
                )
        return out


class JaxTPUBackend:
    """Continuous-batching TPU backend behind the 4-method protocol."""

    def __init__(self) -> None:
        # EngineCore (dp=1) or runtime.dp_engine.ReplicatedEngine (dp>1);
        # both expose the same serving surface
        self.core: Optional[Any] = None
        self._embedder: Optional[Embedder] = None
        self._config = None

    # -- protocol --

    def load_model(self, config: Any) -> None:
        # accept the full VGTConfig through the seam; fall back to the global
        # for callers that still pass only the model section
        self._config = config if hasattr(config, "tpu") else get_config()
        if getattr(self._config, "pod", None) and self._config.pod.workers > 0:
            # process-isolated workers: the gateway routes over N engine
            # processes with fencing/failover; takes precedence over
            # in-process dp (each worker is its own full engine stack)
            from vgate_tpu.runtime.pod_engine import PodEngine

            self.core = PodEngine(self._config)
        elif self._config.tpu.dp > 1:
            # dp replicas have their own failover; unsupervised
            from vgate_tpu.runtime.dp_engine import ReplicatedEngine

            self.core = ReplicatedEngine(self._config)
        elif self._config.recovery.enabled:
            from vgate_tpu.runtime.supervisor import EngineSupervisor

            self.core = EngineSupervisor(self._config)
        else:
            self.core = EngineCore(self._config)
        self.core.start()
        logger.info(
            "jax_tpu backend ready",
            extra={
                "extra_data": {
                    "model": self.core.spec.name,
                    "mesh": {
                        k: int(v) for k, v in self.core.mesh.shape.items()
                    },
                    "kv_pages": self.core.geometry.num_pages,
                }
            },
        )

    def create_sampling_params(self, **kwargs: Any) -> SamplingParams:
        return SamplingParams(**kwargs)

    def generate(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
    ) -> List[GenerationResult]:
        assert self.core is not None, "load_model not called"
        faults.check("backend_generate")
        raw = self.core.generate(prompts, sampling_params)
        return [GenerationResult(**r) for r in raw]

    def shutdown(self) -> None:
        if self.core is not None:
            self.core.stop()
            self.core = None

    def abort_in_flight(self, reason: str = "drain") -> None:
        """Graceful-drain straggler sweep: ask the engine thread to
        request-abort every resident sequence at its next tick
        (supervised cores delegate to the live EngineCore)."""
        if self.core is None:
            return
        fn = getattr(self.core, "abort_in_flight", None)
        if fn is not None:
            fn(reason)

    def set_spec_suspended(self, flag: bool) -> None:
        """Brownout L3 (vgate_tpu/admission.py): suspend/resume
        speculative decoding on the live core (supervised cores
        delegate; dp routers fan out to every replica)."""
        fn = getattr(self.core, "set_spec_suspended", None) if (
            self.core is not None
        ) else None
        if fn is not None:
            try:
                fn(bool(flag))
            except Exception:  # pragma: no cover - mid-rebuild race
                logger.warning("set_spec_suspended failed", exc_info=True)

    def set_prefix_insert_suspended(self, flag: bool) -> None:
        """Brownout L4 (vgate_tpu/admission.py "bypass cache writes"):
        stop prefix-tree inserts, keep serving hits (supervised cores
        delegate; dp routers fan out to every replica)."""
        fn = getattr(
            self.core, "set_prefix_insert_suspended", None
        ) if self.core is not None else None
        if fn is not None:
            try:
                fn(bool(flag))
            except Exception:  # pragma: no cover - mid-rebuild race
                logger.warning(
                    "set_prefix_insert_suspended failed", exc_info=True
                )

    def pressure_signals(self) -> Dict[str, Any]:
        """KV/queue gauges for gateway admission + brownout; empty while
        the core is loading or mid-rebuild (the controllers then fall
        back to gateway-side signals alone)."""
        fn = getattr(self.core, "pressure_signals", None) if (
            self.core is not None
        ) else None
        if fn is None:
            return {}
        try:
            return fn() or {}
        except Exception:  # pragma: no cover - mid-rebuild race
            return {}

    # -- async extensions used by the gateway --

    async def generate_settled_async(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
        cancel_tokens: Optional[Sequence[Any]] = None,
        request_meta: Optional[Sequence[Any]] = None,
    ) -> List[Any]:
        """Like ``generate_async`` but failures are returned per slot (the
        exception object in place of a GenerationResult) instead of failing
        the whole batch — one deadline-shed or failed sequence must not
        discard its co-batched neighbours' completed generations.

        ``cancel_tokens`` (one ``lifecycle.CancelToken`` or None per
        prompt) is the request-scoped cancellation plumbing: a token
        cancelled while its sequence decodes aborts exactly that
        sequence — slot and KV pages free within one engine tick — and
        its slot settles with finish_reason "abort" while batchmates
        keep decoding.  This closes the gap where batched gateway
        traffic ran under the batcher's own task and a client
        disconnect left the sequence decoding to completion.

        ``request_meta`` (one ``observability.RequestMeta`` or None per
        prompt) carries the gateway request id and the captured OTel
        context: the engine parents its queue/prefill/decode phase
        spans on it and stamps flight-recorder records with the
        request/trace ids."""
        assert self.core is not None
        faults.check("backend_generate")
        loop = asyncio.get_running_loop()
        seqs = []
        for i, (p, sp) in enumerate(zip(prompts, sampling_params)):
            try:
                seq = self.core.submit_prompt(
                    p, sp,
                    meta=request_meta[i] if request_meta else None,
                )
            except Exception as exc:  # queue full / dead engine
                seqs.append(exc)
                continue
            token = cancel_tokens[i] if cancel_tokens else None
            if token is not None:
                # fires immediately when the client vanished between
                # enqueue and dispatch (add_callback runs late
                # registrants inline)
                token.add_callback(
                    lambda s=seq, t=token: s.request_abort(
                        t.reason or "client_disconnect"
                    )
                )
            seqs.append(seq)

        def wait_all():
            for seq in seqs:
                if not isinstance(seq, BaseException):
                    seq.done_event.wait()

        try:
            await loop.run_in_executor(None, wait_all)
        except asyncio.CancelledError:
            # the awaiting task died (client disconnect on a direct
            # caller, or the whole batch task torn down) — release the
            # engine work it was waiting on
            for seq in seqs:
                if not isinstance(seq, BaseException):
                    seq.request_abort()
            raise
        results: List[Any] = []
        for seq in seqs:
            if isinstance(seq, BaseException):
                results.append(seq)
            elif seq.status is SeqStatus.FAILED:
                results.append(seq.error)
            else:
                # the final-text assembly (tokenizer decode + stop
                # truncation) is the request's last serving phase
                with (
                    seq.trace.span(
                        "detokenize", tokens=seq.num_output_tokens
                    )
                    if seq.trace is not None
                    else contextlib.nullcontext()
                ):
                    text = self.core.final_text(seq)
                results.append(
                    GenerationResult(
                        text=text,
                        token_ids=list(seq.generated_ids),
                        num_tokens=seq.num_output_tokens,
                        prompt_tokens=seq.orig_prompt_len,
                        finish_reason=seq.finish_reason,
                        metrics={
                            "ttft": seq.ttft or 0.0,
                            "tpot": seq.tpot or 0.0,
                            "gen_time": (
                                (seq.finish_t or 0.0) - seq.arrival_t
                            ),
                            **seq.resume_metrics(),
                        },
                        logprobs=(
                            self.core.logprob_entries(seq)
                            if seq.params.logprobs
                            else None
                        ),
                    )
                )
        return results

    async def generate_async(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
    ) -> List[GenerationResult]:
        """Submit into the running engine and await completion without
        blocking the event loop (sequences from concurrent batches share
        decode steps — this is where continuous batching pays off).  Raises
        the first failure; callers batching unrelated requests should use
        ``generate_settled_async``."""
        settled = await self.generate_settled_async(prompts, sampling_params)
        for item in settled:
            if isinstance(item, BaseException):
                raise item
        return settled

    async def stream_async(
        self,
        prompt: str,
        params: SamplingParams,
        on_finish: Optional[Any] = None,
        on_usage: Optional[Any] = None,
        request_meta: Optional[Any] = None,
    ) -> AsyncIterator[str]:
        """Token-by-token text deltas for SSE streaming.  ``on_finish`` (if
        given) is called with the sequence's finish_reason after the last
        delta, so the gateway can close the stream with the true reason;
        ``on_usage`` (if given) receives the request's token usage dict
        just before that (OpenAI stream_options.include_usage).

        With ``params.logprobs`` each yield is a dict ``{"text": delta,
        "logprobs": [entries for the tokens consumed since the previous
        yield]}`` (deltas are text-level, and stop-string holdback means
        a delta can span several tokens); plain requests yield bare
        strings, the original contract."""
        assert self.core is not None
        loop = asyncio.get_running_loop()
        q: "asyncio.Queue[Optional[int]]" = asyncio.Queue()

        def on_token(token: int) -> None:
            try:
                loop.call_soon_threadsafe(q.put_nowait, token)
            except RuntimeError:
                pass  # loop closed: consumer disconnected, abort follows

        seq = self.core.submit_prompt(
            prompt, params, stream_cb=on_token, meta=request_meta
        )

        def on_done() -> None:
            seq.done_event.wait()
            try:
                loop.call_soon_threadsafe(q.put_nowait, None)
            except RuntimeError:
                pass  # loop closed: nothing left to notify

        threading.Thread(target=on_done, daemon=True).start()

        emitted = ""
        ids: List[int] = []
        pending_lp: List[Any] = []

        def wrap(delta: str):
            if not params.logprobs:
                return delta
            out = {"text": delta, "logprobs": pending_lp[:]}
            pending_lp.clear()
            return out

        stops = params.stop or []
        longest_stop = max((len(s) for s in stops), default=0)
        completed = False
        try:
            while True:
                token = await q.get()
                if token is None:
                    # flush the held-back tail: the engine's own stop
                    # detection is authoritative (final_text truncates
                    # at a stop match)
                    final = self.core.final_text(seq)
                    if len(final) > len(emitted) or pending_lp:
                        yield wrap(final[len(emitted):])
                    break
                ids.append(token)
                if params.logprobs and len(seq.logprob_data) >= len(ids):
                    lp, top = seq.logprob_data[len(ids) - 1]
                    pending_lp.append(self.core.lp_entry(token, lp, top))
                text = self.core.tokenizer.decode(ids)
                if stops:
                    cut = min(
                        (
                            i
                            for i in (text.find(s) for s in stops)
                            if i != -1
                        ),
                        default=-1,
                    )
                    if cut >= 0:
                        if cut > len(emitted) or pending_lp:
                            # flush even a zero-length delta: the entries
                            # for the stop-completing tokens must not
                            # vanish
                            yield wrap(text[len(emitted):cut])
                        break
                    # hold back a stop-length tail so a stop string
                    # arriving across several tokens is never partially
                    # emitted
                    text = text[: max(len(emitted), len(text) - longest_stop)]
                if len(text) > len(emitted):
                    delta = text[len(emitted):]
                    emitted = text
                    yield wrap(delta)
            completed = True
        finally:
            if not completed and not seq.done_event.is_set():
                # the consumer went away mid-stream (SSE client
                # disconnect cancels the handler, closing this
                # generator) — stop burning decode steps on it
                seq.request_abort()
        if seq.status is SeqStatus.FAILED:
            raise seq.error  # type: ignore[misc]
        # streamed requests bypass the batcher, whose _normalize is
        # where non-streaming TTFT/TPOT land — observe here so the
        # vgt_* histograms cover the latency-sensitive path too (the
        # loadlab smoke drill asserts the server's TTFT view tracks the
        # client-observed one; before this, streams never fed it)
        from vgate_tpu import metrics as vgt_metrics
        from vgate_tpu.tracing import context_trace_id

        trace_id = (
            context_trace_id(request_meta.trace_ctx)
            if request_meta is not None
            and getattr(request_meta, "trace_ctx", None) is not None
            else None
        )
        for hist, value in (
            (vgt_metrics.TTFT, seq.ttft),
            (vgt_metrics.TPOT, seq.tpot),
        ):
            if value is None:
                continue
            if trace_id:
                vgt_metrics.observe_with_exemplar(
                    hist, value, trace_id=trace_id
                )
            else:
                hist.observe(value)
        if on_usage is not None:
            on_usage({
                "prompt_tokens": seq.orig_prompt_len,
                "completion_tokens": seq.num_output_tokens,
                "total_tokens": (
                    seq.orig_prompt_len + seq.num_output_tokens
                ),
            })
        if on_finish is not None:
            on_finish(seq.finish_reason)

    # -- embeddings --

    def embed(self, inputs: Sequence[str]) -> List[List[float]]:
        if self._embedder is None:
            config = self._config or get_config()
            self._embedder = Embedder(
                config.model.embedding_model_id,
                config.model.embedding_checkpoint_path,
                jnp.float32,
            )
        return self._embedder.embed(inputs)

    # -- introspection --

    def device_health(self) -> Dict[str, Any]:
        if self.core is None:
            return {"alive": False, "error": "not loaded"}
        return self.core.device_health()

    def serving_state(self) -> str:
        """Health-state-machine position ("serving" | "degraded" |
        "recovering" | "dead"); unsupervised cores are "serving" while
        alive and "dead" after a fatal."""
        if self.core is None:
            return "dead"
        state = getattr(self.core, "state", None)
        if state is not None:
            return state.value
        if getattr(self.core, "_fatal", None) is not None:
            return "dead"
        return "serving"

    def serving_health(self) -> Dict[str, Any]:
        """Engine liveness block for /health: always present, regardless
        of whether the device exposes health (satellite: app.py must not
        depend on device_health existing)."""
        health_fn = getattr(self.core, "health", None)
        if health_fn is not None:
            return health_fn()
        state = self.serving_state()
        body: Dict[str, Any] = {
            "state": state,
            "alive": state_is_alive(state),
            "ready": state_is_ready(state),
        }
        stats_fn = getattr(self.core, "get_stats", None)
        if stats_fn is not None:
            try:
                sched = (stats_fn() or {}).get("scheduler", {})
                body["queue_depth"] = sched.get("waiting", 0)
                body["running"] = sched.get("running", 0)
            except Exception:
                pass
        return body

    def get_stats(self) -> Dict[str, Any]:
        if self.core is None:
            return {}
        return self.core.get_stats()
