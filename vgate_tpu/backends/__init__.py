"""Pluggable inference backends (reference seam: vgate/backends/base.py:21-34)."""

from vgate_tpu.backends.base import (
    DryRunBackend,
    GenerationResult,
    InferenceBackend,
    SamplingParams,
)

__all__ = [
    "DryRunBackend",
    "GenerationResult",
    "InferenceBackend",
    "SamplingParams",
]
