"""Optional vLLM comparison backend.

The reference's headline benchmark runs vLLM and SGLang side by side
(/root/reference/benchmarks/bench_compare.py:145-178 — both backends in
one table); this adapter restores that capability for apples-to-apples
GPU-vs-TPU comparisons when a ``vllm`` wheel is present.  It is a thin
adapter over ``vllm.LLM.generate`` mapped onto OUR 4-method seam and
per-request ``SamplingParams`` (the reference applies the first
request's temperature to the whole batch, vgate/batcher.py:271; vLLM
itself supports per-request params, so we pass them through per
prompt).

vLLM is deliberately NOT a dependency — this image has no GPU and no
egress — so the import is lazy and the error is explicit.  Select with
``model.engine_type: "vllm"`` or benchmark side by side via
``benchmarks/bench_compare.py --engines jax_tpu vllm``.
"""

from __future__ import annotations

import time
from typing import Any, List, Sequence

from vgate_tpu.backends.base import GenerationResult, SamplingParams
from vgate_tpu.logging_config import get_logger

logger = get_logger(__name__)


class VLLMBackend:
    """``vllm.LLM`` behind the engine seam (comparison use)."""

    def __init__(self) -> None:
        self._llm = None
        self.model_id = ""

    def load_model(self, config: Any) -> None:
        try:
            from vllm import LLM
        except ImportError as exc:  # pragma: no cover - no vllm in image
            raise RuntimeError(
                "engine_type 'vllm' needs the vllm package (not bundled: "
                "this deployment is TPU-native; install vllm in a GPU "
                "image to benchmark side by side)"
            ) from exc
        model_cfg = getattr(config, "model", config)
        self.model_id = getattr(model_cfg, "model_id", "")
        kwargs = {}
        quant = getattr(model_cfg, "quantization", None)
        if quant:
            # our int8/int4 schemes don't map onto vLLM's awq/gptq
            # checkpoints — say so loudly instead of silently comparing
            # quantized TPU numbers against fp16 vLLM numbers
            logger.warning(
                "vllm backend ignores quantization=%s (no mapping to a "
                "vLLM scheme); it will serve the model unquantized",
                quant,
            )
        max_len = getattr(model_cfg, "max_model_len", None)
        if max_len:
            kwargs["max_model_len"] = max_len
        self._llm = LLM(model=self.model_id, **kwargs)
        logger.info(
            "vllm backend ready",
            extra={"extra_data": {"model": self.model_id}},
        )

    def create_sampling_params(self, **kwargs: Any) -> SamplingParams:
        return SamplingParams(**kwargs)

    def generate(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
    ) -> List[GenerationResult]:
        from vllm import SamplingParams as VSP

        assert self._llm is not None, "load_model first"
        vsp = [
            VSP(
                max_tokens=p.max_tokens,
                min_tokens=p.min_tokens,
                temperature=p.temperature,
                top_p=p.top_p,
                top_k=p.top_k if p.top_k > 0 else -1,
                stop=p.stop,
                stop_token_ids=p.stop_token_ids,
                seed=p.seed,
                logprobs=(p.top_logprobs or 1) if p.logprobs else None,
                frequency_penalty=p.frequency_penalty,
                presence_penalty=p.presence_penalty,
            )
            for p in sampling_params
        ]
        start = time.perf_counter()
        outs = self._llm.generate(list(prompts), vsp)
        wall = time.perf_counter() - start
        results = []
        for out in outs:
            comp = out.outputs[0]
            n = len(comp.token_ids)
            # per-request timings from vLLM's own RequestMetrics when
            # present (first_token_time etc.); the batch wall is only
            # the last-resort fallback so side-by-side tables compare
            # real TTFT/TPOT, not a shared wall-clock smear
            m = getattr(out, "metrics", None)
            arrival = getattr(m, "arrival_time", None)
            first = getattr(m, "first_token_time", None)
            finished = getattr(m, "finished_time", None)
            ttft = (
                first - arrival
                if first is not None and arrival is not None
                else wall
            )
            gen_time = (
                finished - arrival
                if finished is not None and arrival is not None
                else wall
            )
            results.append(
                GenerationResult(
                    text=comp.text,
                    token_ids=list(comp.token_ids),
                    num_tokens=n,
                    prompt_tokens=len(out.prompt_token_ids or ()),
                    metrics={
                        "ttft": ttft,
                        "gen_time": gen_time,
                        "tpot": (
                            (gen_time - ttft) / (n - 1)
                            if n > 1
                            else gen_time
                        ),
                    },
                    finish_reason=comp.finish_reason or "stop",
                )
            )
        return results

    def shutdown(self) -> None:
        self._llm = None
