"""Optional SGLang comparison backend.

The reference's headline benchmark tables vLLM AND SGLang side by side
(/root/reference/benchmarks/bench_compare.py:145-178); the vLLM half
landed in r3 (backends/vllm_backend.py) and this adapter completes the
pair, so ``benchmarks/bench_compare.py --engines jax_tpu vllm sglang``
reproduces the reference's full comparison matrix on a machine that has
those wheels.

SGLang is deliberately NOT a dependency — this image has no GPU and no
egress — so the import is lazy and the error explicit.  The adapter
drives ``sglang.Engine`` (the offline engine API, the analog of
``vllm.LLM``) through OUR 4-method seam with per-request sampling
params.  Select with ``model.engine_type: "sglang"``.
"""

from __future__ import annotations

import time
from typing import Any, List, Sequence

from vgate_tpu.backends.base import GenerationResult, SamplingParams
from vgate_tpu.logging_config import get_logger

logger = get_logger(__name__)


class SGLangBackend:
    """``sglang.Engine`` behind the engine seam (comparison use)."""

    def __init__(self) -> None:
        self._engine = None
        self.model_id = ""

    def load_model(self, config: Any) -> None:
        try:
            import sglang
        except ImportError as exc:  # pragma: no cover - not in image
            raise RuntimeError(
                "engine_type 'sglang' needs the sglang package (not "
                "bundled: this deployment is TPU-native; install sglang "
                "in a GPU image to benchmark side by side)"
            ) from exc
        model_cfg = getattr(config, "model", config)
        self.model_id = getattr(model_cfg, "model_id", "")
        kwargs = {}
        max_len = getattr(model_cfg, "max_model_len", None)
        if max_len:
            kwargs["context_length"] = max_len
        quant = getattr(model_cfg, "quantization", None)
        if quant:
            logger.warning(
                "sglang backend ignores quantization=%s (no mapping to "
                "an sglang scheme); it will serve the model unquantized",
                quant,
            )
        self._engine = sglang.Engine(model_path=self.model_id, **kwargs)
        logger.info(
            "sglang backend ready",
            extra={"extra_data": {"model": self.model_id}},
        )

    def create_sampling_params(self, **kwargs: Any) -> SamplingParams:
        return SamplingParams(**kwargs)

    def generate(
        self,
        prompts: Sequence[str],
        sampling_params: Sequence[SamplingParams],
    ) -> List[GenerationResult]:
        assert self._engine is not None, "load_model first"
        sgl_params = [
            {
                "max_new_tokens": p.max_tokens,
                "temperature": p.temperature,
                "top_p": p.top_p,
                "top_k": p.top_k if p.top_k > 0 else -1,
                "stop": list(p.stop) if p.stop else None,
                "stop_token_ids": (
                    list(p.stop_token_ids) if p.stop_token_ids else None
                ),
                "frequency_penalty": p.frequency_penalty,
                "presence_penalty": p.presence_penalty,
                "min_new_tokens": p.min_tokens,
            }
            for p in sampling_params
        ]
        start = time.perf_counter()
        outs = self._engine.generate(list(prompts), sgl_params)
        wall = time.perf_counter() - start
        if isinstance(outs, dict):  # single-prompt shape
            outs = [outs]
        results = []
        for out in outs:
            meta = out.get("meta_info", {})
            n = int(meta.get("completion_tokens", 0)) or len(
                out.get("output_ids", ())
            )
            # sglang reports per-request e2e/ttft latencies in meta_info
            # when available; the batch wall is the last-resort fallback
            ttft = meta.get("ttft", meta.get("first_token_latency", wall))
            gen_time = meta.get("e2e_latency", wall)
            results.append(
                GenerationResult(
                    text=out.get("text", ""),
                    token_ids=list(out.get("output_ids", ())),
                    num_tokens=n,
                    prompt_tokens=int(meta.get("prompt_tokens", 0)),
                    metrics={
                        "ttft": ttft,
                        "gen_time": gen_time,
                        "tpot": (
                            (gen_time - ttft) / (n - 1)
                            if n > 1
                            else gen_time
                        ),
                    },
                    finish_reason=(
                        (meta.get("finish_reason") or {}).get(
                            "type", "stop"
                        )
                        if isinstance(meta.get("finish_reason"), dict)
                        else (meta.get("finish_reason") or "stop")
                    ),
                )
            )
        return results

    def shutdown(self) -> None:
        if self._engine is not None:
            shutdown = getattr(self._engine, "shutdown", None)
            if shutdown is not None:
                shutdown()
        self._engine = None
