"""API-key auth + sliding-window rate limiting as aiohttp middleware.

Reproduces the reference's security layer (vgate/security.py:42-251): Bearer
token extraction, per-key sliding windows of timestamps, 401 on
missing/invalid keys, 429 with ``X-RateLimit-*`` and ``Retry-After`` headers
when over the window limit, and exempt paths that skip both checks.  The
reference is FastAPI/Starlette middleware; here it is an aiohttp
``@middleware`` since this framework's HTTP layer is aiohttp-native.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from aiohttp import web

from vgate_tpu.logging_config import get_logger
from vgate_tpu.tracing import get_tracer

logger = get_logger(__name__)
tracer = get_tracer(__name__)


def extract_api_key(request: web.Request) -> Optional[str]:
    """Pull the Bearer token from the Authorization header
    (reference: vgate/security.py:116-136)."""
    auth = request.headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        token = auth[len("Bearer "):].strip()
        return token or None
    return None


class RateLimiter:
    """Per-key sliding window over raw timestamps
    (reference: vgate/security.py:42-113).

    Windows are ``deque``s (O(1) expiry at the old end, vs the O(n)
    ``list.pop(0)`` this replaced), and keys whose window has fully
    expired are swept out once per window period — the key space is
    client-controlled (API keys / IPs), so an entry per distinct key
    forever is an unbounded-memory hole under key-rotating traffic."""

    def __init__(
        self,
        requests_per_minute: int = 60,
        per_key_limits: Optional[Dict[str, int]] = None,
        window_s: float = 60.0,
    ) -> None:
        self.default_limit = requests_per_minute
        self.per_key_limits = dict(per_key_limits or {})
        self.window_s = window_s
        self._windows: Dict[str, Deque[float]] = {}
        self._last_sweep = 0.0

    def limit_for(self, key: str) -> int:
        return self.per_key_limits.get(key, self.default_limit)

    def _sweep(self, now: float) -> None:
        """Drop keys with no timestamp inside the window.  O(total
        entries), amortized to once per window period."""
        cutoff = now - self.window_s
        for key in list(self._windows):
            window = self._windows[key]
            while window and window[0] <= cutoff:
                window.popleft()
            if not window:
                del self._windows[key]
        self._last_sweep = now

    def check(self, key: str, now: Optional[float] = None) -> Tuple[bool, Dict[str, str]]:
        """Record one request attempt.  Returns (allowed, headers)."""
        now = time.monotonic() if now is None else now
        if now - self._last_sweep >= self.window_s:
            self._sweep(now)
        window = self._windows.setdefault(key, deque())
        cutoff = now - self.window_s
        while window and window[0] <= cutoff:
            window.popleft()
        limit = self.limit_for(key)
        headers = {
            "X-RateLimit-Limit": str(limit),
            "X-RateLimit-Remaining": str(max(0, limit - len(window) - 1)),
        }
        if len(window) >= limit:
            retry_after = max(0.0, window[0] + self.window_s - now)
            headers["X-RateLimit-Remaining"] = "0"
            headers["Retry-After"] = str(int(retry_after) + 1)
            return False, headers
        window.append(now)
        return True, headers

    def get_stats(self) -> Dict[str, int]:
        return {key: len(win) for key, win in self._windows.items()}


def _error_json(status: int, message: str, err_type: str) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": err_type}}, status=status
    )


def build_security_middleware(config) -> web.middleware:
    """Factory producing the auth+ratelimit middleware for one app instance
    (reference: SecurityMiddleware at vgate/security.py:139-251)."""
    rate_limiter = RateLimiter(
        requests_per_minute=config.rate_limit.requests_per_minute,
        per_key_limits=config.rate_limit.per_key_limits,
    )
    valid_keys = set(config.security.api_keys)
    exempt = set(config.security.exempt_paths)

    @web.middleware
    async def security_middleware(request: web.Request, handler):
        if not config.security.enabled or request.path in exempt:
            return await handler(request)
        with tracer.start_as_current_span("security.check"):
            key = extract_api_key(request)
            if key is None:
                return _error_json(
                    401, "Missing API key", "authentication_error"
                )
            if valid_keys and key not in valid_keys:
                return _error_json(
                    401, "Invalid API key", "authentication_error"
                )
            # downstream consumers (admission tier mapping, per-key
            # in-flight caps) read the authenticated key from here
            request["api_key"] = key
            if config.rate_limit.enabled:
                allowed, headers = rate_limiter.check(key)
                if not allowed:
                    resp = _error_json(
                        429, "Rate limit exceeded", "rate_limit_error"
                    )
                    resp.headers.update(headers)
                    return resp
                response = await handler(request)
                response.headers.update(headers)
                return response
            return await handler(request)

    security_middleware.rate_limiter = rate_limiter  # type: ignore[attr-defined]
    return security_middleware
