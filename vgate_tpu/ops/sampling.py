"""Device-side token sampling with *per-request* parameters.

The reference applies the first request's temperature/top_p to the whole
batch (vgate/batcher.py:271 — a documented quirk); here every slot carries
its own (temperature, top_p, top_k) vector and sampling happens on device in
one fused program.

Exactness note: sampling operates on the top ``TRUNC`` logits (lax.top_k)
rather than a full-vocab sort.  Top-k is exact for k <= TRUNC; top-p is
exact whenever the top-TRUNC probability mass covers ``top_p`` (true for all
practical temperatures); both fall back to the best-available distribution
otherwise.  This keeps the per-step cost O(V + TRUNC log TRUNC) instead of a
full 150k-vocab sort per slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TRUNC = 256  # logits kept per slot for sampling
_GREEDY_EPS = 1e-4


def _masked_scaled(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
):
    """Truncate + scale + apply top-k/top-p masks.  Returns
    (raw top-trunc logits [B, trunc] sorted desc, their token ids,
    the temperature-scaled logits with ineligible entries at -1e30)."""
    B, V = logits.shape
    trunc = min(TRUNC, V)
    logits32 = logits.astype(jnp.float32)
    top_vals, top_idx = jax.lax.top_k(logits32, trunc)  # [B, trunc] sorted desc

    safe_temp = jnp.maximum(temperature, _GREEDY_EPS)[:, None]
    scaled = top_vals / safe_temp

    # top-k mask within the truncated, sorted slice
    ranks = jnp.arange(trunc)[None, :]
    k = jnp.where(top_k[:, None] > 0, top_k[:, None], trunc)
    k_mask = ranks < k

    # top-p (nucleus) mask: keep the smallest prefix whose mass >= top_p;
    # exclusive cumsum guarantees the argmax token always stays eligible.
    probs = jax.nn.softmax(scaled, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    p_mask = cum_excl < jnp.clip(top_p, 0.0, 1.0)[:, None]

    mask = k_mask & p_mask
    masked = jnp.where(mask, scaled, -1e30)
    return top_vals, top_idx, masked


def _row_keys(
    key: jax.Array,
    seeds: jnp.ndarray,  # [B] int32, -1 => unseeded
    steps: jnp.ndarray | None,  # [B] int32 per-seq sample index
    B: int,
):
    """Per-row PRNG keys: a row with ``seed >= 0`` derives from
    ``fold_in(PRNGKey(seed), step)`` — reproducible regardless of batch
    composition or engine step — else from the engine key + row index."""

    def slot_key(seed, step, slot):
        seeded = jax.random.fold_in(
            jax.random.PRNGKey(seed.astype(jnp.uint32)), step
        )
        unseeded = jax.random.fold_in(key, slot)
        return jnp.where(seed >= 0, seeded, unseeded)

    return jax.vmap(slot_key)(
        seeds,
        jnp.zeros((B,), jnp.int32) if steps is None else steps,
        jnp.arange(B, dtype=jnp.int32),
    )


def _topk_and_pos(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    key: jax.Array,
    seeds: jnp.ndarray | None,
    steps: jnp.ndarray | None,
):
    """Shared sampling core: returns (raw top-trunc logits [B, trunc]
    sorted desc, their token ids, the chosen position within them)."""
    B, V = logits.shape
    top_vals, top_idx, masked = _masked_scaled(
        logits, temperature, top_p, top_k
    )
    trunc = top_idx.shape[1]

    if seeds is None:
        gumbel = jax.random.gumbel(key, (B, trunc), dtype=jnp.float32)
    else:
        slot_keys = _row_keys(key, seeds, steps, B)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (trunc,), dtype=jnp.float32)
        )(slot_keys)
    sampled_pos = jnp.argmax(masked + gumbel, axis=-1)  # [B]

    greedy = temperature <= _GREEDY_EPS
    pos = jnp.where(greedy, 0, sampled_pos)
    return top_vals, top_idx, pos


def verify_and_sample(
    logits: jnp.ndarray,  # [R, V] processed (penalized/suppressed) logits
    draft_next: jnp.ndarray,  # [R] int32 draft token this row verifies
    is_bonus: jnp.ndarray,  # [R] bool: no draft to verify at this row
    temperature: jnp.ndarray,  # [R]
    top_p: jnp.ndarray,  # [R]
    top_k: jnp.ndarray,  # [R] int32, 0 => disabled
    key: jax.Array,
    seeds: jnp.ndarray | None = None,  # [R] int32, -1 => unseeded
    steps: jnp.ndarray | None = None,  # [R] int32 per-seq sample index
    num_top: int = 0,
    all_greedy: bool = False,  # static: every row is temperature 0
):
    """Distribution-preserving speculative verification (rejection
    sampling with a deterministic proposal).

    Each row holds the model's logits at one candidate position and the
    draft token proposed there.  With the prompt-lookup drafter the
    proposal q is a point mass at the draft t, so the standard
    accept-with-min(1, p/q), resample-from-(p-q)+ rule (Leviathan et al.;
    the scheme vLLM's rejection sampler implements on GPU) reduces to:

      * accept t with probability p(t) — p being the row's actual
        sampling distribution: temperature-scaled, top-k/top-p-masked,
        over the top-``TRUNC`` slice (the distribution ``sample_tokens``
        draws from, so the guarantee is exact w.r.t. the engine, not an
        idealized full-vocab softmax);
      * on rejection, resample from p with t excluded (the normalized
        residual max(0, p - q)).

    The emitted token is then exactly p-distributed at every position,
    whatever the drafter proposed.  Greedy rows (temperature <= eps)
    reduce to exact argmax matching — the pre-existing greedy-exact
    contract.  ``is_bonus`` rows skip verification and draw a plain
    sample (the bonus token at the end of an all-accepted run).

    Returns ``(model_toks [R] int32, accept [R] bool, lp_data)`` where
    ``lp_data`` is ``(chosen_lp [R], top_ids [R, num_top], top_lps
    [R, num_top])`` when ``num_top > 0`` else None.
    """
    R, V = logits.shape
    if all_greedy and num_top == 0:
        # one-pass argmax verification: accept iff the draft IS the
        # argmax (same semantics as the general greedy branch below)
        am = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return am, (am == draft_next) & ~is_bonus, None
    top_vals, top_idx, masked = _masked_scaled(
        logits, temperature, top_p, top_k
    )
    trunc = top_idx.shape[1]

    seeds_eff = (
        jnp.full((R,), -1, jnp.int32) if seeds is None else seeds
    )
    base_keys = _row_keys(key, seeds_eff, steps, R)
    sub = jax.vmap(lambda k: jax.random.split(k, 2))(base_keys)  # [R,2,2]
    u = jax.vmap(lambda k: jax.random.uniform(k, ()))(sub[:, 0])
    gumbel = jax.vmap(
        lambda k: jax.random.gumbel(k, (trunc,), dtype=jnp.float32)
    )(sub[:, 1])

    probs = jax.nn.softmax(masked, axis=-1)  # ineligible entries ~0
    is_draft = top_idx == draft_next[:, None]  # [R, trunc]
    p_draft = jnp.sum(jnp.where(is_draft, probs, 0.0), axis=-1)
    greedy = temperature <= _GREEDY_EPS
    accept = (
        jnp.where(greedy, top_idx[:, 0] == draft_next, u < p_draft)
        & ~is_bonus
    )

    # One gumbel draw serves both the rejection-resample (draft token
    # excluded — argmax-gumbel over the residual support renormalizes
    # implicitly) and the plain bonus sample (no exclusion): the two are
    # mutually exclusive per row.  A rejected row always has other
    # eligible entries: p_draft == 1 makes rejection impossible
    # (u ~ U[0,1) < 1).
    exclude = is_draft & ~is_bonus[:, None]
    pos_rs = jnp.argmax(
        jnp.where(exclude, -jnp.inf, masked) + gumbel, axis=-1
    )
    pos_draft = jnp.argmax(is_draft, axis=-1)
    pos = jnp.where(greedy, 0, jnp.where(accept, pos_draft, pos_rs))
    model_toks = jnp.take_along_axis(
        top_idx, pos[:, None], axis=-1
    )[:, 0].astype(jnp.int32)

    if num_top > 0:
        lse = jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1, keepdims=True
        )
        lps = top_vals - lse
        chosen_lp = jnp.take_along_axis(lps, pos[:, None], axis=-1)[:, 0]
        return model_toks, accept, (
            chosen_lp,
            top_idx[:, :num_top].astype(jnp.int32),
            lps[:, :num_top],
        )
    return model_toks, accept, None


def sample_tokens(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    key: jax.Array,
    seeds: jnp.ndarray | None = None,  # [B] int32, -1 => unseeded
    steps: jnp.ndarray | None = None,  # [B] int32 per-seq sample index
    all_greedy: bool = False,  # static: every row is temperature 0
) -> jnp.ndarray:
    """Sample one token per slot honoring per-slot params. Returns [B] int32.

    When ``seeds``/``steps`` are given, a slot with ``seed >= 0`` draws its
    gumbel noise from ``fold_in(PRNGKey(seed), step)`` — a function of the
    request's seed and its per-sequence token index only, so the same seed
    reproduces the same tokens regardless of batch composition, engine step
    count, or preemption (the reference exposes vLLM's per-request ``seed``,
    vgate/backends/vllm_backend.py:39-46).  Unseeded slots fold the slot
    index into the engine's step key.  ``key`` must be a legacy uint32[2]
    key (``jax.random.PRNGKey``) so keys can be selected with ``where``.

    ``all_greedy`` (a STATIC flag the engine sets when every active
    request has temperature 0) takes a one-pass argmax instead of the
    top-``TRUNC`` ``lax.top_k`` — on TPU the top-k over a ~150k vocab
    lowers to an expensive sort, pure waste when nothing samples.
    """
    if all_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    _top_vals, top_idx, pos = _topk_and_pos(
        logits, temperature, top_p, top_k, key, seeds, steps
    )
    return jnp.take_along_axis(top_idx, pos[:, None], axis=-1)[:, 0].astype(
        jnp.int32
    )


def sample_tokens_with_logprobs(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    key: jax.Array,
    seeds: jnp.ndarray | None = None,
    steps: jnp.ndarray | None = None,
    num_top: int = 8,
):
    """``sample_tokens`` plus OpenAI-style logprobs.

    Returns ``(tokens [B], chosen_lp [B], top_ids [B, num_top],
    top_lps [B, num_top])`` where logprobs are log-softmax of the RAW
    logits (temperature/top-k/top-p modify only the sampling draw, not
    the reported distribution — the standard API convention).  The
    full-vocab logsumexp is the only extra work over plain sampling.
    """
    top_vals, top_idx, pos = _topk_and_pos(
        logits, temperature, top_p, top_k, key, seeds, steps
    )
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1, keepdims=True
    )
    lps = top_vals - lse  # [B, trunc] raw-logit log-softmax, sorted desc
    tokens = jnp.take_along_axis(
        top_idx, pos[:, None], axis=-1
    )[:, 0].astype(jnp.int32)
    chosen_lp = jnp.take_along_axis(lps, pos[:, None], axis=-1)[:, 0]
    return (
        tokens,
        chosen_lp,
        top_idx[:, :num_top].astype(jnp.int32),
        lps[:, :num_top],
    )


def apply_penalties(
    logits: jnp.ndarray,  # [B, V]
    counts: jnp.ndarray,  # [B, V] per-slot output-token counts (uint16/int32)
    frequency_penalty: jnp.ndarray,  # [B]
    presence_penalty: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """OpenAI frequency/presence penalties over the full vocabulary.

    ``logits[b, v] -= freq[b] * counts[b, v] + pres[b] * (counts[b, v] > 0)``
    — counts cover the tokens the request has GENERATED so far (not the
    prompt), matching the OpenAI definition.  Applied before temperature/
    top-k/top-p; when a request also asks for logprobs they are computed
    from these penalized logits (the distribution actually sampled).
    """
    c = counts.astype(jnp.float32)
    return (
        logits.astype(jnp.float32)
        - frequency_penalty[:, None] * c
        - presence_penalty[:, None] * (c > 0).astype(jnp.float32)
    )


def apply_logit_bias(
    logits: jnp.ndarray,  # [B, V]
    bias_ids: jnp.ndarray,  # [B, K] int32 token ids; >= V entries pad
    bias_vals: jnp.ndarray,  # [B, K] f32 additive biases
) -> jnp.ndarray:
    """OpenAI ``logit_bias``: add per-request biases to selected token
    logits before sampling (-100 effectively bans a token, +100
    effectively forces it).  Padding entries use an out-of-vocab id —
    XLA scatter-add drops out-of-bounds updates, so they are no-ops by
    construction (the same trick as suppress_stop_tokens)."""
    B = logits.shape[0]
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], bias_ids.shape)
    return logits.astype(jnp.float32).at[b_idx, bias_ids].add(
        bias_vals, mode="drop"
    )


def suppress_stop_tokens(
    logits: jnp.ndarray,  # [B, V]
    steps: jnp.ndarray,  # [B] tokens generated so far
    min_tokens: jnp.ndarray,  # [B] per-slot floor (0 = off)
    stop_ids: jnp.ndarray,  # [B, K] int32 stop ids; >= V entries are padding
) -> jnp.ndarray:
    """min_tokens: slots below their floor cannot sample a stop token.

    Padding entries use an out-of-vocab id — XLA scatter drops
    out-of-bounds updates, so they are no-ops by construction.
    """
    B = logits.shape[0]
    suppress = (steps < min_tokens)[:, None]  # [B, 1]
    b_idx = jnp.broadcast_to(
        jnp.arange(B)[:, None], stop_ids.shape
    )
    masked = logits.at[b_idx, stop_ids].set(
        -1e30, mode="drop"
    )
    return jnp.where(suppress, masked, logits)
