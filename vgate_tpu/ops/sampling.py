"""Device-side token sampling with *per-request* parameters.

The reference applies the first request's temperature/top_p to the whole
batch (vgate/batcher.py:271 — a documented quirk); here every slot carries
its own (temperature, top_p, top_k) vector and sampling happens on device in
one fused program.

Exactness note: sampling operates on the top ``TRUNC`` logits (lax.top_k)
rather than a full-vocab sort.  Top-k is exact for k <= TRUNC; top-p is
exact whenever the top-TRUNC probability mass covers ``top_p`` (true for all
practical temperatures); both fall back to the best-available distribution
otherwise.  This keeps the per-step cost O(V + TRUNC log TRUNC) instead of a
full 150k-vocab sort per slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TRUNC = 256  # logits kept per slot for sampling
_GREEDY_EPS = 1e-4


def sample_tokens(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    key: jax.Array,
) -> jnp.ndarray:
    """Sample one token per slot honoring per-slot params. Returns [B] int32."""
    B, V = logits.shape
    trunc = min(TRUNC, V)
    logits32 = logits.astype(jnp.float32)
    top_vals, top_idx = jax.lax.top_k(logits32, trunc)  # [B, trunc] sorted desc

    safe_temp = jnp.maximum(temperature, _GREEDY_EPS)[:, None]
    scaled = top_vals / safe_temp

    # top-k mask within the truncated, sorted slice
    ranks = jnp.arange(trunc)[None, :]
    k = jnp.where(top_k[:, None] > 0, top_k[:, None], trunc)
    k_mask = ranks < k

    # top-p (nucleus) mask: keep the smallest prefix whose mass >= top_p;
    # exclusive cumsum guarantees the argmax token always stays eligible.
    probs = jax.nn.softmax(scaled, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    p_mask = cum_excl < jnp.clip(top_p, 0.0, 1.0)[:, None]

    mask = k_mask & p_mask
    masked = jnp.where(mask, scaled, -1e30)

    gumbel = jax.random.gumbel(key, (B, trunc), dtype=jnp.float32)
    sampled_pos = jnp.argmax(masked + gumbel, axis=-1)  # [B]

    greedy = temperature <= _GREEDY_EPS
    pos = jnp.where(greedy, 0, sampled_pos)
    return jnp.take_along_axis(top_idx, pos[:, None], axis=-1)[:, 0].astype(
        jnp.int32
    )
