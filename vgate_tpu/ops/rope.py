"""Rotary position embeddings (rotate-half formulation, matching the
HF Qwen2/Llama convention so torch parity tests line up exactly),
including the Llama-3.1 long-context frequency scaling."""

from __future__ import annotations

import math

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, theta: float = 10000.0, scaling=None
) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], fp32.

    ``scaling`` (optional) is the Llama-3.1 rule as a tuple
    ``(factor, low_freq_factor, high_freq_factor, original_max_pos)``:
    low-frequency components (wavelength beyond the original context)
    are slowed by ``factor``, high-frequency ones kept, and the band in
    between interpolated — the published recipe that stretches a model
    trained at ``original_max_pos`` to ``factor``x the context.
    """
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta ** exponent)
    if scaling is None:
        return inv_freq
    factor, low_f, high_f, orig_max = scaling
    low_wavelen = orig_max / low_f
    high_wavelen = orig_max / high_f
    wavelen = 2.0 * math.pi / inv_freq
    smooth = (orig_max / wavelen - low_f) / (high_f - low_f)
    mid = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    return jnp.where(
        wavelen > low_wavelen,
        inv_freq / factor,
        jnp.where(wavelen < high_wavelen, inv_freq, mid),
    )


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    theta: float = 10000.0,
    scaling=None,
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by per-token angles.

    ``positions`` has shape broadcastable to x.shape[:-2] (i.e. [..., seq]).
    Computed in fp32, returned in the input dtype.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., S, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., S, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    # rotate_half: (x1, x2) -> (x1*cos - x2*sin, x2*cos + x1*sin)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
