"""Rotary position embeddings (rotate-half formulation, matching the
HF Qwen2/Llama convention so torch parity tests line up exactly)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], fp32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponent)


def apply_rope(
    x: jnp.ndarray, positions: jnp.ndarray, theta: float = 10000.0
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by per-token angles.

    ``positions`` has shape broadcastable to x.shape[:-2] (i.e. [..., seq]).
    Computed in fp32, returned in the input dtype.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., S, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., S, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    # rotate_half: (x1, x2) -> (x1*cos - x2*sin, x2*cos + x1*sin)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
