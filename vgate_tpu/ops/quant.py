"""Weight-only int8 / int4 quantization.

The TPU-native counterpart of the AWQ 4-bit quantization the reference
passes through to vLLM (vgate/config.py:46, vllm_backend.py:32 — opaque
there).  Symmetric per-output-channel narrow-int: weights store as
``QTensor(q=int8|int4, scale=f32[out])`` and dequantize inside the matmul's
consumer (XLA fuses the narrow-int→bf16 convert + scale into the
surrounding computation), cutting weight HBM traffic 2x (int8) or 4x
(int4, packed two-per-byte on TPU) — the resource that bounds decode.

Every weight in the decoder layout keeps its output dim LAST, so one
broadcast rule covers q/k/v/o/gate/up/down and lm_head.  MoE expert weights
[L, E, in, out] quantize per (layer, expert, out-channel) and dequantize
inside the per-expert GEMMs (models/decoder.py _expert_einsum); the router
stays fp32 (it is tiny and drives top-k selection).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """narrow-int values + per-output-channel scale (output dim is last)."""

    q: jnp.ndarray  # int8, same shape as the original weight
    scale: jnp.ndarray  # f32, shape = original.shape[-1:] (or [L, out])


class PackedQTensor(NamedTuple):
    """int4 weights stored two-per-byte (uint8) along the contracted dim.

    jnp.int4 (``S4``) arrays cannot cross a jit boundary on the TPU runtime
    (device_put relayout recurses), and packed bytes are the honest 4-bit
    representation anyway — the same layout AWQ uses on GPU.  ``q_packed``
    has the original shape with dim -2 (the ``in`` dim) halved, in a
    **half-split** layout: byte ``p[..., i, out]`` holds
    ``w[..., i, out]`` in its low nibble and ``w[..., i + in/2, out]`` in
    its high nibble, two's-complement.  Half-split (not interleaved) so
    the consumer can contract each nibble plane directly against the
    matching half of the activations — no interleaving reshape, and the
    unpacked weight never materializes (see ``packed_einsum``).
    """

    q_packed: jnp.ndarray  # uint8 [..., in/2, out]
    scale: jnp.ndarray  # f32 [..., out]


_QDTYPES = {8: (jnp.int8, 127), 4: (jnp.int8, 7)}


Weight = Union[jnp.ndarray, QTensor, PackedQTensor]

def _use_quant_kernel(subscripts: str, w: Weight) -> bool:
    """Shape eligibility for the fused dequant kernels
    (ops/pallas/quant_matmul.py): 2D per-layer weights (packed int4 or
    int8) in a plain [..., in] @ [in, out] contraction ("...d,dh->...h"
    etc.).  Stacked/expert weights and exotic einsums keep the jnp path.
    Whether a kernel actually runs is the caller's ``quant_kernel`` flag
    (threaded per-engine via ModelSpec.quant_kernel — the engine enables
    it only on TPU with no model-parallel axes, since pallas_call does
    not auto-partition under jit sharding)."""
    vals = w.q_packed if isinstance(w, PackedQTensor) else w.q
    if vals.ndim != 2:
        return False
    ins, out = subscripts.split("->")
    a, b = ins.split(",")
    if not (a.startswith("...") and len(a) == 4 and len(b) == 2):
        return False
    return a[3] == b[0] and out == "..." + b[1]


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """int8 values in [-7, 7], shape [..., in, out] -> uint8 [..., in/2, out]
    (half-split layout: low nibbles = first half of ``in``, high = second)."""
    if q.shape[-2] % 2:
        raise ValueError(f"in-dim {q.shape[-2]} must be even to pack int4")
    half = q.shape[-2] // 2
    lo = q[..., :half, :].astype(jnp.uint8) & jnp.uint8(0x0F)
    hi = q[..., half:, :].astype(jnp.uint8) & jnp.uint8(0x0F)
    return lo | (hi << jnp.uint8(4))


def _sext4(nibble: jnp.ndarray) -> jnp.ndarray:
    """two's-complement 4-bit -> int8."""
    return (nibble.astype(jnp.int8) ^ jnp.int8(8)) - jnp.int8(8)


def _nibble_planes(p: jnp.ndarray):
    """Half-split packed bytes -> sign-extended int8 ``(lo, hi)`` planes
    (the single home of the layout invariant shared by ``unpack_int4``,
    ``packed_einsum`` and ``int8_native_einsum``)."""
    return _sext4(p & jnp.uint8(0x0F)), _sext4(p >> jnp.uint8(4))


def unpack_int4(p: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., in/2, out] -> sign-extended int8 [..., in, out]."""
    lo, hi = _nibble_planes(p)
    return jnp.concatenate([lo, hi], axis=-2)


def packed_einsum(
    subscripts: str, x: jnp.ndarray, w: "PackedQTensor",
    preferred_element_type=None,
) -> jnp.ndarray:
    """einsum against packed int4 without materializing the unpacked weight.

    Every decoder einsum contracts x's LAST axis against w's dim -2, so the
    half-split layout lets each nibble plane multiply the matching half of
    the activations: two half-size MXU GEMMs whose narrow-int -> bf16
    converts fuse into the operand feed, with no interleave reshape and no
    full-size int8 weight tensor in flight.  Output scale is NOT applied
    (callers broadcast ``w.scale`` themselves — its shape differs between
    dense and expert weights)."""
    half = w.q_packed.shape[-2]
    lo, hi = _nibble_planes(w.q_packed)
    lo, hi = lo.astype(x.dtype), hi.astype(x.dtype)
    kw = (
        {}
        if preferred_element_type is None
        else {"preferred_element_type": preferred_element_type}
    )
    return jnp.einsum(subscripts, x[..., :half], lo, **kw) + jnp.einsum(
        subscripts, x[..., half:], hi, **kw
    )


def _quantize_activations(x: jnp.ndarray):
    """Dynamic symmetric per-token int8 quantization of activations:
    per-row absmax over the contracted (last) axis.  Returns
    ``(x_q int8, x_scale f32[..., 1])``."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    x_scale = jnp.maximum(absmax, 1e-8) / 127.0
    x_q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / x_scale), -127, 127
    ).astype(jnp.int8)
    return x_q, x_scale


def int8_native_partial(
    subscripts: str, x: jnp.ndarray, w: Weight
) -> jnp.ndarray:
    """W8A8 contraction WITHOUT the weight scale: dynamically quantize
    activations per-token and contract int8 x int8 with int32
    accumulation — XLA lowers this to the MXU's native s8 x s8 -> s32
    path on v5e-class TPUs (2x bf16 matmul throughput), with no
    dequantized weight plane ever materializing.

    Works for QTensor (one int8 GEMM) and PackedQTensor (W4A8: the two
    sign-extended nibble planes stay int8 and each contracts the
    matching activation half — two native GEMMs, packed bytes in HBM).
    Returns ``(x @ w) * x_scale`` in f32; the CALLER applies ``w.scale``
    (its broadcast shape differs between dense [out] and expert
    [E, out] weights — the same split as ``packed_einsum``).
    """
    x_q, x_scale = _quantize_activations(x)
    if isinstance(w, PackedQTensor):
        half = w.q_packed.shape[-2]
        lo, hi = _nibble_planes(w.q_packed)
        acc = jnp.einsum(
            subscripts, x_q[..., :half], lo,
            preferred_element_type=jnp.int32,
        ) + jnp.einsum(
            subscripts, x_q[..., half:], hi,
            preferred_element_type=jnp.int32,
        )
    else:
        acc = jnp.einsum(
            subscripts, x_q, w.q, preferred_element_type=jnp.int32
        )
    return acc.astype(jnp.float32) * x_scale


def int8_native_einsum(
    subscripts: str, x: jnp.ndarray, w: Weight, out_dtype,
) -> jnp.ndarray:
    """Dense-weight W8A8/W4A8: ``int8_native_partial`` with the
    per-output-channel scale applied — the TPU-native answer to the
    fused AWQ dequant-GEMM the reference gets through vLLM's CUDA
    kernels (vgate/config.py:46): weight HBM traffic is the narrow-int
    bytes AND the MACs run at int8 rate."""
    out = int8_native_partial(subscripts, x, w) * w.scale
    return out.astype(out_dtype)


def _finish(q: jnp.ndarray, scale: jnp.ndarray, bits: int) -> Weight:
    if bits == 4:
        return PackedQTensor(q_packed=pack_int4(q), scale=scale)
    return QTensor(q=q, scale=scale)


def quantize_tensor(w: jnp.ndarray, bits: int = 8) -> Weight:
    """Symmetric per-channel int8/int4 over the last (output) dim."""
    dtype, qmax = _QDTYPES[bits]
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=tuple(range(w.ndim - 1)))
    scale = jnp.maximum(absmax, 1e-8) / qmax
    q = jnp.clip(jnp.round(w32 / scale), -qmax, qmax).astype(dtype)
    return _finish(q, scale, bits)


def quantize_stacked(w: jnp.ndarray, bits: int = 8) -> Weight:
    """Quantize a stacked-layer weight [L, ..., out]: per (layer, channel)."""
    dtype, qmax = _QDTYPES[bits]
    w32 = w.astype(jnp.float32)
    reduce_axes = tuple(range(1, w.ndim - 1))
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes)  # [L, out]
    scale = jnp.maximum(absmax, 1e-8) / qmax
    q = jnp.clip(
        jnp.round(w32 / scale[(slice(None),) + (None,) * (w.ndim - 2)]),
        -qmax,
        qmax,
    ).astype(dtype)
    return _finish(q, scale, bits)


def quantize_expert_stacked(w: jnp.ndarray, bits: int = 8) -> Weight:
    """Quantize stacked MoE expert weights [L, E, in, out]: the scale is per
    (layer, expert, out-channel) — reducing only the contracted ``in`` dim —
    so each expert keeps its own dynamic range."""
    dtype, qmax = _QDTYPES[bits]
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2)  # [L, E, out]
    scale = jnp.maximum(absmax, 1e-8) / qmax
    q = jnp.clip(
        jnp.round(w32 / scale[..., None, :]), -qmax, qmax
    ).astype(dtype)
    return _finish(q, scale, bits)


def weighted_einsum(
    subscripts: str, x: jnp.ndarray, w: Weight, preferred_element_type=None,
    quant_kernel: bool = False, int8_native: bool = False,
) -> jnp.ndarray:
    """einsum that accepts plain or quantized weights.

    For QTensor the int8 values enter the einsum cast to the activation
    dtype and the per-channel scale multiplies the output's last dim —
    valid because every decoder weight keeps out-dim last.  PackedQTensor
    int4 nibbles unpack in-consumer (XLA fuses the byte ops into the
    convert; only the packed bytes ever sit in HBM).
    ``preferred_element_type`` sets the accumulation/output dtype across
    all three branches (the lm_head path accumulates logits in fp32).
    ``int8_native`` (W8A8/W4A8, tpu.int8_native): dynamic per-token
    activation quantization feeding the MXU's native s8 x s8 -> s32 —
    takes precedence over ``quant_kernel`` for eligible contractions.
    """
    kw = (
        {}
        if preferred_element_type is None
        else {"preferred_element_type": preferred_element_type}
    )
    out_dtype = preferred_element_type or x.dtype
    if (
        int8_native
        and isinstance(w, (QTensor, PackedQTensor))
        and _use_quant_kernel(subscripts, w)
    ):
        return int8_native_einsum(subscripts, x, w, out_dtype)
    if isinstance(w, PackedQTensor):
        if quant_kernel and _use_quant_kernel(subscripts, w):
            from vgate_tpu.ops.pallas.quant_matmul import (
                int4_matmul_pallas,
            )

            return int4_matmul_pallas(
                x, w.q_packed, w.scale, out_dtype=out_dtype
            )
        out = packed_einsum(
            subscripts, x, w, preferred_element_type=preferred_element_type
        )
        return out * w.scale.astype(out_dtype)
    if isinstance(w, QTensor):
        if quant_kernel and _use_quant_kernel(subscripts, w):
            from vgate_tpu.ops.pallas.quant_matmul import (
                int8_matmul_pallas,
            )

            return int8_matmul_pallas(
                x, w.q, w.scale, out_dtype=out_dtype
            )
        out = jnp.einsum(subscripts, x, w.q.astype(x.dtype), **kw)
        return out * w.scale.astype(out_dtype)
    return jnp.einsum(subscripts, x, w, **kw)


def quantize_decoder_params(params: Any, spec, bits: int = 8) -> Any:
    """Quantize the projection weights of a loaded (possibly sharded) param
    pytree in place of their bf16 versions.  Dense models quantize all seven
    projections; MoE models quantize q/k/v/o per-channel and gate/up/down
    per (expert, channel), leaving the tiny fp32 router exact."""
    out = {
        "embed": params["embed"],  # gathers stay high-precision
        "final_norm": params["final_norm"],
    }
    layers = dict(params["layers"])
    for name in ("q", "k", "v", "o"):
        entry = dict(layers[name])
        entry["w"] = quantize_stacked(layers[name]["w"], bits)
        layers[name] = entry
    expert_quant = quantize_expert_stacked if spec.is_moe else quantize_stacked
    for name in ("gate", "up", "down"):
        entry = dict(layers[name])
        entry["w"] = expert_quant(layers[name]["w"], bits)
        layers[name] = entry
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = quantize_tensor(params["lm_head"], bits)
    return out
