"""Normalization layers."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-6,
    unit_offset: bool = False,
) -> jnp.ndarray:
    """RMSNorm in fp32 accumulation, cast back to input dtype (the HF Qwen2
    convention, so logits match the reference architecture bit-for-bit-ish).

    ``unit_offset`` selects the Gemma convention where the stored weight is
    a delta around 1 (output scaled by ``1 + w``)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    w32 = weight.astype(jnp.float32)
    if unit_offset:
        w32 = w32 + 1.0
    return (normed * w32).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    """Classic LayerNorm (BERT-family encoders, e.g. bge embeddings)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    normed = (x32 - mean) * (var + eps) ** -0.5
    out = normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(dtype)
